"""GSPMD sharded training — the TPU-native capability layer that subsumes the
reference's distributed machinery (``DataParallelExecutorGroup`` +
kvstore reduce + ``PlaceDevice`` model parallelism; reference
``python/mxnet/module/executor_group.py:77``, ``src/kvstore/comm.h:211``,
``src/executor/graph_executor.cc:318``) and extends it to the parallelism
modes the reference lacks (tensor/sequence/expert — SURVEY.md §2.4).

One fused jitted step = forward + backward + optimizer update, with every
array carrying a ``NamedSharding`` over a ``jax.sharding.Mesh``.  XLA inserts
the collectives (psum over the ``data`` axis for gradients — the kvstore
all-reduce; all-gather/reduce-scatter along ``model`` for sharded weights)
and schedules them to overlap with compute on ICI — the role the reference's
per-layer ``priority=-index`` push/pull scheduling plays by hand
(``model.py:94-110``).
"""

from __future__ import annotations

import contextlib as _contextlib
import time as _time

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as _np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError
from ..observability import attribution as _attr
from ..observability import efficiency as _eff
from ..observability import memory as _mem
from ..observability import metrics as _metrics

__all__ = ["ShardedTrainer", "auto_tp_specs", "zero_extend_spec"]

# -- compile accounting: every jit cache miss (step / grad / fwd / each
# (n, unroll) pipeline trace) is one entry here.  Steady state records
# NOTHING — a counter that moves outside warmup IS the recompile bug the
# cache keys exist to prevent (changed pipeline depth, epoch-tail flush,
# resharded input), and the histogram says what each miss cost.
_M_COMPILES = _metrics.counter(
    "trainer_compiles_total",
    "Jit-cache misses (traces compiled), by cache key; steady-state "
    "training must not move this", ["cache"])
_M_COMPILE_T = _metrics.histogram(
    "trainer_compile_seconds",
    "Wall time of each first-call trace+compile, by cache key", ["cache"])
_M_STREAM_STALLS = _metrics.counter(
    "stream_stalls_total",
    "Stream-source stall timeouts surfaced to fit_stream; each is one "
    "bounded-retry episode, never a silent hang (watchdog rule "
    "stream_stall fires on a sustained run of them)")
_M_STREAM_SKIPPED = _metrics.counter(
    "stream_skipped_total",
    "Chunks abandoned by fit_stream's skip-and-count degraded mode "
    "after a typed corrupt-stream error")


def auto_tp_specs(symbol, arg_shapes, mesh, data_axis="data", model_axis="model"):
    """Heuristic tensor-parallel sharding specs for a symbol's parameters.

    Megatron-style: FullyConnected / Convolution output channels shard along
    ``model_axis`` when divisible by its size; everything else replicates.
    (The reference has no TP at all — this is capability-gap item §2.4.)
    """
    if model_axis not in mesh.axis_names:
        return {}
    msize = mesh.shape[model_axis]
    specs = {}
    for name, shape in arg_shapes.items():
        if name.endswith("_weight") and len(shape) >= 2 and shape[0] % msize == 0:
            specs[name] = P(model_axis, *([None] * (len(shape) - 1)))
        elif name.endswith("_bias") and len(shape) == 1 and shape[0] % msize == 0:
            specs[name] = P(model_axis)
    return specs


def zero_extend_spec(spec, shape, mesh, data_axis="data"):
    """Extend a parameter's PartitionSpec with the ``data`` axis on the first
    unsharded, divisible dimension — the ZeRO sharding rule.

    The reference shards optimizer state across parameter-server processes by
    key (``src/kvstore/kvstore_dist_server.h:136-205`` applies the optimizer on
    each server's shard); on a TPU mesh the same idea is a sharding
    annotation: optimizer state (and, for ZeRO-3/FSDP, the weights) live
    sliced along ``data`` and XLA inserts the reduce-scatter/all-gather.
    Returns ``spec`` unchanged when no dimension divides the axis size.
    """
    if data_axis not in mesh.axis_names:
        return spec
    dsize = mesh.shape[data_axis]
    entries = list(spec) + [None] * (len(shape) - len(spec))
    used = [ax for e in entries if e is not None
            for ax in (e if isinstance(e, tuple) else (e,))]
    if data_axis in used:  # caller already shards this param over data
        return spec
    for i, (e, s) in enumerate(zip(entries, shape)):
        if e is None and s > 0 and s % dsize == 0:
            entries[i] = data_axis
            while entries and entries[-1] is None:
                entries.pop()
            return P(*entries)
    return spec


_STEP_COUNT = "__num_update__"  # reserved key in the optimizer-state tree


def resolve_update_op(optimizer, optimizer_params, momentum, learning_rate,
                      wd, rescale_grad, clip_gradient):
    """Resolve an optimizer name to ``(update_op, attrs, n_states, needs_t)``
    over the registered fused-update ops (reference
    ``src/operator/optimizer_op.cc``) — shared by ShardedTrainer and
    PipelinedTrainer so there is ONE spelling of the optimizer contract."""
    from ..ops.registry import get_op

    opt_name = (optimizer or "sgd").lower()
    opt_kwargs = dict(optimizer_params or {})
    if opt_name == "sgd":
        # momentum may arrive via the historical kwarg or (MXNet-parity)
        # optimizer_params; both at once must agree
        if ("momentum" in opt_kwargs and momentum
                and opt_kwargs["momentum"] != momentum):
            raise MXNetError(
                "momentum given twice (momentum=%r, optimizer_params"
                "['momentum']=%r)" % (momentum, opt_kwargs["momentum"]))
        eff_mom = opt_kwargs.pop("momentum", momentum)
        op_name = "sgd_mom_update" if eff_mom else "sgd_update"
        if eff_mom:
            opt_kwargs["momentum"] = eff_mom
    else:
        if momentum:
            raise MXNetError(
                "momentum= is an SGD knob; pass optimizer_params for %r"
                % opt_name)
        op_name = (opt_name if opt_name.endswith("_update")
                   else opt_name + "_update")
    try:
        update_op = get_op(op_name)
    except Exception:
        raise MXNetError(
            "no fused update op %r for optimizer %r" % (op_name, opt_name))
    static = {"lr": learning_rate, "wd": wd, "rescale_grad": rescale_grad,
              "clip_gradient": (clip_gradient if clip_gradient is not None
                                else -1.0)}
    static.update(opt_kwargs)
    attrs = update_op.parse_attrs(static)
    n_states = update_op.n_outputs(attrs) - 1
    return update_op, attrs, n_states, "t" in update_op.params


def sgd_mom_tree_stock(attrs, params, grads, moms, ok=None):
    """Stock whole-tree momentum step: one ``sgd_mom_update`` per
    parameter, then (when ``ok`` is given) the ``skip_nonfinite`` guard
    as separate keep-old passes over each subtree — the per-parameter
    dispatch shape the reference updater (``model.py _update_params``)
    and the trainer's generic loop both spell.  Returns
    ``(new_params, new_moms)`` dicts over the same keys."""
    from ..ops.tensor import _sgd_mom_update

    new_p, new_m = {}, {}
    for n in params:
        new_p[n], new_m[n] = _sgd_mom_update(attrs, params[n], grads[n],
                                             moms[n])
    if ok is not None:
        keep = jax.tree_util.tree_map
        new_p = keep(lambda a, b: jnp.where(ok, a, b), new_p,
                     dict(params))
        new_m = keep(lambda a, b: jnp.where(ok, a, b), new_m,
                     dict(moms))
    return new_p, new_m


def fused_sgd_mom_tree(attrs, params, grads, moms, ok=None):
    """Fused whole-tree momentum step (ISSUE 19 hot path b): rescale +
    clip + weight decay + momentum + the ``skip_nonfinite`` select, all
    folded into ONE pass per leaf, one jitted dispatch for the whole
    parameter tree — no per-parameter op dispatches and no post-update
    guard round trips over the tree.  Registered as the
    ``sgd_mom_tree_update``/``fused`` variant
    (``ops/fused/optimizer_kernels.py``); bitwise-equal to
    :func:`sgd_mom_tree_stock` (the parity harness holds it to byte
    equality, and the trainer reaches it only through the dispatch
    seam, so ``MXNET_TPU_OPS_FUSED=0`` restores the stock spelling)."""
    lr, wd = attrs["lr"], attrs["wd"]
    mu, rescale = attrs["momentum"], attrs["rescale_grad"]
    clip = attrs.get("clip_gradient")

    def leaf(w, g, m):
        g = g * rescale
        if clip is not None and clip > 0:
            g = jnp.clip(g, -clip, clip)
        new_m = mu * m - lr * (g + wd * w)
        new_w = w + new_m
        if ok is not None:
            new_w = jnp.where(ok, new_w, w)
            new_m = jnp.where(ok, new_m, m)
        return new_w, new_m

    out = {n: leaf(params[n], grads[n], moms[n]) for n in params}
    return ({n: wm[0] for n, wm in out.items()},
            {n: wm[1] for n, wm in out.items()})


def resolve_lr_fn(lr_scheduler, learning_rate):
    """Resolve a scheduler to a traced ``num_update -> lr`` callable (or
    None), validating at construction time rather than first trace.

    Matching the reference optimizer contract (``optimizer.py`` sets
    ``lr_scheduler.base_lr = optimizer.learning_rate``), the scheduler
    object is retargeted **in place** to this trainer's ``learning_rate``.
    Consequence: one scheduler instance cannot be shared between trainers
    with different learning rates — the last-constructed trainer wins.
    Pass separate scheduler instances (or a plain ``callable(num_update)``,
    which is never mutated) when rates differ."""
    if lr_scheduler is None:
        return None
    from ..lr_scheduler import LRScheduler

    if isinstance(lr_scheduler, LRScheduler):
        lr_scheduler.base_lr = learning_rate
        # fail at construction, not first trace: the subclass must provide
        # the jnp form next to its host __call__
        if type(lr_scheduler).traced is LRScheduler.traced:
            raise MXNetError(
                "%s has no traced() form for in-step evaluation"
                % type(lr_scheduler).__name__)
        return lr_scheduler.traced
    if callable(lr_scheduler):
        return lr_scheduler  # jnp map of the traced counter
    raise MXNetError("lr_scheduler must be an LRScheduler or a "
                     "callable(num_update) -> lr")




class ShardedTrainer:
    """A whole-model sharded training step over a device mesh.

    Parameters
    ----------
    symbol : Symbol
        Loss-headed symbol (e.g. ``SoftmaxOutput`` net).
    mesh : jax.sharding.Mesh
        Logical device mesh; conventional axes: ``data`` (DP), ``model`` (TP),
        ``seq`` (SP), ``expert`` (EP), ``pipe`` (PP).
    data_shapes : dict name -> global shape for data inputs.
    data_specs : dict name -> PartitionSpec for data inputs (default: batch
        axis over ``data``, and — when a ``seq`` axis exists in the mesh —
        axis 1 over ``seq`` for rank>=2 integer/sequence inputs).
    param_specs : dict name -> PartitionSpec (default: auto_tp_specs).

    Output-shape contract under ``grad_accum=k``: batched outputs (rank>=1
    per microbatch) merge back row-major to the full-batch shape; rank-0
    scalar heads are AVERAGED across the k microbatches so shapes (not
    dtypes — integer scalars promote to float) are invariant to k.  The
    average equals the full-batch value for mean-normalized losses over
    the equal row-major split; a sum-normalized scalar head reads k times
    smaller — fold the factor into ``grad_scale``/``rescale_grad`` or
    normalize per-row if the logged magnitude matters.
    """

    def __init__(self, symbol, mesh: Mesh, data_shapes: Dict[str, tuple],
                 label_shapes: Optional[Dict[str, tuple]] = None,
                 data_specs: Optional[Dict[str, P]] = None,
                 param_specs: Optional[Dict[str, P]] = None,
                 type_dict: Optional[Dict[str, str]] = None,
                 learning_rate=0.01, momentum=0.0, wd=0.0,
                 rescale_grad=1.0, clip_gradient=None,
                 data_axis="data", dtype="float32",
                 remat=False, remat_policy=None, zero_stage=0,
                 optimizer="sgd", optimizer_params=None, lr_scheduler=None,
                 grad_accum=1, multi_precision=False, skip_nonfinite=False,
                 pipeline_steps=1):
        from ..executor import _graph_fn
        from ..symbol import _infer

        from . import default_mesh

        self.symbol = symbol
        self.mesh = mesh
        self.data_axis = data_axis
        label_shapes = label_shapes or {}
        type_dict = dict(type_dict or {})
        # gradient accumulation: the declared shapes stay the GLOBAL batch;
        # the graph traces at the microbatch (dim0 / grad_accum), the step
        # lax.scans the microbatches and sums gradients before ONE optimizer
        # update — effective batch beyond HBM with identical update math.
        # place_batch splits row-major: microbatch i = rows [i*mb, (i+1)*mb).
        self.grad_accum = int(grad_accum)
        if self.grad_accum < 1:
            raise MXNetError("grad_accum must be >= 1")
        # multi-step fusion: pipeline_steps=K runs K optimizer steps inside
        # ONE jitted lax.scan over a stacked superbatch, so the host→device
        # dispatch (the ~1-2 ms/call tunnel tax — docs/PERF.md "Batch-32
        # inference") is paid once per K steps.  Semantics are the per-step
        # path's exactly: per-step RNG keys, LR schedule, skip_nonfinite
        # verdicts, and grad_accum all evaluate per scanned step.
        self.pipeline_steps = int(pipeline_steps)
        if self.pipeline_steps < 1:
            raise MXNetError("pipeline_steps must be >= 1")
        if self.grad_accum > 1:
            def _micro(name, shp):
                if not shp or shp[0] % self.grad_accum:
                    raise MXNetError(
                        "input %r dim0 %r not divisible by grad_accum=%d"
                        % (name, shp, self.grad_accum))
                return (shp[0] // self.grad_accum,) + tuple(shp[1:])

            data_shapes = {n: _micro(n, s) for n, s in data_shapes.items()}
            label_shapes = {n: _micro(n, s)
                            for n, s in label_shapes.items()}
        shapes = dict(data_shapes)
        shapes.update(label_shapes)
        # mesh-aware ops (ring attention) consult the ambient mesh while the
        # graph traces; scope it so multiple trainers don't clobber each other
        with default_mesh(mesh):
            arg_shapes, out_shapes, aux_shapes, arg_dtypes, aux_dtypes = _infer(
                symbol, shapes, type_dict)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        self._input_names = set(shapes)
        self.param_names = [n for n in arg_names if n not in self._input_names]
        self.arg_shapes = dict(zip(arg_names, arg_shapes))
        self.aux_shapes = dict(zip(aux_names, aux_shapes))
        self.arg_dtypes = dict(zip(arg_names, arg_dtypes))
        self.aux_dtypes = dict(zip(aux_names, aux_dtypes))
        if any(self.arg_shapes[n] is None for n in arg_names):
            missing = [n for n in arg_names if self.arg_shapes[n] is None]
            raise MXNetError("cannot infer shapes for %s" % missing)

        # -- shardings ---------------------------------------------------
        pspecs = auto_tp_specs(
            symbol, {n: self.arg_shapes[n] for n in self.param_names}, mesh,
            data_axis)
        pspecs.update(param_specs or {})
        self.param_specs = {n: pspecs.get(n, P()) for n in self.param_names}
        # ZeRO: stage>=1 shards optimizer state (and constrains gradients)
        # along the data axis; stage>=3 shards the weights themselves (FSDP).
        # Stages compose with TP specs — zero_extend_spec only claims a
        # dimension the TP spec left unsharded.
        if zero_stage not in (0, 1, 2, 3):
            raise MXNetError("zero_stage must be 0, 1, 2, or 3")
        self.zero_stage = zero_stage
        self.opt_specs = dict(self.param_specs)
        if zero_stage >= 1:
            for n in self.param_names:
                self.opt_specs[n] = zero_extend_spec(
                    self.param_specs[n], self.arg_shapes[n], mesh, data_axis)
            if zero_stage >= 3:
                self.param_specs = dict(self.opt_specs)
        dspecs = {}
        for n in self._input_names:
            shp = self.arg_shapes[n]
            spec = [None] * len(shp)
            if len(shp) >= 1 and data_axis in mesh.axis_names \
                    and shp[0] % mesh.shape[data_axis] == 0:
                spec[0] = data_axis
            if len(shp) >= 2 and "seq" in mesh.axis_names \
                    and shp[1] % mesh.shape["seq"] == 0:
                spec[1] = "seq"
            dspecs[n] = P(*spec)
        dspecs.update(data_specs or {})
        self.data_specs = dspecs

        self._run = _graph_fn(symbol)
        # rematerialization: trade FLOPs for HBM in backward (the reference's
        # memonger / MXNET_BACKWARD_DO_MIRROR, graph_executor.cc:87-89 —
        # here it's jax.checkpoint over the traced graph).  remat_policy is
        # a jax.checkpoint_policies name, e.g. 'dots_saveable' keeps matmul
        # outputs (MXU work) and recomputes the cheap elementwise chains.
        self._remat = bool(remat) or remat_policy is not None
        self._remat_policy = (getattr(jax.checkpoint_policies, remat_policy)
                              if remat_policy is not None else None)
        # -- optimizer: any registered fused-update op (the single source of
        # update math shared with the imperative Optimizer classes).  The
        # bias-correction step count and LR schedules both ride an on-device
        # counter so long runs never recompile (Optimizer sets
        # sched.base_lr, reference optimizer.py:60-61).
        (self._update_op, self._opt_attrs, self._n_states,
         self._needs_t) = resolve_update_op(
            optimizer, optimizer_params, momentum, learning_rate, wd,
            rescale_grad, clip_gradient)
        self._lr_fn = resolve_lr_fn(lr_scheduler, learning_rate)
        self._needs_count = self._needs_t or self._lr_fn is not None
        # -- multi-precision: weights live in a low-precision dtype (HBM
        # bandwidth + memory), the optimizer updates an fp32 MASTER copy
        # stored as the leading optimizer-state slot (so ZeRO shards it —
        # the bf16 + sharded-fp32-master recipe).  The reference's
        # fp16 + multi_precision SGD concept, TPU-idiomatic in bf16.
        if multi_precision:
            self._mp_dtype = ("bfloat16" if multi_precision is True
                              else str(multi_precision))
        else:
            self._mp_dtype = None
        self._diff_set = {
            n for n in self.param_names
            if not _np.issubdtype(_np.dtype(self.arg_dtypes.get(n, "float32")),
                                  _np.integer)
        }
        self._use_momentum = (self._n_states > 0
                              or self._mp_dtype is not None)
        # -- non-finite guard: when enabled the step checks loss + every
        # gradient for NaN/Inf IN-GRAPH and, on a bad batch, keeps the old
        # (params, moms, aux) via jnp.where — the step's inputs are donated,
        # so a host-side revert is impossible by construction.  The step
        # then reports the verdict as one extra trailing scalar output
        # (1.0 ok / 0.0 skipped) that ``fit`` consumes for its
        # skip-count/abort policy.  Opt-in: the trace changes shape.
        self._skip_nonfinite = bool(skip_nonfinite)
        self._step_raw = None  # untraced step body, shared with pipeline_fn
        self._jit_step = None
        self._jit_fwd = None
        self._jit_grad = None  # gradient-only step for kvstore-backed fit
        self._jit_pipe = {}  # n-step pipelines keyed by (n, unroll) —
        # partial epoch-tail flushes get their own cached trace

    def _param_dtype(self, name):
        """On-device storage dtype for a parameter (the working copy)."""
        if self._mp_dtype is not None and name in self._diff_set:
            return self._mp_dtype
        return self.arg_dtypes.get(name, "float32")

    def _state_layout(self, name):
        """(slots, state_dtype, bare) of ``moms[name]``: slot count
        (+1 leading fp32 master under multi_precision), element dtype, and
        whether a single slot stores bare (legacy sgd-momentum layout)."""
        mp = self._mp_dtype is not None and name in self._diff_set
        slots = self._n_states + (1 if mp else 0)
        dtype = "float32" if mp else self.arg_dtypes.get(name, "float32")
        return slots, dtype, (slots == 1 and not mp)

    # ------------------------------------------------------------------
    def _sharding(self, spec):
        return NamedSharding(self.mesh, spec)

    def init(self, initializer=None, seed=0):
        """Create (params, moms, aux) host-side then place sharded on mesh."""
        from ..initializer import Uniform, InitDesc

        initializer = initializer or Uniform(0.07)
        # initializers draw from the global numpy stream (reference
        # initializer.py does the same); seed it for reproducibility but
        # restore the caller's stream position afterwards
        saved_state = _np.random.get_state()
        _np.random.seed(seed)
        try:
            params, moms, aux = {}, {}, {}
            for n in self.param_names:
                shp = self.arg_shapes[n]
                arr = _np.zeros(shp, dtype=self.arg_dtypes.get(n, "float32"))
                initializer(InitDesc(n), _HostArray(arr))
                params[n] = jax.device_put(
                    arr.astype(self._param_dtype(n)),
                    self._sharding(self.param_specs[n]))
                slots, sdtype, bare = self._state_layout(n)
                if slots:
                    oshard = self._sharding(self.opt_specs[n])
                    mp_here = self._mp_dtype is not None and n in self._diff_set
                    states = []
                    if mp_here:  # leading slot = the fp32 master copy
                        states.append(jax.device_put(
                            arr.astype(_np.float32), oshard))
                    while len(states) < slots:
                        states.append(jax.device_put(
                            _np.zeros(shp, dtype=sdtype), oshard))
                    moms[n] = states[0] if bare else tuple(states)
            for n, shp in self.aux_shapes.items():
                init_val = (_np.ones if n.endswith("_var") or "moving_var" in n
                            else _np.zeros)
                aux[n] = jax.device_put(
                    init_val(shp, dtype=self.aux_dtypes.get(n, "float32")),
                    self._sharding(P()))
        finally:
            _np.random.set_state(saved_state)
        if self._needs_count:
            moms[_STEP_COUNT] = jax.device_put(
                _np.zeros((), _np.int32), self._sharding(P()))
        return params, moms, aux

    def opt_state_struct(self):
        """ShapeDtypeStructs matching ``init()``'s optimizer-state tree
        (tuples for multi-state optimizers, the on-device step counter for
        bias-corrected ones) — the restore target for sharded checkpoints."""
        if not self._use_momentum and not self._needs_count:
            return {}
        out = {}
        if self._use_momentum:
            for n in self.param_names:
                slots, sdtype, bare = self._state_layout(n)
                if not slots:
                    continue
                s = jax.ShapeDtypeStruct(
                    tuple(self.arg_shapes[n]), sdtype,
                    sharding=self._sharding(self.opt_specs[n]))
                out[n] = s if bare else (s,) * slots
        if self._needs_count:
            out[_STEP_COUNT] = jax.ShapeDtypeStruct(
                (), _np.int32, sharding=self._sharding(P()))
        return out

    def place_batch(self, arrays: Dict[str, _np.ndarray], train=True):
        """Shard a host batch onto the mesh along the declared input specs.
        With ``grad_accum=k`` a TRAINING batch splits row-major into
        ``[k, dim0/k, ...]`` on the host (free) so the scanned microbatch
        axis is unsharded and each device keeps its own rows.
        ``train=False`` places the batch unsplit for ``forward_fn`` —
        inference has no accumulation semantics, so any batch size goes."""
        out = {}
        for n, v in arrays.items():
            v = _np.asarray(v)
            if train and self.grad_accum > 1:
                k = self.grad_accum
                if v.shape[0] % k:
                    raise MXNetError(
                        "batch %r dim0 %d not divisible by grad_accum=%d"
                        % (n, v.shape[0], k))
                v = v.reshape((k, v.shape[0] // k) + v.shape[1:])
            out[n] = jax.device_put(
                v, self._sharding(self._batch_spec(n) if train
                                  else self.data_specs[n]))
        return out

    # ------------------------------------------------------------------
    def _build_step(self):
        """The raw (untraced) fused step body — the ONE spelling of the
        train-step math, traced standalone by ``step_fn`` and under
        ``lax.scan`` by ``pipeline_fn`` so the two paths cannot drift."""
        if self._step_raw is not None:
            return self._step_raw
        run = self._run
        use_mom = self._use_momentum
        update_op = self._update_op
        opt_attrs = self._opt_attrs
        needs_t = self._needs_t
        needs_count = self._needs_count
        lr_fn = self._lr_fn
        diff = [n for n in self.param_names if n in self._diff_set]
        layouts = {n: self._state_layout(n) for n in self.param_names}
        mp_set = (set(diff) if self._mp_dtype is not None else set())
        mp_dtype = self._mp_dtype
        # fused-tier whole-tree optimizer step: only the plain momentum
        # shape qualifies (bare momentum slot per param, no fp32-master
        # mixed precision, no traced step count) — everything else stays
        # on the generic per-op loop below
        use_tree = (use_mom and update_op.name == "sgd_mom_update"
                    and not mp_set and not needs_count
                    and all(layouts[n][2] for n in diff))

        graph = run
        if self._remat:
            graph = jax.checkpoint(
                run, policy=self._remat_policy, static_argnums=(3,))

        accum = self.grad_accum

        def step(params, moms, aux, batch, rng):
            def micro_grads(dparams, aux_c, mb, key):
                def loss_fn(p):
                    args = dict(mb)
                    args.update(params)
                    args.update(p)
                    outs, new_aux = graph(args, aux_c, key, True)
                    total = sum(jnp.sum(o.astype(jnp.float32)) for o in outs)
                    return total, (outs, new_aux)

                return jax.value_and_grad(loss_fn, has_aux=True)(dparams)

            def constrain(g):
                # force the gradient reduction to land sharded
                # (reduce-scatter rather than all-reduce) so the optimizer
                # math runs on 1/dp of each tensor — the ZeRO saving
                if not zero:
                    return g
                return {n: jax.lax.with_sharding_constraint(
                    g[n], zero_shard[n]) for n in g}

            dparams = {n: params[n] for n in diff}
            if accum == 1:
                (loss_total, (outs, new_aux)), grads = micro_grads(
                    dparams, aux, batch, rng)
                grads = constrain(grads)
            else:
                def body(carry, xs):
                    gacc, aux_c, lsum = carry
                    mb, i = xs
                    (lv, (outs_i, aux_n)), g = micro_grads(
                        dparams, aux_c, mb, jax.random.fold_in(rng, i))
                    gacc = constrain({
                        n: gacc[n] + g[n].astype(jnp.float32) for n in g})
                    return (gacc, aux_n, lsum + lv), outs_i

                gacc0 = constrain({
                    n: jnp.zeros(dparams[n].shape, jnp.float32)
                    for n in diff})
                (gacc, new_aux, loss_total), outs_stack = jax.lax.scan(
                    body, (gacc0, aux, jnp.float32(0)),
                    (batch, jnp.arange(accum)))
                # multi-precision updates consume fp32 grads directly;
                # otherwise return to the parameter dtype
                grads = {n: (gacc[n] if n in mp_set
                             else gacc[n].astype(dparams[n].dtype))
                         for n in diff}
                # merge the stacked microbatch axis back into the batch axis
                # (row-major — the inverse of place_batch's split); scalar
                # heads (rank-0 per microbatch) average across microbatches
                # so output shapes are invariant to grad_accum — exact for
                # mean-normalized losses over the equal row-major split
                outs = [o.reshape((o.shape[0] * o.shape[1],) + o.shape[2:])
                        if o.ndim >= 2 else o.mean(0) for o in outs_stack]
            if guard:
                ok = jnp.isfinite(loss_total)
                for n in diff:
                    ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(grads[n])))
            new_params, new_moms = dict(params), dict(moms)
            attrs = opt_attrs
            if needs_count:
                t_new = moms[_STEP_COUNT] + 1
                new_moms[_STEP_COUNT] = t_new
                attrs = dict(opt_attrs)
                if needs_t:
                    attrs["t"] = t_new
                if lr_fn is not None:
                    attrs["lr"] = lr_fn(t_new)
            if use_tree:
                from ..ops.registry import dispatch_variant

                okv = ok if guard else None
                tree_p, tree_m = dispatch_variant(
                    "sgd_mom_tree_update", sgd_mom_tree_stock, attrs,
                    {n: params[n] for n in diff}, grads,
                    {n: moms[n] for n in diff}, okv)
                new_params.update(tree_p)
                new_moms.update(tree_m)
                if guard:
                    # params/moms guard is folded into the tree step;
                    # aux still keeps its old state on a bad batch
                    new_aux = jax.tree_util.tree_map(
                        lambda a, b: jnp.where(ok, a, b), new_aux, aux)
                    outs = list(outs) + [ok.astype(jnp.float32)]
                return outs, new_params, new_moms, new_aux
            for n in diff:
                slots, _, bare = layouts[n]
                st = moms.get(n, ()) if use_mom else ()
                if bare:
                    st = (st,)
                if n in mp_set:
                    # update the fp32 master (leading state slot); the
                    # working weight is its low-precision cast
                    master, op_st = st[0], st[1:]
                    upd, _ = update_op.apply(
                        attrs,
                        [master, grads[n].astype(jnp.float32), *op_st])
                    new_params[n] = upd[0].astype(mp_dtype)
                    new_moms[n] = tuple(upd)
                else:
                    upd, _ = update_op.apply(
                        attrs, [params[n], grads[n], *st])
                    new_params[n] = upd[0]
                    if bare:
                        new_moms[n] = upd[1]
                    elif slots:
                        new_moms[n] = tuple(upd[1:])
            if guard:
                # bad batch: keep EVERY piece of old state (weights, momenta,
                # the schedule counter, aux) — the skipped step never happened
                keep = jax.tree_util.tree_map
                new_params = keep(lambda a, b: jnp.where(ok, a, b),
                                  new_params, params)
                new_moms = keep(lambda a, b: jnp.where(ok, a, b),
                                new_moms, moms)
                new_aux = keep(lambda a, b: jnp.where(ok, a, b),
                               new_aux, aux)
                outs = list(outs) + [ok.astype(jnp.float32)]
            return outs, new_params, new_moms, new_aux

        guard = self._skip_nonfinite
        zero = self.zero_stage >= 1
        zero_shard = {n: self._sharding(self.opt_specs[n])
                      for n in self.param_names}
        self._step_raw = step
        return step

    def _step_shardings(self):
        """NamedSharding trees ``(pshard, mshard, ashard, dshard)`` for the
        fused step's arguments — one spelling shared by ``step_fn`` and
        ``pipeline_fn`` so their placement contracts cannot diverge."""
        zero_shard = {n: self._sharding(self.opt_specs[n])
                      for n in self.param_names}
        pshard = {n: self._sharding(self.param_specs[n])
                  for n in self.param_names}
        mshard = {}
        if self._use_momentum:
            for n in self.param_names:
                slots, _, bare = self._state_layout(n)
                if not slots:
                    continue
                mshard[n] = (zero_shard[n] if bare
                             else (zero_shard[n],) * slots)
        if self._needs_count:
            mshard[_STEP_COUNT] = self._sharding(P())
        ashard = {n: self._sharding(P()) for n in self.aux_shapes}
        dshard = {n: self._sharding(self._batch_spec(n))
                  for n in self._input_names}
        return pshard, mshard, ashard, dshard

    def _compile_counted(self, cache, jitted, raw=None, steps=1):
        """Wrap a jitted callable so its FIRST call (the trace+compile)
        lands in the compile-accounting families under ``cache``; every
        later call passes straight through.  Pairs with the jit caches:
        one wrapper per cache entry, so steady-state fit records zero
        compiles and a moving counter means the cache keys missed.

        With ``raw`` (the underlying ``jax.jit`` object), the first
        call also records the compiled program's HLO cost analysis —
        FLOPs / bytes / memory footprint under
        ``trainer_compile_flops{cache}`` etc. and, via ``steps`` (how
        many optimizer steps one dispatch advances — ``pipeline_fn(n)``
        scans ``n``; 0 = not a train step), the per-step model-FLOPs
        figure MFU is derived from (``observability.efficiency``).  The
        lowering happens BEFORE the dispatch runs, while donated
        argument buffers are still live; its cost (one extra AOT
        compile per cache under the default
        ``MXNET_TPU_COST_ANALYSIS=compiled`` tier) is deliberately
        inside the ``trainer_compile_seconds`` window so the goodput
        ledger books it as recompile badput."""
        done = []
        mesh = self.mesh

        def call(*args, **kwargs):
            if done:
                return jitted(*args, **kwargs)
            t0 = _time.monotonic()
            if raw is not None:
                from . import default_mesh

                def _lower():
                    with default_mesh(mesh):
                        return raw.lower(*args, **kwargs)

                _eff.record_compile(cache, _lower, steps=steps)
            out = jitted(*args, **kwargs)
            done.append(True)
            _M_COMPILES.labels(cache).inc()
            _M_COMPILE_T.labels(cache).observe(_time.monotonic() - t0)
            return out

        return call

    def step_fn(self):
        """The fused train step: (params, moms, aux, batch, rng) ->
        (outputs, new_params, new_moms, new_aux)."""
        if self._jit_step is not None:
            return self._jit_step
        step = self._build_step()
        pshard, mshard, ashard, dshard = self._step_shardings()
        self._jit_step_raw = jax.jit(
            step,
            in_shardings=(pshard, mshard, ashard, dshard, None),
            out_shardings=(None, pshard, mshard, ashard),
            donate_argnums=(0, 1),
        )
        self._jit_step = self._compile_counted(
            "step", self._with_mesh(self._jit_step_raw),
            raw=self._jit_step_raw)
        return self._jit_step

    # ------------------------------------------------------------------
    def _superbatch_spec(self, name):
        """Input spec for the stacked pipeline axis: ``[K, ...]`` with the
        leading (scanned) step axis unsharded on top of ``_batch_spec``."""
        return P(None, *self._batch_spec(name))

    def place_superbatch(self, batches):
        """Stack K host batches into one ``[K, ...]`` superbatch sharded on
        the mesh — ``pipeline_fn``'s input.  Each element of ``batches`` is
        a ``name -> host array`` dict; under ``grad_accum`` each batch is
        first split row-major exactly as ``place_batch`` would (so the
        scanned layout is ``[K, grad_accum, mb, ...]``)."""
        if not batches:
            raise MXNetError("place_superbatch needs at least one batch")
        out = {}
        ga = self.grad_accum
        for n in batches[0]:
            vs = []
            for b in batches:
                v = _np.asarray(b[n])
                if ga > 1:
                    if v.shape[0] % ga:
                        raise MXNetError(
                            "batch %r dim0 %d not divisible by grad_accum=%d"
                            % (n, v.shape[0], ga))
                    v = v.reshape((ga, v.shape[0] // ga) + v.shape[1:])
                vs.append(v)
            out[n] = jax.device_put(
                _np.stack(vs), self._sharding(self._superbatch_spec(n)))
        return out

    def pipeline_fn(self, n=None, unroll=None):
        """``n`` fused steps in ONE dispatch: ``(params, moms, aux,
        superbatch, base_key, step0) -> (stacked_outs, params, moms, aux)``.

        ``lax.scan`` over the superbatch's leading axis runs the SAME raw
        step body ``step_fn`` traces; scanned step ``i`` draws
        ``fold_in(base_key, step0 + i)`` — ``fold_in`` of a traced counter
        is bitwise the eager per-step stream, so pipelined parameter
        evolution is the per-step path's exactly.  Outputs come back
        stacked ``[n, ...]`` (the trailing skip_nonfinite verdict, when
        enabled, as an ``[n]`` vector) and are fetched once per flush —
        the tunnel is crossed once per ``n`` steps.  Jitted per
        ``(n, unroll)`` and cached, so epoch-tail partial flushes reuse
        their own trace.

        ``unroll`` defaults to full (the scan emits ``n`` copies of the
        step): pipeline depths are small, and the rolled while-loop
        measured ~5x slower per step on XLA:CPU (the loop carries the
        whole parameter tree through per-iteration buffer shuffles that
        straight-line code avoids).  Pass ``unroll=1`` to trade that for
        an ``n``-independent compile time at large depths — or when
        bitwise-exact parity with the per-step path matters for
        multi-state optimizers: full unroll lets XLA fuse across
        iterations, which moved adam by ~1e-8 in testing (sgd/momentum/
        multi-precision stayed exact either way)."""
        if n is None:
            n = self.pipeline_steps
        n = int(n)
        if n < 1:
            raise MXNetError("pipeline_fn needs n >= 1")
        unroll = n if unroll is None else int(unroll)
        cached = self._jit_pipe.get((n, unroll))
        if cached is not None:
            return cached
        step = self._build_step()

        def pipe(params, moms, aux, superbatch, base_key, step0):
            def body(carry, xs):
                p, m, a = carry
                batch, i = xs
                key = jax.random.fold_in(base_key, step0 + i)
                outs, p, m, a = step(p, m, a, batch, key)
                return (p, m, a), outs

            (p, m, a), outs_stack = jax.lax.scan(
                body, (params, moms, aux),
                (superbatch, jnp.arange(n, dtype=jnp.int32)),
                unroll=unroll)
            return outs_stack, p, m, a

        pshard, mshard, ashard, _ = self._step_shardings()
        sshard = {nm: self._sharding(self._superbatch_spec(nm))
                  for nm in self._input_names}
        fn = jax.jit(
            pipe,
            in_shardings=(pshard, mshard, ashard, sshard, None, None),
            out_shardings=(None, pshard, mshard, ashard),
            donate_argnums=(0, 1),
        )
        wrapped = self._compile_counted(
            "pipe:%d:%d" % (n, unroll), self._with_mesh(fn), raw=fn,
            steps=n)
        self._jit_pipe[(n, unroll)] = wrapped
        return wrapped

    def _batch_spec(self, name):
        """Input spec as the step receives it (microbatch axis prepended
        under grad_accum — matching place_batch's host-side split)."""
        spec = self.data_specs[name]
        return P(None, *spec) if self.grad_accum > 1 else spec

    def lowered_step(self, params, moms, aux, batch, rng):
        """AOT-lower the fused step for inspection (cost/memory analysis via
        ``.compile().memory_analysis()`` — the memonger accounting)."""
        from . import default_mesh

        self.step_fn()
        with default_mesh(self.mesh):
            return self._jit_step_raw.lower(params, moms, aux, batch, rng)

    def grad_fn(self):
        """Jitted gradient-only step for parameter-server training:
        ``(params, aux, batch, rng) -> (outputs, grads, new_aux)``.

        Where ``step_fn`` fuses forward + backward + optimizer update,
        this stops at the gradients: the optimizer runs wherever the
        authoritative weights live — for ``kvstore='dist_async'`` that is
        the (replicated) parameter server, which applies the update the
        moment the pushed gradient arrives (``set_optimizer`` contract).
        Inputs are NOT donated: the caller re-feeds the same ``params``
        until the next pull replaces them."""
        if self._jit_grad is not None:
            return self._jit_grad
        run = self._run
        graph = run
        if self._remat:
            graph = jax.checkpoint(
                run, policy=self._remat_policy, static_argnums=(3,))
        diff = [n for n in self.param_names if n in self._diff_set]

        def gstep(params, aux, batch, rng):
            def loss_fn(p):
                args = dict(batch)
                args.update(params)
                args.update(p)
                outs, new_aux = graph(args, aux, rng, True)
                total = sum(jnp.sum(o.astype(jnp.float32)) for o in outs)
                return total, (outs, new_aux)

            dparams = {n: params[n] for n in diff}
            (_, (outs, new_aux)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(dparams)
            return outs, grads, new_aux

        pshard, _, ashard, dshard = self._step_shardings()
        gjit = jax.jit(gstep, in_shardings=(pshard, ashard, dshard, None))
        self._jit_grad = self._compile_counted(
            "grad", self._with_mesh(gjit), raw=gjit)
        return self._jit_grad

    def forward_fn(self):
        """Jitted inference forward: (params, aux, batch) -> outputs."""
        if self._jit_fwd is not None:
            return self._jit_fwd
        run = self._run

        def fwd(params, aux, batch, rng):
            # inference takes the batch UNSPLIT regardless of grad_accum —
            # accumulation only exists to fit the backward pass in HBM
            args = dict(batch)
            args.update(params)
            outs, _ = run(args, aux, rng, False)
            return outs

        pshard = {n: self._sharding(self.param_specs[n]) for n in self.param_names}
        ashard = {n: self._sharding(P()) for n in self.aux_shapes}
        dshard = {n: self._sharding(self.data_specs[n])
                  for n in self._input_names}
        fjit = jax.jit(fwd, in_shardings=(pshard, ashard, dshard, None))
        # steps=0: the eval forward is not a training step — its cost
        # rows are recorded, the model-FLOPs/step gauge is left alone
        self._jit_fwd = self._compile_counted(
            "fwd", self._with_mesh(fjit), raw=fjit, steps=0)
        return self._jit_fwd

    # ------------------------------------------------------------------
    def fit(self, train_data, eval_data=None, num_epoch=1, seed=0,
            eval_metric="accuracy", initializer=None, state=None,
            begin_epoch=0, checkpoint_dir=None, checkpoint_every=None,
            resume=None, max_bad_steps=5, log_every=50, logger=None,
            batch_end_callback=None, metric_every=1, kvstore=None,
            roster=None):
        """Mesh-native training loop — ``Module.fit``'s role
        (reference ``module/base_module.py:368``) for a ``ShardedTrainer``:
        epochs over a ``DataIter``, metric updates, throughput logging
        (``Speedometer``, reference ``callback.py:89``), optional eval pass
        and sharded checkpoints.

        Pipelined execution
        -------------------
        With ``pipeline_steps=K > 1`` each dispatch runs a K-step
        ``pipeline_fn`` flush over a superbatch that a background
        ``PrefetchFeeder`` (engine IO lane) staged while the previous
        flush computed — dispatch and host-feed latency hide behind
        device work, and parameter evolution stays bitwise the per-step
        path's (same per-step RNG keys, LR schedule, skip policy).
        Chunk sizes are planned so flush boundaries land exactly on
        ``checkpoint_every`` multiples — checkpoints and their resume
        metas are identical to the per-step path's, including resume
        from a checkpoint that falls mid-superbatch.  ``metric_every=F``
        fetches step outputs for the metric only every F-th flush (the
        non-blocking-metrics knob: the skipped flushes never sync on a
        readback); the epoch metric then samples 1/F of the flushes.
        The trailing short flush of an epoch reuses a cached smaller
        trace, so tails cost one extra compile, not wrong math.

        Fault tolerance
        ---------------
        ``checkpoint_every=N`` saves every N global steps (numbered by
        global step) in addition to epoch ends; without it, epoch-end
        saves keep the historical ``epoch + 1`` numbering.  Every save
        made by this loop also writes a ``fit-meta-<step>.json`` sidecar
        recording the loop position (global step, epoch, batch offset,
        RNG anchor).

        ``resume="auto"`` restarts from the newest restorable checkpoint
        in ``checkpoint_dir``: the newest one is validated by actually
        restoring it, and on failure (torn write, corrupt shard) the loop
        falls back to the previous step, then the one before, starting
        fresh only when none restore.  A resumed run re-enters the
        interrupted epoch at the saved batch offset with the SAME
        per-step RNG stream, so an interrupted+resumed run reproduces the
        uninterrupted run's parameters at every later checkpoint
        boundary.  (Resume replaces ``state``/``begin_epoch``;
        ``num_epoch`` stays the TOTAL epoch target, so a run killed at
        epoch 3 of 10 resumes and finishes the remaining 7.)

        When the trainer was built with ``skip_nonfinite=True``, each
        step's non-finite verdict feeds a skip policy: a bad batch leaves
        the state untouched and is excluded from the metric;
        ``max_bad_steps`` CONSECUTIVE bad batches abort with
        ``MXNetError`` (a diverged run re-reading the same poison forever
        is worse than a crash).

        ``state`` resumes from an existing ``(params, moms, aux)`` (e.g. a
        ``checkpoint.restore_sharded`` result); pass ``begin_epoch`` so
        checkpoint steps and history keys continue from the right epoch.
        NOTE: the step donates its inputs, so ``state``'s arrays are
        CONSUMED by the first step — a caller branching several runs from
        one restore must re-restore (or copy) per run.
        Returns ``((params, moms, aux), history)`` where ``history[epoch]``
        maps ``"train"``/``"eval"`` to the metric's ``get()`` result.

        ``kvstore=`` switches to parameter-server-backed training: each
        step computes gradients locally (``grad_fn``), pushes them to
        the kvstore (whose server-side optimizer — ``set_optimizer``,
        called by the caller beforehand — applies the update), and pulls
        the fresh weights back.  A replicated ``dist_async`` store rides
        out single-server failures transparently inside push/pull
        (heartbeat failover + same-seq retry), so a mid-epoch primary
        kill neither aborts the loop nor trips any resume machinery.

        ``roster=`` (kvstore path only) makes the worker set elastic:
        an :class:`~mxnet_tpu.elastic.WorkerRoster` assigns each global
        batch index to exactly one member rank, re-consulted EVERY
        batch — a ``roster.join``/``drain`` between two steps
        re-balances the remaining batches across the new member set
        with no epoch restart.  The loop records its position in the
        roster (``mark_progress``) after every batch, so a rank that
        joins mid-epoch fast-forwards its iterator to the group's
        ``resume_point()`` instead of re-running covered batches — the
        mid-epoch handoff that keeps ``resume="auto"``-style
        exactly-once batch coverage across topology changes.  The
        roster is this process's view of membership (in-process ranks
        share one instance; cross-process deployments drive each
        process's roster from the same control plane).

        A terminal failure escaping the loop (``ShardFailedError`` after
        a whole-group loss, poison surfacing at a sync point, divergence
        abort) triggers the flight recorder on its way out — with
        ``MXNET_TPU_FLIGHT_DIR`` set, a postmortem bundle (span tail,
        metrics snapshot, chaos rules, membership epochs, exception
        chain) lands there before the exception reaches the caller.
        """
        try:
            return self._fit_impl(
                train_data, eval_data=eval_data, num_epoch=num_epoch,
                seed=seed, eval_metric=eval_metric,
                initializer=initializer, state=state,
                begin_epoch=begin_epoch, checkpoint_dir=checkpoint_dir,
                checkpoint_every=checkpoint_every, resume=resume,
                max_bad_steps=max_bad_steps, log_every=log_every,
                logger=logger, batch_end_callback=batch_end_callback,
                metric_every=metric_every, kvstore=kvstore,
                roster=roster)
        except Exception as exc:
            from ..observability import flight_recorder as _flight

            _flight.record_failure("trainer.fit", exc)
            raise

    def _fit_impl(self, train_data, eval_data=None, num_epoch=1, seed=0,
                  eval_metric="accuracy", initializer=None, state=None,
                  begin_epoch=0, checkpoint_dir=None,
                  checkpoint_every=None, resume=None, max_bad_steps=5,
                  log_every=50, logger=None, batch_end_callback=None,
                  metric_every=1, kvstore=None, roster=None):
        import logging
        import time as _time

        import jax as _jax

        from .. import metric as _metric_mod
        from .. import observability as _obs
        from . import checkpoint as _ckpt
        from . import prefetch as _prefetch

        if kvstore is not None:
            return self._fit_kvstore(
                kvstore, train_data, eval_data=eval_data,
                num_epoch=num_epoch, seed=seed, eval_metric=eval_metric,
                initializer=initializer, state=state,
                begin_epoch=begin_epoch, checkpoint_dir=checkpoint_dir,
                checkpoint_every=checkpoint_every, resume=resume,
                log_every=log_every, logger=logger,
                batch_end_callback=batch_end_callback, roster=roster)

        if roster is not None:
            raise MXNetError(
                "roster= is the elastic-worker knob of the kvstore path; "
                "pass kvstore= as well (the local fused-update path has "
                "no cross-worker batch assignment to re-balance)")

        log = logger or logging.getLogger(__name__)
        metric = (eval_metric if isinstance(eval_metric, _metric_mod.EvalMetric)
                  else _metric_mod.create(eval_metric))

        # -- resume="auto": newest RESTORABLE checkpoint wins ------------
        resume_meta = None
        if resume not in (None, False, "auto"):
            raise MXNetError("resume must be None or 'auto', got %r"
                             % (resume,))
        if resume == "auto" and checkpoint_dir is not None:
            from .. import durable as _durable
            from ..base import CheckpointCorruptError as _CkptCorrupt

            _state_in = state  # restored on every fallback hop
            for ckpt_step in reversed(_ckpt.all_steps(checkpoint_dir)):
                try:
                    verified = _ckpt.verify_checkpoint(checkpoint_dir,
                                                       ckpt_step)
                    state = _ckpt.restore_sharded(checkpoint_dir, ckpt_step,
                                                  trainer=self)
                    resume_meta = _ckpt.load_fit_meta(checkpoint_dir,
                                                      ckpt_step)
                except _CkptCorrupt as exc:
                    state = _state_in
                    _durable.quarantine(
                        "checkpoint", exc, step=int(ckpt_step),
                        directory=str(checkpoint_dir),
                        file=getattr(exc, "file", None))
                    log.warning(
                        "resume: checkpoint step %d failed integrity "
                        "verification (%s); falling back to the previous "
                        "checkpoint", ckpt_step, exc)
                    continue
                except Exception as exc:  # noqa: BLE001 — fall back a step
                    log.warning(
                        "resume: checkpoint step %d failed validation "
                        "(%r); falling back to the previous checkpoint",
                        ckpt_step, exc)
                    continue
                if resume_meta is None and verified:
                    # manifest-era checkpoint with its sidecar missing:
                    # the save was killed between the shard write and the
                    # meta write — its loop position is unknowable, so
                    # fall back to the previous intact step
                    state = _state_in
                    log.warning(
                        "resume: checkpoint step %d has a manifest but no "
                        "fit-meta sidecar (save killed mid-write); falling "
                        "back to the previous checkpoint", ckpt_step)
                    continue
                if resume_meta is None:
                    # pre-sidecar checkpoint: its step number is an epoch
                    # boundary (the historical epoch+1 numbering) and the
                    # historical RNG anchoring applies
                    resume_meta = {"global_step": 0, "epoch": ckpt_step,
                                   "batch_in_epoch": 0, "seed": seed,
                                   "base_epoch": ckpt_step}
                log.info("resume: restored checkpoint step %d (epoch %d, "
                         "batch %d, global step %d)", ckpt_step,
                         resume_meta["epoch"],
                         resume_meta.get("batch_in_epoch", 0),
                         resume_meta.get("global_step", 0))
                break
            else:
                log.info("resume: no restorable checkpoint under %r — "
                         "starting fresh", checkpoint_dir)

        params, moms, aux = (state if state is not None
                             else self.init(initializer=initializer,
                                            seed=seed))
        # memory-ledger seams: the state trees are the pool baseline the
        # reconcile gate checks against jax.live_arrays() at sample points
        _mem.tag_tree("params", id(self), (params, aux))
        _mem.tag_tree("optimizer", id(self), moms)
        K = self.pipeline_steps
        step = self.step_fn() if K == 1 else None
        fwd = self.forward_fn()

        from ..io import batch_arrays as _io_batch_arrays

        def batch_arrays(batch, it):
            # the shared iterator hook, restricted to this graph's inputs
            return _io_batch_arrays(batch, it, self._input_names)

        from ..callback import Speedometer
        from ..model import BatchEndParam

        callbacks = (list(batch_end_callback)
                     if isinstance(batch_end_callback, (list, tuple))
                     else [batch_end_callback] if batch_end_callback
                     else [])
        speedo = None  # built from the first batch's row count

        history = {}
        if checkpoint_every is not None:
            checkpoint_every = int(checkpoint_every)
            if checkpoint_every < 1:
                raise MXNetError("checkpoint_every must be >= 1")
            if checkpoint_dir is None:
                raise MXNetError(
                    "checkpoint_every needs a checkpoint_dir to save into")
        if resume_meta is not None:
            start_epoch = int(resume_meta["epoch"])
            global_step = int(resume_meta.get("global_step", 0))
            skip_batches = int(resume_meta.get("batch_in_epoch", 0))
            rng_seed = int(resume_meta.get("seed", seed))
            rng_anchor = int(resume_meta.get("base_epoch", 0))
        else:
            start_epoch = begin_epoch
            global_step = 0
            skip_batches = 0
            rng_seed = seed
            # fold begin_epoch in so a manually-resumed run (state= +
            # begin_epoch=) continues a fresh key stream instead of
            # replaying the original run's dropout masks
            rng_anchor = begin_epoch
        end_epoch = begin_epoch + num_epoch
        # per-step keys are fold_in(anchor, global_step): because BOTH the
        # anchor and the step index persist across resume (via the meta
        # sidecar), a resumed run draws exactly the keys the uninterrupted
        # run would have
        base_key = _jax.random.fold_in(_jax.random.PRNGKey(rng_seed),
                                       rng_anchor)

        # stream-capable iterators (state()/load_state(): StreamDataIter)
        # carry their serialized cursor in the meta sidecar, so a
        # mid-epoch resume restores the EXACT read position (file,
        # offset, shuffle epoch) instead of replaying the epoch head;
        # epoch starts go through seek_epoch(epoch) so the shuffle
        # schedule is a pure function of the loop epoch on fresh and
        # resumed runs alike
        streamable = (hasattr(train_data, "state")
                      and hasattr(train_data, "load_state"))
        stream_state = [None]
        stream_loaded = False
        if streamable and resume_meta is not None:
            st = resume_meta.get("stream")
            if st is not None and int(st.get("epoch", -1)) == start_epoch:
                train_data.load_state(st)
                skip_batches = 0
                stream_loaded = True

        def fit_meta(epoch, batch_in_epoch):
            meta = {"global_step": global_step, "epoch": epoch,
                    "batch_in_epoch": batch_in_epoch, "seed": rng_seed,
                    "base_epoch": rng_anchor}
            if stream_state[0] is not None:
                meta["stream"] = stream_state[0]
            return meta

        # observability: handles resolved ONCE here; the loop pays one
        # method call per event (MXNET_TPU_METRICS=0 short-circuits it)
        _m_step = _obs.histogram(
            "trainer_step_seconds",
            "Optimizer-step wall time seen by the fit loop; pipelined "
            "flushes are amortized over their K fused steps")
        _m_steps = _obs.counter("trainer_steps_total",
                                "Optimizer steps applied by fit")
        _m_tokens = _obs.gauge(
            "trainer_tokens_per_sec",
            "Training throughput (batch rows per second) of the most "
            "recent step or flush")

        # goodput ledger: every wall second from here to the return is
        # accounted productive vs badput{cause} (observability.efficiency);
        # the snapshot must precede the first compile so warmup books as
        # recompile badput
        led = _eff.ledger()
        t_fit = _time.monotonic()

        guard = self._skip_nonfinite
        bad_streak = 0
        skipped_total = 0
        last_saved = None
        flushes = 0
        metric_every = int(metric_every)
        if metric_every < 1:
            raise MXNetError("metric_every must be >= 1")

        def after_step(epoch, arrays, data_names, ok, outs_host,
                       can_ckpt=True, att=None):
            """Per-step host bookkeeping shared by the per-step and
            pipelined paths: skip policy, metric, speedometer, callbacks,
            periodic checkpoint.  ``outs_host=None`` = this step's flush
            skipped its metric fetch (``metric_every``); ``can_ckpt`` is
            False for mid-flush steps — the in-hand (params, moms, aux)
            are END-of-flush state, valid to save only at the flush's
            last step (chunk planning puts every checkpoint boundary
            there)."""
            nonlocal bad_streak, skipped_total, speedo, last_saved
            if ok:
                bad_streak = 0
                if outs_host is not None:
                    labels = [v for n, v in arrays.items()
                              if n not in data_names]
                    metric.update([_np.asarray(v) for v in labels],
                                  outs_host)
            else:
                bad_streak += 1
                skipped_total += 1
                log.warning(
                    "non-finite loss/grad at global step %d — step "
                    "skipped, state unchanged (%d consecutive, %d "
                    "total)", global_step - 1, bad_streak,
                    skipped_total)
                if bad_streak >= max_bad_steps:
                    raise MXNetError(
                        "aborting fit: %d consecutive non-finite "
                        "steps (last at global step %d) — the run "
                        "has diverged or the input data is bad"
                        % (bad_streak, global_step - 1))
            if speedo is None and log_every:
                # windowed samples/s (metric=None so the epoch metric
                # is not reset mid-epoch by the logger)
                speedo = Speedometer(
                    next(iter(arrays.values())).shape[0],
                    frequent=log_every)
            bep = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                eval_metric=metric, locals=None)
            if speedo is not None:
                speedo(bep._replace(eval_metric=None))
            for cb in callbacks:
                cb(bep)
            if (can_ckpt and checkpoint_every
                    and global_step % checkpoint_every == 0):
                # timed as its own phase: the in-step save is badput in
                # the goodput ledger's books, not productive step time
                with (att.phase("checkpoint") if att is not None
                      else _contextlib.nullcontext()):
                    _ckpt.save_sharded(checkpoint_dir, global_step,
                                       params, moms, aux)
                    _ckpt.save_fit_meta(checkpoint_dir, global_step,
                                        fit_meta(epoch, nbatch))
                last_saved = global_step
                _attr.sample_memory()

        for epoch in range(start_epoch, end_epoch):
            metric.reset()
            if stream_loaded and epoch == start_epoch:
                pass  # cursor already at the bitwise mid-epoch position
            elif streamable and hasattr(train_data, "seek_epoch"):
                train_data.seek_epoch(epoch)
            else:
                train_data.reset()
            nbatch = 0
            if K == 1:
                it = iter(train_data)
                while True:
                    # attribution brackets the WHOLE step — including the
                    # iterator pull — so the phase sums plus the residual
                    # reconcile against trainer_step_seconds exactly
                    att = _attr.attributor()
                    t_step = _time.monotonic()
                    try:
                        with att.phase("data_wait"):
                            batch = next(it)
                    except StopIteration:
                        break
                    if skip_batches:
                        # resumed mid-epoch: replay the iterator up to the
                        # checkpointed batch offset without stepping (the
                        # attributor is dropped unclosed: records nothing)
                        skip_batches -= 1
                        nbatch += 1
                        continue
                    if streamable:
                        # the batch just pulled left the cursor exactly
                        # at its end — the watermark the next periodic
                        # checkpoint's meta will carry
                        stream_state[0] = train_data.state()
                    arrays, data_names = batch_arrays(batch, train_data)
                    with _obs.span("trainer.step", step=global_step):
                        with att.phase("placement"):
                            placed = self.place_batch(arrays)
                        with att.phase("compute"):
                            outs, params, moms, aux = step(
                                params, moms, aux, placed,
                                _jax.random.fold_in(base_key, global_step))
                            ok = True
                            if guard:
                                # trailing scalar = the step's in-graph
                                # verdict; the asnumpy read syncs, which
                                # the skip policy needs anyway
                                ok = bool(_np.asarray(outs[-1]))
                                outs = outs[:-1]
                    global_step += 1
                    nbatch += 1
                    flushes += 1
                    with att.phase("flush"):
                        outs_host = ([_np.asarray(o) for o in outs]
                                     if flushes % metric_every == 0
                                     else None)
                    after_step(epoch, arrays, data_names, ok, outs_host,
                               att=att)
                    dt = _time.monotonic() - t_step
                    led.step(dt, att.close(dt))
                    _m_step.observe(dt)
                    _m_steps.inc()
                    _eff.record_step_rate(1, dt)
                    if dt > 0:
                        _m_tokens.set(
                            next(iter(arrays.values())).shape[0] / dt)
            else:
                # -- pipelined path: K fused steps per dispatch over a
                # feeder-staged superbatch -------------------------------
                while skip_batches:
                    # resumed mid-epoch: replay BEFORE the feeder starts
                    # prefetching, so chunk 0 begins at the right batch
                    try:
                        next(train_data)
                    except StopIteration:
                        break
                    skip_batches -= 1
                    nbatch += 1
                # plan chunk sizes at push time so every flush END lands
                # on a checkpoint boundary (never crosses one mid-flush):
                # the feeder calls plan_size once per fetch, in push order
                planned = [global_step]

                def plan_size():
                    k = K
                    if checkpoint_every:
                        k = min(k, checkpoint_every
                                - planned[0] % checkpoint_every)
                    planned[0] += k
                    return k

                def extract(b):
                    # runs on the IO worker right after the iterator
                    # pull, so a stream-capable iterator's cursor is
                    # exactly at this batch's end: the snapshot rides
                    # with the batch and the checkpoint at a flush end
                    # gets the watermark of the last CONSUMED batch,
                    # immune to the feeder's read-ahead
                    arrays, data_names = batch_arrays(b, train_data)
                    return (arrays, data_names,
                            train_data.state() if streamable else None)

                with _obs.span("trainer.prefetch_start"):
                    # fetch ops pushed by the constructor inherit this
                    # span as their cross-thread parent
                    feeder = _prefetch.PrefetchFeeder(
                        iter(train_data), extract=extract,
                        place=lambda host: self.place_superbatch(
                            [h[0] for h in host]),
                        sizes=plan_size, depth=2, name="fit.prefetch")
                try:
                    while True:
                        # per-FLUSH attribution (feeder-side placement is
                        # accounted by prefetch_place_seconds_total — here
                        # data_wait is the stall waiting on the feeder)
                        att = _attr.attributor()
                        t_flush = _time.monotonic()
                        with _obs.span("trainer.flush", flush=flushes):
                            with att.phase("data_wait"):
                                chunk = feeder.next_chunk()
                            if chunk is None:
                                break
                            n = chunk.count
                            with att.phase("compute"):
                                outs_stack, params, moms, aux = \
                                    self.pipeline_fn(n)(
                                        params, moms, aux, chunk.placed,
                                        base_key, _np.int32(global_step))
                        flushes += 1
                        verdicts = None
                        with att.phase("flush"):
                            if guard:
                                # one [n] readback per flush drives the
                                # skip policy for all n steps
                                verdicts = _np.asarray(outs_stack[-1])
                                outs_stack = outs_stack[:-1]
                            outs_host = None
                            if flushes % metric_every == 0:
                                outs_host = [_np.asarray(o)
                                             for o in outs_stack]
                        for j in range(n):
                            arrays, data_names = chunk.host[j][:2]
                            if streamable:
                                stream_state[0] = chunk.host[j][2]
                            ok = (True if verdicts is None
                                  else bool(verdicts[j]))
                            global_step += 1
                            nbatch += 1
                            after_step(
                                epoch, arrays, data_names, ok,
                                None if outs_host is None
                                else [o[j] for o in outs_host],
                                can_ckpt=(j == n - 1), att=att)
                        dt = _time.monotonic() - t_flush
                        led.step(dt, att.close(dt))
                        _m_steps.inc(n)
                        for _ in range(n):  # amortized per-step latency
                            _m_step.observe(dt / n)
                        _eff.record_step_rate(n, dt)
                        if dt > 0:
                            rows = next(iter(
                                chunk.host[0][0].values())).shape[0]
                            _m_tokens.set(rows * n / dt)
                        # flush end = a stable live set (no mid-dispatch
                        # churn): the meaningful HBM watermark point
                        _attr.sample_memory()
                finally:
                    feeder.close()
            history.setdefault(epoch, {})["train"] = metric.get()
            log.info("epoch %d train: %s", epoch, history[epoch]["train"])

            if eval_data is not None:
                metric.reset()
                eval_data.reset()
                for batch in eval_data:
                    arrays, data_names = batch_arrays(batch, eval_data)
                    placed = self.place_batch(arrays, train=False)
                    outs = fwd(params, aux, placed,
                               _jax.random.PRNGKey(0))
                    labels = [v for n, v in arrays.items()
                              if n not in data_names]
                    metric.update([_np.asarray(v) for v in labels],
                                  [_np.asarray(o) for o in outs])
                history[epoch]["eval"] = metric.get()
                log.info("epoch %d eval: %s", epoch, history[epoch]["eval"])

            if checkpoint_dir is not None:
                t_ck = _time.monotonic()
                if checkpoint_every:
                    # global-step numbering throughout (the historical
                    # epoch+1 numbering would collide with step numbers)
                    if last_saved != global_step:
                        _ckpt.save_sharded(checkpoint_dir, global_step,
                                           params, moms, aux)
                        last_saved = global_step
                    # (re)write the meta to point at the NEXT epoch's first
                    # batch — a periodic save at the epoch's last batch
                    # would otherwise resume into a fully-skipped epoch
                    _ckpt.save_fit_meta(checkpoint_dir, global_step,
                                        fit_meta(epoch + 1, 0))
                else:
                    _ckpt.save_sharded(checkpoint_dir, epoch + 1, params,
                                       moms, aux)
                    _ckpt.save_fit_meta(checkpoint_dir, epoch + 1,
                                        fit_meta(epoch + 1, 0))
                _attr.sample_memory()
                # out-of-step badput: the epoch-end save happens outside
                # any step window
                led.bad("checkpoint", _time.monotonic() - t_ck)
        led.close(_time.monotonic() - t_fit)
        return (params, moms, aux), history

    def _fit_kvstore(self, kv, train_data, eval_data=None, num_epoch=1,
                     seed=0, eval_metric="accuracy", initializer=None,
                     state=None, begin_epoch=0, checkpoint_dir=None,
                     checkpoint_every=None, resume=None, log_every=50,
                     logger=None, batch_end_callback=None, roster=None):
        """Parameter-server-backed loop behind ``fit(kvstore=)``: local
        gradients (``grad_fn``) pushed to the kvstore, whose server-side
        optimizer owns weights and state; fresh weights pulled back each
        step.  Requires the caller to have called ``kv.set_optimizer``.

        With ``roster=`` the batch loop becomes elastic: each global
        batch index runs on the rank ``roster.owns`` says, membership
        re-read per batch so a join/drain re-balances mid-epoch, and
        ``mark_progress``/``resume_point`` give a joining rank the
        iterator fast-forward (see :meth:`fit`)."""
        import logging

        import jax as _jax

        from .. import metric as _metric_mod
        from ..callback import Speedometer
        from ..io import batch_arrays as _io_batch_arrays
        from ..model import BatchEndParam
        from ..ndarray import NDArray

        if self.pipeline_steps != 1 or self.grad_accum != 1:
            raise MXNetError(
                "kvstore-backed fit pushes one gradient per step: "
                "pipeline_steps and grad_accum must both be 1 (the server "
                "applies updates per arriving push)")
        if self._skip_nonfinite:
            raise MXNetError(
                "skip_nonfinite guards the fused LOCAL update; with "
                "kvstore= the optimizer runs server-side where the verdict "
                "cannot gate it — not supported")
        if checkpoint_dir is not None or checkpoint_every or resume:
            raise MXNetError(
                "kvstore-backed fit: weights and optimizer state live on "
                "the parameter server (replicated shards are the "
                "durability story) — checkpoint_dir/checkpoint_every/"
                "resume are not supported here")

        log = logger or logging.getLogger(__name__)
        metric = (eval_metric
                  if isinstance(eval_metric, _metric_mod.EvalMetric)
                  else _metric_mod.create(eval_metric))
        params, moms, aux = (state if state is not None
                             else self.init(initializer=initializer,
                                            seed=seed))
        diff = [n for n in self.param_names if n in self._diff_set]
        # seed the server: rank-0-wins first-writer semantics, so every
        # worker calling this converges on one initial state
        kv.init(diff, [NDArray(jnp.asarray(params[n])) for n in diff])
        # pull buffers reused across steps (pull writes them in place)
        bufs = [NDArray(jnp.asarray(params[n])) for n in diff]
        kv.pull(diff, out=bufs)
        pshard = {n: self._sharding(self.param_specs[n]) for n in diff}
        for n, b in zip(diff, bufs):
            params[n] = jax.device_put(
                jnp.asarray(b._data).astype(self._param_dtype(n)),
                pshard[n])
        _mem.tag_tree("params", id(self), (params, aux))
        _mem.tag_tree("optimizer", id(self), moms)
        gradf = self.grad_fn()
        fwd = self.forward_fn()

        def batch_arrays(batch, it):
            return _io_batch_arrays(batch, it, self._input_names)

        callbacks = (list(batch_end_callback)
                     if isinstance(batch_end_callback, (list, tuple))
                     else [batch_end_callback] if batch_end_callback
                     else [])
        speedo = None
        history = {}
        global_step = 0
        base_key = _jax.random.fold_in(_jax.random.PRNGKey(seed),
                                       begin_epoch)
        end_epoch = begin_epoch + num_epoch
        # same step-latency families the local paths feed — one dashboard
        # regardless of where the optimizer runs; the kv phase (absent
        # from the local paths) is where this loop earns its breakdown
        _m_step = _metrics.histogram(
            "trainer_step_seconds",
            "Optimizer-step wall time seen by the fit loop; pipelined "
            "flushes are amortized over their K fused steps")
        _m_steps = _metrics.counter("trainer_steps_total",
                                    "Optimizer steps applied by fit")
        _m_tokens = _metrics.gauge(
            "trainer_tokens_per_sec",
            "Training throughput (batch rows per second) of the most "
            "recent step or flush")
        # goodput ledger: RPC retry/backoff and failover seconds booked by
        # the kvstore client surface here as badput counter deltas
        led = _eff.ledger()
        t_fit = _time.monotonic()
        my_rank = getattr(kv, "rank", 0)
        for epoch in range(begin_epoch, end_epoch):
            metric.reset()
            train_data.reset()
            nbatch = 0
            bidx = -1
            for batch in train_data:
                bidx += 1
                if roster is not None:
                    if (epoch, bidx) < roster.resume_point():
                        # the group already covered this batch before we
                        # joined — fast-forward, never re-apply it
                        continue
                    if not roster.owns(my_rank, bidx):
                        roster.mark_progress(epoch, bidx + 1)
                        continue
                att = _attr.attributor()
                t_step = _time.monotonic()
                arrays, data_names = batch_arrays(batch, train_data)
                with att.phase("placement"):
                    placed = self.place_batch(arrays)
                with att.phase("compute"):
                    outs, grads, aux = gradf(
                        params, aux, placed,
                        _jax.random.fold_in(base_key, global_step))
                with att.phase("kv"):
                    # the push may ride out a shard failover internally
                    # (promote + same-seq retry); only whole-group loss
                    # escapes, as ShardFailedError.  push_pull fuses the
                    # step's two flushes into one RPC per shard on
                    # dist_async (falling back to push();pull() on every
                    # other mode or with coalescing off)
                    if hasattr(kv, "push_pull"):
                        kv.push_pull(diff,
                                     [NDArray(grads[n]) for n in diff],
                                     out=bufs)
                    else:
                        kv.push(diff, [NDArray(grads[n]) for n in diff])
                        kv.pull(diff, out=bufs)
                with att.phase("placement"):
                    # accumulates onto the batch placement above: both
                    # are host->device transfers on the step's critical
                    # path
                    for n, b in zip(diff, bufs):
                        params[n] = jax.device_put(
                            jnp.asarray(b._data).astype(
                                self._param_dtype(n)),
                            pshard[n])
                global_step += 1
                nbatch += 1
                if roster is not None:
                    roster.mark_progress(epoch, bidx + 1)
                with att.phase("flush"):
                    labels = [v for n, v in arrays.items()
                              if n not in data_names]
                    metric.update([_np.asarray(v) for v in labels],
                                  [_np.asarray(o) for o in outs])
                dt = _time.monotonic() - t_step
                led.step(dt, att.close(dt))
                _m_step.observe(dt)
                _m_steps.inc()
                _eff.record_step_rate(1, dt)
                if dt > 0:
                    _m_tokens.set(
                        next(iter(arrays.values())).shape[0] / dt)
                if speedo is None and log_every:
                    speedo = Speedometer(
                        next(iter(arrays.values())).shape[0],
                        frequent=log_every)
                bep = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                    eval_metric=metric, locals=None)
                if speedo is not None:
                    speedo(bep._replace(eval_metric=None))
                for cb in callbacks:
                    cb(bep)
            history.setdefault(epoch, {})["train"] = metric.get()
            log.info("epoch %d train: %s", epoch, history[epoch]["train"])
            if eval_data is not None:
                metric.reset()
                eval_data.reset()
                for batch in eval_data:
                    arrays, data_names = batch_arrays(batch, eval_data)
                    placed = self.place_batch(arrays, train=False)
                    outs = fwd(params, aux, placed, _jax.random.PRNGKey(0))
                    labels = [v for n, v in arrays.items()
                              if n not in data_names]
                    metric.update([_np.asarray(v) for v in labels],
                                  [_np.asarray(o) for o in outs])
                history[epoch]["eval"] = metric.get()
                log.info("epoch %d eval: %s", epoch,
                         history[epoch]["eval"])
        led.close(_time.monotonic() - t_fit)
        return (params, moms, aux), history

    def fit_stream(self, train_data, seed=0, max_steps=None,
                   checkpoint_dir=None, checkpoint_every=100,
                   checkpoint_every_s=None, resume=None,
                   initializer=None, state=None, max_bad_steps=5,
                   retries=None, backoff_s=None, stall_timeout=None,
                   skip_on_error=False, log_every=0, logger=None,
                   batch_end_callback=None):
        """Online learning: consume an UNBOUNDED iterator (e.g. a
        ``loop=True`` :class:`~mxnet_tpu.stream.StreamDataIter`),
        checkpointing every ``checkpoint_every`` steps and/or every
        ``checkpoint_every_s`` seconds — the producer side of the
        continuous-training loop (``deployd`` is the consumer).

        There are no epochs: the loop runs until ``max_steps``
        optimizer steps land (``None`` = forever), pulling
        feeder-staged chunks whose decode runs on the engine IO lane.
        Every checkpoint's meta sidecar carries the stream iterator's
        serialized cursor, so ``resume="auto"`` continues **bitwise**
        from the last saved step: same records, same shuffle order,
        same per-step RNG keys.

        Failure contract (never a silent hang):

        - a stalled source surfaces as a typed
          :class:`~mxnet_tpu.base.StreamStallError` after
          ``stall_timeout`` seconds (default
          ``MXNET_TPU_PREFETCH_STALL_S``), is retried with exponential
          backoff up to ``retries`` times (default
          ``MXNET_TPU_STREAM_RETRIES``, backoff base
          ``MXNET_TPU_STREAM_BACKOFF_S``), each stall counted in
          ``stream_stalls_total`` — the watchdog's ``stream_stall``
          rule fires on a sustained run of them — and the final miss
          re-raises;
        - a truncated/garbled source surfaces as
          ``CorruptMessageError``; with ``skip_on_error=True`` the bad
          chunk is counted (``stream_skipped_total``) and skipped
          (feeder reset, stream keeps moving), bounded by
          ``max_bad_steps`` consecutive losses;
        - a cleanly-ending finite iterator just ends the loop.

        Returns ``((params, moms, aux), info)`` where ``info`` has
        ``steps``/``global_step``/``stalls``/``skipped``/
        ``last_checkpoint``.  A terminal escape is flight-recorded
        (``trainer.fit_stream``)."""
        try:
            return self._fit_stream_impl(
                train_data, seed=seed, max_steps=max_steps,
                checkpoint_dir=checkpoint_dir,
                checkpoint_every=checkpoint_every,
                checkpoint_every_s=checkpoint_every_s, resume=resume,
                initializer=initializer, state=state,
                max_bad_steps=max_bad_steps, retries=retries,
                backoff_s=backoff_s, stall_timeout=stall_timeout,
                skip_on_error=skip_on_error, log_every=log_every,
                logger=logger, batch_end_callback=batch_end_callback)
        except Exception as exc:
            from ..observability import flight_recorder as _flight

            _flight.record_failure("trainer.fit_stream", exc)
            raise

    def _fit_stream_impl(self, train_data, seed=0, max_steps=None,
                         checkpoint_dir=None, checkpoint_every=100,
                         checkpoint_every_s=None, resume=None,
                         initializer=None, state=None, max_bad_steps=5,
                         retries=None, backoff_s=None, stall_timeout=None,
                         skip_on_error=False, log_every=0, logger=None,
                         batch_end_callback=None):
        import logging
        import os as _os

        import jax as _jax

        from .. import observability as _obs
        from ..base import CorruptMessageError, StreamStallError
        from ..io import batch_arrays as _io_batch_arrays
        from ..model import BatchEndParam
        from . import checkpoint as _ckpt
        from . import prefetch as _prefetch

        log = logger or logging.getLogger(__name__)
        if checkpoint_every is not None:
            checkpoint_every = int(checkpoint_every)
            if checkpoint_every < 1:
                raise MXNetError("checkpoint_every must be >= 1")
        if checkpoint_dir is None:
            # no directory = no checkpointing (checkpoint_every keeps
            # its default so callers opting IN only pass the dir)
            checkpoint_every = None
            checkpoint_every_s = None
        if retries is None:
            try:
                retries = int(_os.environ.get(
                    "MXNET_TPU_STREAM_RETRIES", "5") or 5)
            except ValueError:
                retries = 5
        if backoff_s is None:
            try:
                backoff_s = float(_os.environ.get(
                    "MXNET_TPU_STREAM_BACKOFF_S", "0.05") or 0.05)
            except ValueError:
                backoff_s = 0.05

        # -- resume="auto": the fit ladder, stream cursor included -------
        resume_meta = None
        if resume not in (None, False, "auto"):
            raise MXNetError("resume must be None or 'auto', got %r"
                             % (resume,))
        if resume == "auto" and checkpoint_dir is not None:
            from .. import durable as _durable
            from ..base import CheckpointCorruptError as _CkptCorrupt

            _state_in = state  # restored on every fallback hop
            for ckpt_step in reversed(_ckpt.all_steps(checkpoint_dir)):
                try:
                    verified = _ckpt.verify_checkpoint(checkpoint_dir,
                                                       ckpt_step)
                    state = _ckpt.restore_sharded(checkpoint_dir,
                                                  ckpt_step, trainer=self)
                    resume_meta = _ckpt.load_fit_meta(checkpoint_dir,
                                                      ckpt_step)
                except _CkptCorrupt as exc:
                    state = _state_in
                    _durable.quarantine(
                        "checkpoint", exc, step=int(ckpt_step),
                        directory=str(checkpoint_dir),
                        file=getattr(exc, "file", None))
                    log.warning(
                        "resume: checkpoint step %d failed integrity "
                        "verification (%s); falling back to the previous "
                        "checkpoint", ckpt_step, exc)
                    continue
                except Exception as exc:  # noqa: BLE001 — fall back a step
                    log.warning(
                        "resume: checkpoint step %d failed validation "
                        "(%r); falling back to the previous checkpoint",
                        ckpt_step, exc)
                    continue
                if resume_meta is None and verified:
                    # manifest-era step with no sidecar: the save was
                    # killed between shard and meta writes — fall back
                    state = _state_in
                    log.warning(
                        "resume: checkpoint step %d has a manifest but no "
                        "fit-meta sidecar (save killed mid-write); falling "
                        "back to the previous checkpoint", ckpt_step)
                    continue
                log.info("resume: restored checkpoint step %d", ckpt_step)
                break
            else:
                log.info("resume: no restorable checkpoint under %r — "
                         "starting fresh", checkpoint_dir)

        params, moms, aux = (state if state is not None
                             else self.init(initializer=initializer,
                                            seed=seed))
        _mem.tag_tree("params", id(self), (params, aux))
        _mem.tag_tree("optimizer", id(self), moms)
        if resume_meta is not None:
            global_step = int(resume_meta.get("global_step", 0))
            rng_seed = int(resume_meta.get("seed", seed))
            rng_anchor = int(resume_meta.get("base_epoch", 0))
        else:
            global_step = 0
            rng_seed = seed
            rng_anchor = 0
        base_key = _jax.random.fold_in(_jax.random.PRNGKey(rng_seed),
                                       rng_anchor)
        streamable = (hasattr(train_data, "state")
                      and hasattr(train_data, "load_state"))
        if (streamable and resume_meta is not None
                and resume_meta.get("stream") is not None):
            train_data.load_state(resume_meta["stream"])
        stream_state = [train_data.state() if streamable else None]

        def fit_meta():
            meta = {"global_step": global_step,
                    "epoch": (stream_state[0] or {}).get("epoch", 0),
                    "batch_in_epoch": 0, "seed": rng_seed,
                    "base_epoch": rng_anchor, "mode": "stream"}
            if stream_state[0] is not None:
                meta["stream"] = stream_state[0]
            return meta

        K = self.pipeline_steps
        stop_at = None if max_steps is None else global_step + int(max_steps)
        planned = [global_step]

        def plan_size():
            # every flush END lands on a checkpoint boundary and never
            # overshoots the stop step (extra read-ahead is harmless:
            # the watermark advances only with consumed batches)
            k = K
            if checkpoint_every:
                k = min(k, checkpoint_every - planned[0] % checkpoint_every)
            if stop_at is not None:
                k = max(min(k, stop_at - planned[0]), 1)
            planned[0] += k
            return k

        def extract(b):
            arrays, names = _io_batch_arrays(b, train_data,
                                             self._input_names)
            return (arrays, names,
                    train_data.state() if streamable else None)

        callbacks = (list(batch_end_callback)
                     if isinstance(batch_end_callback, (list, tuple))
                     else [batch_end_callback] if batch_end_callback
                     else [])
        _m_step = _obs.histogram(
            "trainer_step_seconds",
            "Optimizer-step wall time seen by the fit loop; pipelined "
            "flushes are amortized over their K fused steps")
        _m_steps = _obs.counter("trainer_steps_total",
                                "Optimizer steps applied by fit")
        led = _eff.ledger()
        t_fit = _time.monotonic()
        guard = self._skip_nonfinite
        steps_done = stalls = skipped = 0
        bad_streak = corrupt_streak = 0
        last_saved = None
        last_save_t = _time.monotonic()

        with _obs.span("trainer.stream_prefetch_start"):
            feeder = _prefetch.PrefetchFeeder(
                iter(train_data), extract=extract,
                place=lambda host: self.place_superbatch(
                    [h[0] for h in host]),
                sizes=plan_size, depth=2, name="fit_stream.prefetch")
        try:
            while stop_at is None or global_step < stop_at:
                att = _attr.attributor()
                t_flush = _time.monotonic()
                attempt = 0
                while True:
                    try:
                        with att.phase("data_wait"):
                            chunk = feeder.next_chunk(
                                timeout=stall_timeout)
                        corrupt_streak = 0
                        break
                    except StreamStallError:
                        stalls += 1
                        _M_STREAM_STALLS.inc()
                        attempt += 1
                        if attempt > retries:
                            raise StreamStallError(
                                "stream source stalled: %d consecutive "
                                "next_chunk timeouts at global step %d "
                                "(retries=%d exhausted)"
                                % (attempt, global_step, retries))
                        delay = min(backoff_s * (2 ** (attempt - 1)), 5.0)
                        log.warning(
                            "stream stall at global step %d (attempt "
                            "%d/%d) — backing off %.3fs", global_step,
                            attempt, retries, delay)
                        led.bad("data_wait", delay)
                        _time.sleep(delay)
                    except CorruptMessageError:
                        if not skip_on_error:
                            raise
                        skipped += 1
                        corrupt_streak += 1
                        _M_STREAM_SKIPPED.inc()
                        if corrupt_streak > max_bad_steps:
                            raise
                        log.warning(
                            "corrupt stream chunk at global step %d — "
                            "skipped and counted (%d consecutive)",
                            global_step, corrupt_streak)
                        feeder.reset()
                if chunk is None:
                    break  # finite source ended cleanly
                n = chunk.count
                with _obs.span("trainer.stream_flush", step=global_step):
                    with att.phase("compute"):
                        outs_stack, params, moms, aux = \
                            self.pipeline_fn(n)(
                                params, moms, aux, chunk.placed,
                                base_key, _np.int32(global_step))
                verdicts = None
                with att.phase("flush"):
                    if guard:
                        verdicts = _np.asarray(outs_stack[-1])
                        outs_stack = outs_stack[:-1]
                for j in range(n):
                    if streamable:
                        stream_state[0] = chunk.host[j][2]
                    ok = True if verdicts is None else bool(verdicts[j])
                    global_step += 1
                    steps_done += 1
                    if ok:
                        bad_streak = 0
                    else:
                        bad_streak += 1
                        if bad_streak >= max_bad_steps:
                            raise MXNetError(
                                "aborting fit_stream: %d consecutive "
                                "non-finite steps (last at global step "
                                "%d)" % (bad_streak, global_step - 1))
                    for cb in callbacks:
                        cb(BatchEndParam(epoch=0, nbatch=global_step,
                                         eval_metric=None, locals=None))
                    due_n = (checkpoint_every
                             and global_step % checkpoint_every == 0)
                    due_t = (checkpoint_every_s is not None
                             and _time.monotonic() - last_save_t
                             >= checkpoint_every_s)
                    if (j == n - 1 and checkpoint_dir is not None
                            and (due_n or due_t)):
                        with att.phase("checkpoint"):
                            _ckpt.save_sharded(checkpoint_dir,
                                               global_step, params,
                                               moms, aux)
                            _ckpt.save_fit_meta(checkpoint_dir,
                                                global_step, fit_meta())
                        last_saved = global_step
                        last_save_t = _time.monotonic()
                        _attr.sample_memory()
                dt = _time.monotonic() - t_flush
                led.step(dt, att.close(dt))
                _m_steps.inc(n)
                for _ in range(n):
                    _m_step.observe(dt / n)
                _eff.record_step_rate(n, dt)
                if log_every and steps_done % max(int(log_every), 1) == 0:
                    log.info("fit_stream: %d steps (global %d), "
                             "%d stalls, %d skipped", steps_done,
                             global_step, stalls, skipped)
        finally:
            feeder.close()
        if checkpoint_dir is not None and last_saved != global_step:
            # the exit checkpoint: deployd's next scan sees the final
            # state even when the loop stopped off the periodic boundary
            _ckpt.save_sharded(checkpoint_dir, global_step, params,
                               moms, aux)
            _ckpt.save_fit_meta(checkpoint_dir, global_step, fit_meta())
            last_saved = global_step
        led.close(_time.monotonic() - t_fit)
        return (params, moms, aux), {
            "steps": steps_done, "global_step": global_step,
            "stalls": stalls, "skipped": skipped,
            "last_checkpoint": last_saved}

    def _with_mesh(self, jitted):
        """Call `jitted` with this trainer's mesh ambient, so mesh-aware ops
        trace against the right mesh no matter which trainer traced last."""
        from . import default_mesh

        def call(*args, **kwargs):
            with default_mesh(self.mesh):
                return jitted(*args, **kwargs)

        return call


class _HostArray:
    """Minimal NDArray stand-in so initializer patterns run on numpy buffers."""

    def __init__(self, arr):
        self._arr = arr

    @property
    def shape(self):
        return self._arr.shape

    def __setitem__(self, key, value):
        self._arr[key] = value

    def asnumpy(self):
        return self._arr
