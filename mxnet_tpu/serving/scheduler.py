"""Continuous-batching scheduler: the serving tier's dispatch engine.

Classic batched serving gates on a *full* batch — latency is hostage
to the slowest co-arrival.  Continuous batching (Orca, OSDI '22;
vLLM's scheduler, SOSP '23) inverts that: a dispatch loop per model
pulls **whatever is waiting** the moment the device frees up, pads the
pack to the smallest configured bucket, and runs it.  Requests admitted
while a batch is on the device ride the *next* window — slots free
continuously, nothing waits for stragglers.

Why buckets: each bucket is one shape key in the Predictor's executor
cache, so after one warm pass per bucket steady-state serving performs
**zero recompiles** — the same pad-to-bucket trick the training stack
uses, applied to live traffic.  ``serving_compiles_total{model}``
counts cold buckets; a flat counter after :meth:`Scheduler.warmup` is
the tested contract (``tests/test_serving.py``).

Lifecycle verbs map to production events:

- :meth:`Scheduler.drain` — rolling restart: stop admitting, finish
  everything accepted.
- :meth:`Scheduler.kill` — crash simulation: queued and in-flight
  requests fail with :class:`~.admission.ReplicaDeadError` so a
  router (``replication.py``) can retry them on a peer.  Accepted
  requests are never silently dropped.
- :meth:`Scheduler.fence` — the PR-3 epoch fence: a zombie replica
  that lost its membership epoch refuses new work.

**Multi-tenant fairness** (PR-16): every lane's queue is a
:class:`~.tenancy.FairQueue` — deficit round-robin over per-tenant
FIFO queues, weights from ``MXNET_TPU_TENANT_WEIGHTS`` (or the model's
``tenant_weights`` registration override).  Under contention a
tenant's share of every dispatch window converges to its weight; a
single-tenant lane short-circuits to the plain FIFO it always was.
Admission additionally charges the tenant's token buckets
(:class:`~.tenancy.TenantPolicy`): an exhausted budget sheds with the
typed 429 :class:`~.admission.QuotaExceededError` naming the budget
and carrying the bucket's refill time.  Successful answers are booked
per tenant in ``serving_tenant_requests_total{model,tenant}`` — the
good-counter behind per-tenant SLO error budgets
(``observability/slo.py``).

Chaos sites ``serving.admit`` (in :meth:`submit`, before the queue
lock) and ``serving.dispatch`` (inside the dispatch window, before the
device call) let seeded drills inject shed/delay/crash at both doors.
Dispatch faults are retried ``MXNET_TPU_SERVING_RETRIES`` times on the
same replica before the failure lands on the request futures.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as _np

from .. import chaos
from ..base import MXNetError
from ..observability import metrics as _metrics
from ..observability import tracing as _tracing
from . import admission as _admission
from . import tenancy as _tenancy
from .registry import ModelRegistry

__all__ = ["InferenceRequest", "Scheduler", "default_retries"]


def default_retries():
    """``MXNET_TPU_SERVING_RETRIES``: same-replica dispatch retries
    before a fault is surfaced to the request futures."""
    try:
        return int(os.environ.get("MXNET_TPU_SERVING_RETRIES", "2"))
    except ValueError:
        return 2


class InferenceRequest(object):
    """One admitted request: a future the dispatch loop resolves.

    ``result()`` blocks the submitting thread; the scheduler's dispatch
    thread calls ``_resolve``/``_fail`` exactly once.  ``latency_s``
    (admission -> resolution) feeds ``serving_request_seconds``.
    ``trace`` is the submitter's wire token (the request's root span
    context) — the dispatch loop parents this request's queue-wait span
    under it and lists it in the batch span's fan-in links.
    """

    __slots__ = ("model", "inputs", "deadline", "tenant", "t_admit",
                 "_event", "outputs", "error", "latency_s", "trace",
                 "_h_tenant")

    def __init__(self, model, inputs, deadline,
                 tenant=_tenancy.DEFAULT_TENANT):
        self.model = model
        self.inputs = inputs
        self.deadline = deadline
        self.tenant = tenant
        self.t_admit = time.monotonic()
        self._event = threading.Event()
        self.outputs = None
        self.error = None
        self.latency_s = None
        self.trace = None
        # pre-resolved serving_tenant_requests_total{model,tenant}
        # handle (attached at submit, None with metrics disabled) so
        # the dispatch loop never resolves labels
        self._h_tenant = None

    @property
    def done(self):
        return self._event.is_set()

    def _resolve(self, outputs):
        self.latency_s = time.monotonic() - self.t_admit
        self.outputs = outputs
        self._event.set()

    def _fail(self, error):
        self.latency_s = time.monotonic() - self.t_admit
        self.error = error
        self._event.set()

    def result(self, timeout=30.0):
        """Block for the response; re-raises the typed serving error on
        failure (deadline, overload-requeue exhaustion, dead replica)."""
        if not self._event.wait(timeout):
            raise MXNetError("request to model %r timed out after %.1fs "
                             "(still queued or in flight)"
                             % (self.model, timeout))
        if self.error is not None:
            raise self.error
        return self.outputs


class _Lane(object):
    """Per-model queue + its dispatch thread + pre-resolved metric
    handles (label resolution off the hot path)."""

    __slots__ = ("entry", "queue", "thread", "batches", "rows", "slots",
                 "tenant_handles",
                 "m_req", "m_wait", "m_depth", "m_sat", "m_occ",
                 "m_requests", "m_batches", "m_compiles", "m_errors")

    def __init__(self, entry, weight_fn=None):
        self.entry = entry
        self.queue = _tenancy.FairQueue(weight_fn)
        self.thread = None
        # per-tenant success-counter handles, resolved lazily at submit
        # (never in the dispatch loop)
        self.tenant_handles = {}
        # running totals for bench occupancy (rows served / slots run)
        self.batches = 0
        self.rows = 0
        self.slots = 0


class Scheduler(object):
    """Continuous-batching scheduler for one serving replica.

    Parameters
    ----------
    registry : ModelRegistry, optional
        Shared model registry; a private one is created by default.
    metrics_registry : observability.metrics.Registry, optional
        Where serving metrics live.  Defaults to the process-global
        registry; replica groups pass per-replica registries so the
        federated exposition shows each replica under its own
        ``{shard, role, epoch}`` identity.
    name : str
        Replica name (membership + error messages).
    tenant_policy : tenancy.TenantPolicy, optional
        Per-tenant WFQ weights + quota buckets.  A replica group passes
        ONE policy to every replica so a tenant's budget bounds the
        tenant, not tenant × replicas; defaults to a private policy
        built from the ``MXNET_TPU_TENANT_*`` env rows.
    """

    def __init__(self, registry=None, metrics_registry=None,
                 name="serving0", tenant_policy=None):
        self.name = name
        self.registry = registry if registry is not None else ModelRegistry()
        self._reg = (metrics_registry if metrics_registry is not None
                     else _metrics.REGISTRY)
        # shared across a replica group so quotas bound the TENANT, not
        # tenant-times-replicas; a private policy otherwise
        self.tenants = (tenant_policy if tenant_policy is not None
                        else _tenancy.TenantPolicy())
        self.admission = _admission.AdmissionController(
            reject_counter=self._reg.counter(
                "serving_rejected_total", _admission.REJECTED_HELP,
                _admission.REJECTED_LABELS))
        self._fam = self._families(self._reg)
        self._cond = threading.Condition()
        self._lanes = {}
        self._stopping = False
        self._killed = False
        self._fenced_epoch = None
        self.epoch = 0
        # dispatch loops beat this; a stale beat is how the replica
        # group detects a dead replica (replication.py)
        self.last_beat = time.monotonic()

    @staticmethod
    def _families(reg):
        return {
            "req": reg.histogram(
                "serving_request_seconds",
                "End-to-end request latency, admission to response",
                ["model"]),
            "wait": reg.histogram(
                "serving_queue_wait_seconds",
                "Time a request waited in its model lane before dispatch",
                ["model"]),
            "depth": reg.gauge(
                "serving_queue_depth",
                "Requests currently queued per model lane", ["model"]),
            "sat": reg.gauge(
                "serving_queue_saturation",
                "Queue depth / max_queue per model lane (1.0 = shedding)",
                ["model"]),
            "occ": reg.gauge(
                "serving_batch_occupancy",
                "Live rows / bucket slots of the last dispatched batch",
                ["model"]),
            "requests": reg.counter(
                "serving_requests_total",
                "Requests answered successfully per model", ["model"]),
            "batches": reg.counter(
                "serving_batches_total",
                "Device dispatch windows run per model", ["model"]),
            "compiles": reg.counter(
                "serving_compiles_total",
                "Cold (compiling) buckets per model; flat after warmup",
                ["model"]),
            "errors": reg.counter(
                "serving_dispatch_errors_total",
                "Dispatch attempts that raised (chaos or backend fault)",
                ["model"]),
            "tenant_req": reg.counter(
                "serving_tenant_requests_total",
                "Requests answered successfully per model and tenant "
                "(the per-tenant SLO good-counter)",
                ["model", "tenant"]),
        }

    # -- registration -------------------------------------------------

    def _weight_fn(self, entry):
        """The lane's DRR weight lookup: per-model registration
        overrides first, then the shared tenant policy."""
        overrides = entry.tenant_weights
        policy = self.tenants

        def weight(tenant):
            w = overrides.get(tenant)
            return policy.weight(tenant) if w is None else float(w)
        return weight

    def register(self, name, backend, buckets=None, max_queue=None,
                 tenant_weights=None):
        """Register a model and start its dispatch thread.  Accepts
        anything :func:`~.registry.as_backend` does.  ``tenant_weights``
        optionally overrides the policy's WFQ weights for this model."""
        entry = self.registry.register(name, backend, buckets=buckets,
                                       max_queue=max_queue,
                                       tenant_weights=tenant_weights)
        lane = _Lane(entry, weight_fn=self._weight_fn(entry))
        for key, attr in (("req", "m_req"), ("wait", "m_wait"),
                          ("depth", "m_depth"), ("sat", "m_sat"),
                          ("occ", "m_occ"), ("requests", "m_requests"),
                          ("batches", "m_batches"),
                          ("compiles", "m_compiles"),
                          ("errors", "m_errors")):
            setattr(lane, attr, self._fam[key].labels(name))
        with self._cond:
            self._lanes[name] = lane
        lane.thread = threading.Thread(
            target=self._loop, args=(name, lane),
            name="%s-dispatch-%s" % (self.name, name), daemon=True)
        lane.thread.start()
        return entry

    def swap(self, name, backend):
        """Hot reload: atomically swap ``name``'s backend between
        dispatch windows (see :meth:`~.registry.ModelRegistry.swap`)."""
        return self.registry.swap(name, backend)

    def warmup(self, name):
        """Pre-bind every bucket of ``name`` so live traffic never sees
        a compile.  Returns the number of cold buckets visited."""
        lane = self._lane(name)
        entry = lane.entry
        cold_n = 0
        with entry.dispatch_lock:
            for bucket in entry.buckets:
                batch = {n: _np.zeros((bucket,) + tuple(s),
                                      dtype=_np.float32)
                         for n, s in entry.backend.input_shapes.items()}
                _, cold = entry.backend.infer(batch)
                if cold:
                    cold_n += 1
                    if _metrics.metrics_enabled():
                        lane.m_compiles.inc()
        return cold_n

    # -- admission ----------------------------------------------------

    def _lane(self, name):
        with self._cond:
            lane = self._lanes.get(name)
        if lane is None:
            # registry.get raises the typed UnknownModelError (404)
            self.registry.get(name)
            raise _admission.UnknownModelError(
                "model %r has no dispatch lane" % (name,))
        return lane

    def _check_inputs(self, entry, inputs):
        rows = {}
        want = entry.backend.input_shapes
        for n, shape in want.items():
            if n not in inputs:
                raise MXNetError("request missing input %r (model wants "
                                 "%s)" % (n, sorted(want)))
            row = _np.asarray(inputs[n], dtype=_np.float32)
            if tuple(row.shape) != tuple(shape):
                raise MXNetError(
                    "input %r: got shape %r, model serves per-sample "
                    "shape %r" % (n, tuple(row.shape), tuple(shape)))
            rows[n] = row
        extra = set(inputs) - set(want)
        if extra:
            raise MXNetError("unknown inputs %r (model wants %s)"
                             % (sorted(extra), sorted(want)))
        return rows

    def submit(self, name, inputs, deadline_ms=None, force=False,
               tenant=None):
        """Admit one request; returns its :class:`InferenceRequest`
        future.  ``force=True`` bypasses overload/drain/quota shedding —
        used by the router to re-admit a request that a DEAD peer had
        already accepted (accepted work is never shed twice); kill and
        fencing still refuse.  ``tenant`` labels the request for WFQ,
        quotas and per-tenant accounting (None = ``default``).

        A typed rejection closes a terminal ``serving.shed`` span tagged
        with the reject reason, parented under the submitter's current
        span (the frontend's ``serving.request`` root)."""
        tenant = _tenancy.clean_tenant(tenant)
        try:
            return self._submit(name, inputs, deadline_ms, force, tenant)
        except _admission.ServingError as exc:
            if _tracing.tracing_enabled():
                _tracing.record_span(
                    "serving.shed", cat="serving", model=name,
                    reason=_admission.reject_reason(exc) or "error",
                    tenant=tenant, error=type(exc).__name__)
            raise

    def _submit(self, name, inputs, deadline_ms, force, tenant):
        if self._killed or self._fenced_epoch is not None:
            raise _admission.ReplicaDeadError(
                "replica %r is %s" % (self.name,
                                      "fenced at epoch %r" % self._fenced_epoch
                                      if self._fenced_epoch is not None
                                      else "dead"))
        lane = self._lane(name)
        rows = self._check_inputs(lane.entry, inputs)
        deadline = _admission.deadline_from_ms(deadline_ms)
        req = InferenceRequest(name, rows, deadline, tenant)
        # the submitter's context (e.g. the frontend root span) is this
        # request's identity in the trace: queue-wait spans parent under
        # it and the batch span lists it as a fan-in link
        req.trace = _tracing.capture_wire_context()
        with _tracing.span("serving.admit", cat="serving", model=name,
                           tenant=tenant):
            # chaos fires OUTSIDE the queue lock: an injected delay
            # stalls this caller, not every lane's dispatch loop
            chaos.visit("serving.admit", name=name)
            with self._cond:
                if self._stopping and not force:
                    self.admission.reject(name, "draining", tenant=tenant)
                if not force:
                    self.admission.admit(name, len(lane.queue),
                                         lane.entry.max_queue, deadline,
                                         tenant=tenant)
                    # token-bucket quota AFTER the door checks, so a
                    # request the lane would shed anyway never burns
                    # budget; unlimited tenants short-circuit inside
                    over = self.tenants.charge(tenant)
                    if over is not None:
                        self.admission.quota_reject(name, tenant, *over)
                lane.queue.push(tenant, req)
                if _metrics.metrics_enabled():
                    depth = len(lane.queue)
                    lane.m_depth.set(depth)
                    lane.m_sat.set(depth / float(lane.entry.max_queue))
                    h = lane.tenant_handles.get(tenant)
                    if h is None:
                        h = lane.tenant_handles[tenant] = \
                            self._fam["tenant_req"].labels(name, tenant)
                    req._h_tenant = h
                self._cond.notify_all()
        return req

    def request(self, name, inputs, deadline_ms=None, timeout=30.0,
                tenant=None):
        """Synchronous convenience: :meth:`submit` + ``result()``."""
        return self.submit(name, inputs, deadline_ms=deadline_ms,
                           tenant=tenant).result(timeout=timeout)

    # -- dispatch loop ------------------------------------------------

    def _loop(self, name, lane):
        while True:
            # racy-by-design liveness timestamp: any lane thread bumping
            # it is fresh enough for the straggler-detect sweep
            self.last_beat = time.monotonic()  # graftcheck: disable=lock-discipline
            with self._cond:
                while (not lane.queue and not self._killed
                       and not self._stopping):
                    self._cond.wait(0.05)
                    self.last_beat = time.monotonic()
                if self._killed:
                    return
                if not lane.queue:
                    # stopping with an empty queue: done
                    return
                # DRR window: each tenant's share of the pack converges
                # to its weight under contention (tenancy.FairQueue)
                window = lane.queue.take(lane.entry.buckets[-1])
                if _metrics.metrics_enabled():
                    depth = len(lane.queue)
                    lane.m_depth.set(depth)
                    lane.m_sat.set(depth / float(lane.entry.max_queue))
            self._dispatch(name, lane, window)

    def _dispatch(self, name, lane, window):
        now = time.monotonic()
        traced = _tracing.tracing_enabled()
        live = []
        for req in window:
            # second deadline check: expired while queued -> shed
            # BEFORE costing device time
            if _admission.AdmissionController.expired(req.deadline, now):
                self.admission.account(name, "deadline", req.tenant)
                if traced:
                    _tracing.record_span(
                        "serving.shed", cat="serving",
                        start_us=int(req.t_admit * 1e6),
                        end_us=int(now * 1e6), parent=req.trace,
                        model=name, reason="deadline",
                        error="DeadlineExceededError")
                req._fail(_admission.DeadlineExceededError(
                    "model %r: deadline expired while queued "
                    "(waited %.3fs)" % (name, now - req.t_admit)))
            else:
                live.append(req)
        if not live:
            return
        entry = lane.entry
        outs = None
        # fan-in: N request root spans converge on ONE batch span, so
        # the batch records every packed request's token and each
        # request gets a queue-wait span (true timestamps, synthesized
        # here because the wait only ends at dispatch)
        req_uids = [r.trace for r in live] if traced else ()
        if traced:
            for r in live:
                _tracing.record_span(
                    "serving.queue_wait", cat="serving",
                    start_us=int(r.t_admit * 1e6), end_us=int(now * 1e6),
                    parent=r.trace, model=name)
        # dispatch_lock is the hot-reload atomicity boundary: a swap
        # can never land mid-window
        with entry.dispatch_lock:
            backend = entry.backend
            batch, bucket = entry.pad([r.inputs for r in live])
            for attempt in range(default_retries() + 1):
                if self._killed:
                    break
                try:
                    with _tracing.span("serving.dispatch", cat="serving",
                                       model=name, bucket=bucket,
                                       rows=len(live), attempt=attempt,
                                       requests=req_uids) as dsp:
                        try:
                            chaos.visit("serving.dispatch",
                                        name="%s:%d" % (name, bucket))
                            outs, cold = backend.infer(batch)
                        except Exception as exc:  # noqa: BLE001
                            dsp.set(error=type(exc).__name__)
                            raise
                    break
                except Exception as exc:   # noqa: BLE001 - fault path
                    if _metrics.metrics_enabled():
                        lane.m_errors.inc()
                    last_exc = exc
        if self._killed:
            for req in live:
                req._fail(_admission.ReplicaDeadError(
                    "replica %r died with request in flight" % self.name))
            return
        if outs is None:
            err = MXNetError("model %r: dispatch failed after %d attempts: "
                             "%s" % (name, default_retries() + 1, last_exc))
            for req in live:
                req._fail(err)
            return
        t_done = time.monotonic()
        if _metrics.metrics_enabled():
            lane.m_batches.inc()
            lane.m_occ.set(len(live) / float(bucket))
            if cold:
                lane.m_compiles.inc()
        lane.batches += 1
        lane.rows += len(live)
        lane.slots += bucket
        for i, req in enumerate(live):
            req._resolve([o[i] for o in outs])
            if _metrics.metrics_enabled():
                lane.m_requests.inc()
                if req._h_tenant is not None:
                    req._h_tenant.inc()
                lane.m_wait.observe(now - req.t_admit)
                # the request's trace token rides as the bucket's
                # exemplar: a p99 blip links to a concrete trace
                lane.m_req.observe(t_done - req.t_admit, req.trace)

    # -- lifecycle ----------------------------------------------------

    @property
    def alive(self):
        return not self._killed and self._fenced_epoch is None

    def ready(self):
        """Readiness: alive and admitting (the ``/readyz`` answer)."""
        return self.alive and not self.admission.draining \
            and not self._stopping

    def queue_depth(self, name):
        with self._cond:
            lane = self._lanes.get(name)
            return len(lane.queue) if lane else 0

    def load(self):
        """Total queued requests across lanes — the routing tier's
        least-loaded signal (:mod:`~.routing`)."""
        with self._cond:
            return sum(len(l.queue) for l in self._lanes.values())

    def stats(self, name):
        """Running totals for bench: batches, rows served, bucket slots
        run, and their ratio (mean batch occupancy)."""
        lane = self._lane(name)
        occ = lane.rows / float(lane.slots) if lane.slots else 0.0
        return {"batches": lane.batches, "rows": lane.rows,
                "slots": lane.slots, "occupancy": occ}

    def drain(self):
        """Stop admitting; accepted work keeps flowing (rolling
        restart).  Pair with :meth:`close` to also stop the loops."""
        self.admission.start_drain()

    def close(self, timeout=10.0):
        """Drain, let queues empty, stop dispatch threads."""
        self.drain()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._cond:
                if not any(l.queue for l in self._lanes.values()):
                    break
            time.sleep(0.005)
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        for lane in list(self._lanes.values()):
            if lane.thread is not None:
                lane.thread.join(timeout=timeout)

    def kill(self):
        """Crash simulation: fail every queued request with
        :class:`~.admission.ReplicaDeadError` (a router retries them on
        a peer) and refuse everything new.  Idempotent."""
        with self._cond:
            if self._killed:
                return
            self._killed = True
            orphans = []
            for lane in self._lanes.values():
                orphans.extend(lane.queue.drain())
                if _metrics.metrics_enabled():
                    lane.m_depth.set(0)
                    lane.m_sat.set(0.0)
            self._cond.notify_all()
        err = _admission.ReplicaDeadError(
            "replica %r was killed with the request queued" % self.name)
        for req in orphans:
            req._fail(err)

    def fence(self, epoch):
        """Epoch fence (PR-3 semantics): this replica lost membership
        epoch ``epoch`` and must refuse new work — the zombie half of a
        failover.  Queued work is failed like :meth:`kill` so the new
        epoch's replicas take it over."""
        with self._cond:
            self._fenced_epoch = epoch
        self.kill()
