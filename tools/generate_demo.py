"""``make generate`` / ``python tools/generate_demo.py``: the
autoregressive generation lane, end to end on CPU in a few seconds.

Builds a tiny randomly-initialized transformer LM, registers it on a
:class:`~mxnet_tpu.serving.GenerationScheduler` (paged KV cache,
prefill/decode split), starts the HTTP front-end, and streams tokens
over ``POST /v1/generate`` with chunked transfer encoding — printing
each token AS IT ARRIVES, the way a chat client would.  Then it
verifies the contracts the round-14 issue names:

- the streamed tokens equal a naive re-prefill-per-token full-forward
  chain BITWISE (the KV cache changed nothing but the cost);
- steady-state generation compiled nothing after warmup;
- concurrent prompts share decode steps (iteration-level batching).

Exits non-zero on any miss.  No checkpoint, no accelerator.
"""

import json
import http.client
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MXNET_TPU_METRICS", "1")

import numpy as np  # noqa: E402

from mxnet_tpu import serving  # noqa: E402
from mxnet_tpu.models import transformer as tfm  # noqa: E402


def main():
    vocab, seq_len = 256, 64
    cfg = tfm.lm_config(num_classes=vocab, seq_len=seq_len,
                        num_embed=64, num_heads=4, num_layers=2)
    params = tfm.init_lm_params(cfg, seed=7)
    backend = serving.LMBackend(params, cfg, block_size=16,
                                num_blocks=32, model="demo_lm")
    sched = serving.GenerationScheduler(name="demo")
    sched.register("demo_lm", backend, decode_buckets=[1, 2, 4],
                   prefill_buckets=[8, 16])
    print("warmup: %d shapes compiled" % sched.warmup("demo_lm"))
    compiles = sched._fam["compiles"].labels("demo_lm")
    warm = compiles.value

    fe = serving.start_frontend(sched)
    print("serving %s/v1/generate" % fe.url)

    prompt = [3, 141, 59, 26, 53, 58]
    conn = http.client.HTTPConnection("127.0.0.1", fe.port, timeout=60)
    conn.request("POST", "/v1/generate",
                 json.dumps({"model": "demo_lm", "prompt": prompt,
                             "max_new_tokens": 24}),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200, resp.status
    print("prompt %r ->" % (prompt,))
    streamed, tail = [], None
    t0 = time.perf_counter()
    for raw in resp:                       # chunk-decoded line iterator
        line = json.loads(raw)
        if line.get("done"):
            tail = line
            break
        streamed.append(line["token"])
        print("  +%6.1fms  token %d"
              % ((time.perf_counter() - t0) * 1e3, line["token"]))
    assert tail and tail["tokens"] == streamed, "stream/summary mismatch"
    print("finish_reason=%s (%d tokens)"
          % (tail["finish_reason"], len(streamed)))

    # parity vs the naive chain: re-run the full forward per token
    toks = list(prompt)
    for _ in range(24):
        logits, _, _ = tfm.lm_prefill(
            params, np.asarray(toks, np.int32)[None], cfg)
        toks.append(int(np.argmax(np.asarray(logits)[0, len(toks) - 1])))
    assert toks[len(prompt):] == streamed, \
        "paged-cache decode diverged from the full forward"
    print("parity: streamed tokens == full-forward chain")

    # concurrent prompts: iteration-level batching shares decode steps
    reqs = [sched.submit("demo_lm",
                         np.asarray(p, np.int32), max_new_tokens=16)
            for p in ([5, 9, 2], [100, 3], [42, 77, 18, 6])]
    for r in reqs:
        r.result(timeout=60)
    stats = sched.stats("demo_lm")
    assert stats["max_step_rows"] >= 2, "no decode step was shared"
    print("iteration-level batching: up to %d sequences per decode "
          "step, occupancy %.2f"
          % (stats["max_step_rows"], stats["occupancy"]))

    assert compiles.value == warm, "steady-state generation recompiled"
    print("zero steady-state recompiles after warmup")

    fe.close()
    sched.close()
    print("generation demo: OK")


if __name__ == "__main__":
    main()
