"""Multi-process GSPMD data-parallel training (the multi-HOST story:
one global mesh spanning processes, XLA collectives over the process
boundary — reference tier: ``tests/nightly/dist_lenet.py`` convergence
through the dist kvstore, re-based on a cross-process mesh).

Run: python tools/launch.py -n 2 python tests/dist/dist_sharded_trainer.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import mxnet_tpu as mx  # noqa: E402  (bootstraps jax.distributed)
import jax  # noqa: E402
from jax.experimental import multihost_utils  # noqa: E402
from jax.sharding import Mesh  # noqa: E402
from mxnet_tpu.parallel.trainer import ShardedTrainer  # noqa: E402


def main():
    nproc = jax.process_count()
    devs = jax.devices()  # global: one cpu device per process
    assert len(devs) == nproc, (len(devs), nproc)
    mesh = Mesh(np.array(devs), ("data",))

    rng = np.random.RandomState(0)  # same data on every process
    n_examples = 64 * nproc
    centers = rng.randn(4, 8) * 3.0
    labels = rng.randint(0, 4, n_examples)
    data = (centers[labels] + rng.randn(n_examples, 8)).astype(np.float32)

    sym = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Variable("data"), num_hidden=4, name="fc"), name="softmax")
    B = 16 * nproc  # per-process 16 rows; data length scales below
    tr = ShardedTrainer(sym, mesh, data_shapes={"data": (B, 8)},
                        label_shapes={"softmax_label": (B,)},
                        learning_rate=0.2, momentum=0.9,
                        rescale_grad=1.0 / B)
    params, moms, aux = tr.init(seed=0)
    step = tr.step_fn()
    for epoch in range(20):
        for s in range(0, len(data) - B + 1, B):
            batch = tr.place_batch({
                "data": data[s:s + B],
                "softmax_label": labels[s:s + B].astype(np.float32)})
            outs, params, moms, aux = step(params, moms, aux, batch,
                                           jax.random.PRNGKey(epoch))
    # every process must hold identical (replicated) params.  A global
    # array spanning processes can't be fetched wholesale; read the local
    # shard and allgather the host copies.
    local_w = np.asarray(params["fc_weight"].addressable_shards[0].data)
    w = np.asarray(multihost_utils.process_allgather(local_w))
    assert np.allclose(w[0], w[-1]), "params diverged across processes"
    # and the model must have learned
    batch = tr.place_batch({"data": data[:B],
                            "softmax_label": labels[:B].astype(np.float32)})
    fwd = tr.forward_fn()
    out = fwd(params, aux, batch, jax.random.PRNGKey(0))[0]
    prob = np.concatenate(
        [np.asarray(sh.data) for sh in out.addressable_shards])
    labels_local = labels[:B].reshape(nproc, -1)[jax.process_index()]
    acc = (prob.argmax(axis=1) == labels_local).mean()
    assert acc > 0.9, acc
    sys.stdout.write("rank %d/%d: dist GSPMD training OK (acc %.2f, mesh %s)\n"
                     % (jax.process_index(), nproc, acc, dict(mesh.shape)))
    sys.stdout.flush()


if __name__ == "__main__":
    main()
