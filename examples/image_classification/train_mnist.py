"""Train mlp/lenet on MNIST (parity: reference
``example/image-classification/train_mnist.py`` — same CLI with ``--tpus``).

Runs out of the box: uses idx files from ``--data-dir`` when present,
synthetic separable digits otherwise.
"""

import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)
sys.path.insert(0, os.path.dirname(os.path.dirname(_HERE)))  # repo root

import mxnet_tpu as mx
from common import fit, data


def get_mnist_sym(args):
    from mxnet_tpu import models
    return models.get_symbol(args.network, num_classes=10)


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="train an image classifier on mnist",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--data-dir", type=str, default="data/mnist")
    parser.add_argument("--num-examples", type=int, default=6000)
    fit.add_fit_args(parser)
    parser.set_defaults(
        network="mlp",
        num_epochs=10,
        lr=0.05,
        lr_step_epochs="10",
        batch_size=64,
        disp_batches=50,
    )
    args = parser.parse_args()

    sym = get_mnist_sym(args)
    fit.fit(args, sym, data.get_mnist_iter)
