"""SVM output layer (parity: reference ``example/svm_mnist/`` — replace
the softmax head with ``SVMOutput``: multi-class hinge loss, L2 or L1
margin, directly on the class scores).

Synthetic clustered digits (no-egress fallback).  The gate trains the
SAME trunk with SVMOutput (both margin forms) and with SoftmaxOutput and
asserts all reach the accuracy bar — the reference example's point is
that the hinge head is a drop-in.

    python examples/svm_mnist.py
"""

import argparse
import logging
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

import mxnet_tpu as mx

CLASSES = 10
DIM = 64


# class centers are FIXED (shared by train and validation draws)
_CENTERS = np.random.RandomState(1234).randn(CLASSES, DIM) * 2.0


def make_data(rng, n):
    ys = rng.randint(0, CLASSES, n)
    xs = _CENTERS[ys] + rng.randn(n, DIM) * 0.9
    # scale into the unit-ish range: the squared hinge (use_linear=False)
    # is scale-sensitive, the same reason the reference normalizes MNIST
    return (0.1 * xs).astype(np.float32), ys.astype(np.float32)


def get_symbol(head="svm", use_linear=False):
    data = mx.sym.Variable("data")
    net = mx.sym.Activation(mx.sym.FullyConnected(
        data, num_hidden=48, name="fc1"), act_type="relu")
    scores = mx.sym.FullyConnected(net, num_hidden=CLASSES, name="fc2")
    if head == "svm":
        return mx.sym.SVMOutput(scores, use_linear=use_linear,
                                name="svm")
    return mx.sym.SoftmaxOutput(scores, name="softmax")


def _train_one(sym, xs, ys, xv, yv, epochs, batch, seed):
    label_name = sym.list_arguments()[-1]  # auto-created label variable
    mod = mx.mod.Module(sym, context=mx.cpu(), label_names=(label_name,))
    it = mx.io.NDArrayIter(xs, ys, batch_size=batch, shuffle=True,
                           seed=seed, label_name=label_name)
    mod.fit(it, num_epoch=epochs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9,
                              "wd": 1e-4},
            initializer=mx.initializer.Xavier())
    val = mx.io.NDArrayIter(xv, yv, batch_size=batch,
                            label_name=label_name)
    pred = mod.predict(val).asnumpy().argmax(axis=1)
    return float((pred == yv[:len(pred)]).mean())


def run(epochs=8, batch=50, seed=0, log=True):
    rng = np.random.RandomState(seed)
    np.random.seed(seed + 1)
    xs, ys = make_data(rng, 1000)
    xv, yv = make_data(rng, 200)

    accs = {}
    for name, sym in [
        ("svm_l2", get_symbol("svm", use_linear=False)),
        ("svm_l1", get_symbol("svm", use_linear=True)),
        ("softmax", get_symbol("softmax")),
    ]:
        accs[name] = _train_one(sym, xs, ys, xv, yv, epochs, batch, seed)
        if log:
            logging.info("%s head: val acc=%.3f", name, accs[name])
    return accs


def main():
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    args = ap.parse_args()
    accs = run(epochs=args.epochs)
    print("svm_mnist: " + " ".join("%s=%.3f" % kv for kv in accs.items()))


if __name__ == "__main__":
    main()
