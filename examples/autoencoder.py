"""Deep autoencoder with greedy layer-wise pretraining (parity:
reference ``example/autoencoder/`` — stacked AE pretrained layer by
layer, then fine-tuned end-to-end; the reference runs it on MNIST ahead
of clustering).

Synthetic manifold data (no-egress fallback): 64-D observations
generated from a 4-D latent through a fixed nonlinear map + noise.  A
linear method (PCA) cannot reach the noise floor; the gate asserts the
AE's reconstruction beats same-width PCA by a clear margin.

    python examples/autoencoder.py
"""

import argparse
import logging
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

import mxnet_tpu as mx

DIM, LATENT = 64, 4
HIDDEN = (48, 4)  # encoder widths; decoder mirrors


def make_data(rng, n):
    """A curved LATENT-dim manifold in DIM-dim space: sinusoidal features
    of the latent coordinates (fixed deterministic frequency table).
    Linear projection (PCA) cannot flatten it; a nonlinear AE can."""
    z = rng.uniform(-1.2, 1.2, (n, LATENT))
    freqs = (np.arange(1, DIM * LATENT + 1).reshape(DIM, LATENT)
             % 3 + 1) * 0.8                      # 0.8/1.6/2.4 rad/unit
    phases = np.linspace(0, 2 * np.pi, DIM, endpoint=False)
    x = np.sin(z @ freqs.T + phases) + 0.02 * rng.randn(n, DIM)
    return x.astype(np.float32)


def ae_symbol(widths, tie_name=""):
    """Encoder widths -> mirrored decoder, LinearRegressionOutput on the
    input itself (reconstruction)."""
    data = mx.sym.Variable("data")
    net = data
    for i, w in enumerate(widths):
        net = mx.sym.FullyConnected(net, num_hidden=w,
                                    name="%senc%d" % (tie_name, i))
        # relu hidden layers, tanh bottleneck (bounded code space)
        net = mx.sym.Activation(net, act_type="tanh" if w == widths[-1]
                                else "relu")
    for i, w in enumerate(list(reversed(widths))[1:] + [DIM]):
        net = mx.sym.FullyConnected(net, num_hidden=w,
                                    name="%sdec%d" % (tie_name, i))
        if w != DIM:
            net = mx.sym.Activation(net, act_type="relu")
    return mx.sym.LinearRegressionOutput(net, mx.sym.Variable(
        "softmax_label"), name="recon")


def _fit(sym, xs, targets, epochs, batch, lr, params=None, log=False):
    mod = mx.mod.Module(sym, context=mx.cpu())
    it = mx.io.NDArrayIter(xs, targets, batch_size=batch, shuffle=True,
                           seed=1)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    if params:
        mod.set_params(params, {}, allow_missing=True)
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": lr})
    metric = mx.metric.MSE()
    for _ in range(epochs):
        it.reset()
        metric.reset()
        for b in it:
            mod.forward(b, is_train=True)
            # backward FIRST: the fused fwd+bwd materializes outputs, so
            # the metric read costs no extra execution
            mod.backward()
            mod.update()
            mod.update_metric(metric, b.label)
    return mod, metric.get()[1]


def run(pretrain_epochs=12, finetune_epochs=40, n=800, seed=0, log=True):
    rng = np.random.RandomState(seed)
    np.random.seed(seed + 1)
    xs = make_data(rng, n)

    # ---- greedy layer-wise pretraining (the reference's recipe) ----
    pretrained = {}
    acts = xs
    for i, w in enumerate(HIDDEN):
        one = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=w,
                                    name="enc%d" % i)
        one = mx.sym.Activation(one, act_type="tanh"
                                if i == len(HIDDEN) - 1 else "relu")
        one = mx.sym.FullyConnected(one, num_hidden=acts.shape[1],
                                    name="dec%d" % (len(HIDDEN) - 1 - i))
        one = mx.sym.LinearRegressionOutput(
            one, mx.sym.Variable("softmax_label"))
        mod, mse = _fit(one, acts, acts, epochs=pretrain_epochs,
                        batch=100, lr=3e-3)
        arg = {k: v for k, v in mod.get_params()[0].items()}
        pretrained.update(arg)
        if log:
            logging.info("pretrain layer %d (width %d): mse=%.5f", i, w, mse)
        # propagate activations for the next layer's pretraining
        enc_w = arg["enc%d_weight" % i].asnumpy()
        enc_b = arg["enc%d_bias" % i].asnumpy()
        pre = acts @ enc_w.T + enc_b
        acts = (np.tanh(pre) if i == len(HIDDEN) - 1
                else np.maximum(pre, 0.0))

    # ---- end-to-end fine-tuning from the pretrained stack ----
    _, finetuned_mse = _fit(ae_symbol(HIDDEN), xs, xs,
                            epochs=finetune_epochs, batch=100, lr=3e-3,
                            params=pretrained)

    # PCA baseline at the same bottleneck width
    xc = xs - xs.mean(0)
    _, _, vt = np.linalg.svd(xc, full_matrices=False)
    proj = vt[:LATENT]
    pca_mse = float(np.mean((xc - xc @ proj.T @ proj) ** 2))
    if log:
        logging.info("fine-tuned AE mse=%.5f vs PCA-%d mse=%.5f",
                     finetuned_mse, LATENT, pca_mse)
    return {"ae_mse": float(finetuned_mse), "pca_mse": pca_mse}


def main():
    logging.basicConfig(level=logging.INFO)
    argparse.ArgumentParser().parse_args()
    stats = run()
    print("autoencoder: mse=%.5f (PCA-%d baseline %.5f)"
          % (stats["ae_mse"], LATENT, stats["pca_mse"]))


if __name__ == "__main__":
    main()
