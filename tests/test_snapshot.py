"""Durable cluster snapshots: the consistent-cut plan lifecycle,
all-or-nothing commits under seeded ``storage.write`` faults, the
corruption matrix (one byte flipped in every durable file class must
produce a typed error + quarantine + fallback), topology-change
restores, retention GC, and the checkpoint-side kill-between-writes
regression.

Server fixtures mirror ``test_elastic.py``: real ``AsyncServer``
threads on loopback, a tiny stripe bound so 'big' actually stripes.
"""

import json
import os
import pickle

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import chaos, snapshot
from mxnet_tpu import durable
from mxnet_tpu.base import CheckpointCorruptError, MXNetError
from mxnet_tpu.kvstore_async import AsyncServer, ServerGroup
from mxnet_tpu import observability as obs
from mxnet_tpu.parallel import checkpoint as ckpt

pytestmark = []


@pytest.fixture(autouse=True)
def _fast_fsync(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_SNAPSHOT_FSYNC", "0")


def _servers(n, base=0):
    return [AsyncServer(secret="sn", server_id=base + i).start()
            for i in range(n)]


def _group(servers, bound=1 << 6):
    group = ServerGroup([s.address for s in servers], rank=0,
                        heartbeat=False, secret="sn")
    group._bound = bound
    return group


def _seed_group(group):
    rs = np.random.RandomState(0)
    w0 = np.arange(8).astype(np.float32)
    big0 = rs.standard_normal((32, 8)).astype(np.float32)
    group.init([("w", w0), ("big", big0)])
    keys = [("w", (8,)), ("big", (32, 8))]
    return keys, w0, big0


def _pull_check(group, w0, big0):
    out = group.pull(["w", "big"])
    np.testing.assert_array_equal(np.asarray(out[0]).reshape(8), w0)
    np.testing.assert_array_equal(
        np.asarray(out[1]).reshape(32, 8), big0)


def _flip_byte(path, offset=-8):
    with open(path, "r+b") as f:
        f.seek(offset, os.SEEK_END if offset < 0 else os.SEEK_SET)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0x5A]))


# ---------------------------------------------------------------------
# plan lifecycle + commit protocol
# ---------------------------------------------------------------------


def test_snapshot_plan_lifecycle(tmp_path):
    """Phase ordering is enforced, the committed snapshot verifies
    end-to-end, steps auto-increment, the frozen window is measured
    over the cut only."""
    servers = _servers(2)
    group = _group(servers)
    keys, _w0, _big0 = _seed_group(group)
    d = str(tmp_path / "snaps")
    try:
        plan = snapshot.SnapshotPlan(group, d, keys, step=3)
        with pytest.raises(MXNetError, match="plan is new"):
            plan.cut()
        with pytest.raises(MXNetError, match="plan is new"):
            plan.write()
        plan.run()
        assert plan.state == "committed"
        assert plan.frozen_ms is not None and plan.frozen_ms >= 0.0
        assert plan.save_ms >= plan.frozen_ms
        assert snapshot.list_snapshots(d) == [
            (3, os.path.join(d, "snap-3"))]
        manifest = snapshot.verify(os.path.join(d, "snap-3"))
        assert manifest["shards"] == 2 and manifest["step"] == 3
        assert len(manifest["files"]) == 2
        # a second save without an explicit step lands after the newest
        res = snapshot.save(group, d, keys, secret="sn")
        assert res["step"] == 4 and res["shards"] == 2
    finally:
        group.shutdown()
        for s in servers:
            s.stop()


def test_restore_onto_different_shard_counts(tmp_path):
    """A snapshot saved at S=2 restores bitwise-equal onto S'=3 and
    S'=1 — striped keys are reassembled and re-cut with the live
    group's placement."""
    servers = _servers(2)
    group = _group(servers)
    keys, w0, big0 = _seed_group(group)
    d = str(tmp_path / "snaps")
    snapshot.save(group, d, keys, step=1, secret="sn")
    group.shutdown()
    for s in servers:
        s.stop()
    for n_new, base in ((3, 10), (1, 20)):
        servers2 = _servers(n_new, base=base)
        group2 = _group(servers2)
        try:
            out = snapshot.restore_latest(d, group2, secret="sn")
            assert out["saved_shards"] == 2
            assert out["restored_shards"] == n_new
            _pull_check(group2, w0, big0)
        finally:
            group2.shutdown()
            for s in servers2:
                s.stop()


def test_momentum_survives_topology_change(tmp_path):
    """Server-side optimizer slots re-stripe with their weights: after
    a S=2 → S'=3 restore, pushing the same gradient on the restored
    group and on the uninterrupted original yields bitwise-equal
    weights (momentum included)."""
    servers = _servers(2)
    group = _group(servers)
    keys, _w0, _big0 = _seed_group(group)
    opt = pickle.dumps(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9,
                                        rescale_grad=1.0, wd=0.0))
    group.set_optimizer(opt)
    rs = np.random.RandomState(7)
    g1 = {"w": rs.standard_normal(8).astype(np.float32),
          "big": rs.standard_normal((32, 8)).astype(np.float32)}
    g2 = {"w": rs.standard_normal(8).astype(np.float32),
          "big": rs.standard_normal((32, 8)).astype(np.float32)}
    group.push(list(g1.items()))   # momentum now non-zero everywhere
    group.pull(["w", "big"])       # barrier: updates applied
    d = str(tmp_path / "snaps")
    snapshot.save(group, d, keys, step=1, secret="sn")

    # uninterrupted reference: one more identical push
    group.push(list(g2.items()))
    ref = group.pull(["w", "big"])

    servers2 = _servers(3, base=10)
    group2 = _group(servers2)
    try:
        snapshot.restore_latest(d, group2, secret="sn")
        group2.push(list(g2.items()))
        got = group2.pull(["w", "big"])
        for a, b in zip(got, ref):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    finally:
        group.shutdown()
        group2.shutdown()
        for s in servers + servers2:
            s.stop()


# ---------------------------------------------------------------------
# the corruption matrix
# ---------------------------------------------------------------------


def _two_snapshots(tmp_path):
    servers = _servers(2)
    group = _group(servers)
    keys, w0, big0 = _seed_group(group)
    d = str(tmp_path / "snaps")
    snapshot.save(group, d, keys, step=1, secret="sn")
    snapshot.save(group, d, keys, step=2, secret="sn")
    group.shutdown()
    for s in servers:
        s.stop()
    return d, keys, w0, big0


@pytest.mark.parametrize("victim", ["shard-00000.bin", "manifest.json"])
def test_corrupt_newest_falls_back_with_quarantine(tmp_path, monkeypatch,
                                                   victim):
    """One flipped byte in the newest snapshot (shard payload or
    manifest): the typed error is raised internally, the snapshot is
    quarantined through every ops channel — counter, event, flight
    bundle naming the bad file — and the ladder restores the previous
    intact snapshot."""
    flight_dir = tmp_path / "flight"
    monkeypatch.setenv("MXNET_TPU_FLIGHT_DIR", str(flight_dir))
    d, _keys, w0, big0 = _two_snapshots(tmp_path)
    _flip_byte(os.path.join(d, "snap-2", victim))
    obs.clear_events()

    servers = _servers(2, base=30)
    group = _group(servers)
    try:
        out = snapshot.restore_latest(d, group, secret="sn")
        assert out["step"] == 1
        _pull_check(group, w0, big0)
    finally:
        group.shutdown()
        for s in servers:
            s.stop()

    # exactly one quarantine: the corrupt dir moved out of the ladder
    assert not os.path.isdir(os.path.join(d, "snap-2"))
    assert os.path.isdir(os.path.join(d, "snap-2.quarantined"))
    evs = obs.events(kind="snapshot.quarantined")
    assert len(evs) == 1 and evs[0].fields["what"] == "snapshot"
    assert 'snapshot_quarantined_total{kind="snapshot"} 1' \
        in obs.metrics.dump_metrics()
    bundles = [b for b in os.listdir(str(flight_dir))
               if b.startswith("flight_snapshot_quarantined")]
    assert len(bundles) == 1
    with open(os.path.join(str(flight_dir), bundles[0],
                           "manifest.json")) as f:
        extra = json.load(f)["extra"]
    assert extra["snapshot"] == "snap-2"
    if victim != "manifest.json":   # manifest corruption can't name one
        assert extra["file"] == victim


def test_all_snapshots_corrupt_raises_typed(tmp_path):
    """When every candidate fails verification the ladder exhausts with
    the typed error (and everything is quarantined) — callers can
    distinguish 'no snapshot' from 'only corrupt snapshots'."""
    d, _keys, _w0, _big0 = _two_snapshots(tmp_path)
    _flip_byte(os.path.join(d, "snap-1", "shard-00001.bin"))
    _flip_byte(os.path.join(d, "snap-2", "shard-00000.bin"))
    servers = _servers(2, base=40)
    group = _group(servers)
    try:
        with pytest.raises(CheckpointCorruptError, match="every snapshot"):
            snapshot.restore_latest(d, group, secret="sn")
        with pytest.raises(MXNetError, match="no committed snapshot"):
            snapshot.restore_latest(str(tmp_path / "empty"), group,
                                    secret="sn")
    finally:
        group.shutdown()
        for s in servers:
            s.stop()
    assert snapshot.list_snapshots(d) == []
    assert os.path.isdir(os.path.join(d, "snap-1.quarantined"))
    assert os.path.isdir(os.path.join(d, "snap-2.quarantined"))


@pytest.mark.chaos
def test_enospc_mid_save_aborts_clean(tmp_path):
    """A seeded ``storage.write`` ENOSPC mid-snapshot aborts the save
    with the native OSError, removes the staging directory, and leaves
    the previous snapshot exactly as it was."""
    servers = _servers(2)
    group = _group(servers)
    keys, w0, big0 = _seed_group(group)
    d = str(tmp_path / "snaps")
    try:
        snapshot.save(group, d, keys, step=1, secret="sn")
        before = snapshot.verify(os.path.join(d, "snap-1"))
        with chaos.inject("storage.write", "drop", limit=1) as inj:
            with pytest.raises(OSError) as ei:
                snapshot.save(group, d, keys, step=2, secret="sn")
            assert inj.fires == 1
        import errno

        assert ei.value.errno == errno.ENOSPC
        # all-or-nothing: no snap-2, no staging litter, snap-1 intact
        assert snapshot.list_snapshots(d) == [
            (1, os.path.join(d, "snap-1"))]
        assert not any(n.endswith(".tmp") for n in os.listdir(d))
        assert snapshot.verify(os.path.join(d, "snap-1")) == before
        # the same save succeeds once the fault clears
        snapshot.save(group, d, keys, step=2, secret="sn")
        assert [s for s, _ in snapshot.list_snapshots(d)] == [1, 2]
    finally:
        group.shutdown()
        for s in servers:
            s.stop()


@pytest.mark.chaos
def test_torn_write_fails_save_loudly(tmp_path):
    """A seeded bit flip on the way to disk (corrupt mode at
    ``storage.write``): the post-commit read-back verification catches
    the mismatch AT SAVE TIME, quarantines the corpse, and raises the
    typed error — silent rot never becomes the newest snapshot."""
    servers = _servers(2)
    group = _group(servers)
    keys, _w0, _big0 = _seed_group(group)
    d = str(tmp_path / "snaps")
    try:
        snapshot.save(group, d, keys, step=1, secret="sn")
        with chaos.inject("storage.write", "corrupt", limit=1) as inj:
            with pytest.raises(CheckpointCorruptError):
                snapshot.save(group, d, keys, step=2, secret="sn")
            assert inj.fires == 1
        assert snapshot.list_snapshots(d) == [
            (1, os.path.join(d, "snap-1"))]
        assert os.path.isdir(os.path.join(d, "snap-2.quarantined"))
    finally:
        group.shutdown()
        for s in servers:
            s.stop()


@pytest.mark.chaos
def test_silent_bitrot_caught_by_restore_ladder(tmp_path, monkeypatch):
    """With save-time verification off, the same torn write commits
    silently corrupt — the restore ladder must still catch it by
    checksum, quarantine, and fall back to the intact snapshot."""
    monkeypatch.setenv("MXNET_TPU_SNAPSHOT_VERIFY", "0")
    servers = _servers(2)
    group = _group(servers)
    keys, w0, big0 = _seed_group(group)
    d = str(tmp_path / "snaps")
    try:
        snapshot.save(group, d, keys, step=1, secret="sn")
        with chaos.inject("storage.write", "corrupt", limit=1) as inj:
            snapshot.save(group, d, keys, step=2, secret="sn")
            assert inj.fires == 1
        assert [s for s, _ in snapshot.list_snapshots(d)] == [1, 2]
    finally:
        group.shutdown()
        for s in servers:
            s.stop()
    servers2 = _servers(2, base=50)
    group2 = _group(servers2)
    try:
        out = snapshot.restore_latest(d, group2, secret="sn")
        assert out["step"] == 1
        _pull_check(group2, w0, big0)
    finally:
        group2.shutdown()
        for s in servers2:
            s.stop()


def test_gc_retention(tmp_path, monkeypatch):
    """GC keeps MXNET_TPU_SNAPSHOT_KEEP newest snapshots and sweeps
    stale staging dirs."""
    monkeypatch.setenv("MXNET_TPU_SNAPSHOT_KEEP", "2")
    servers = _servers(1)
    group = _group(servers)
    keys, _w0, _big0 = _seed_group(group)
    d = str(tmp_path / "snaps")
    try:
        os.makedirs(os.path.join(d, "snap-9.tmp"))  # a dead staging dir
        for step in (1, 2, 3, 4):
            snapshot.save(group, d, keys, step=step, secret="sn")
        assert [s for s, _ in snapshot.list_snapshots(d)] == [3, 4]
        assert not os.path.isdir(os.path.join(d, "snap-9.tmp"))
    finally:
        group.shutdown()
        for s in servers:
            s.stop()


# ---------------------------------------------------------------------
# checkpoint-side integrity (fit-meta sidecars, the kill regression)
# ---------------------------------------------------------------------


def test_fit_meta_corruption_is_typed(tmp_path):
    """A flipped byte in a checksummed fit-meta sidecar raises the
    typed error; a missing sidecar stays None (absence != corruption)."""
    d = str(tmp_path)
    ckpt.save_fit_meta(d, 3, {"epoch": 1, "nbatch": 7})
    meta = ckpt.load_fit_meta(d, 3)
    assert meta["epoch"] == 1 and meta["nbatch"] == 7
    _flip_byte(os.path.join(d, "fit-meta-3.json"), offset=10)
    with pytest.raises(CheckpointCorruptError):
        ckpt.load_fit_meta(d, 3)
    assert ckpt.load_fit_meta(d, 99) is None


def test_legacy_plain_json_fit_meta_still_loads(tmp_path):
    """Pre-sidecar checkpoints carry plain-JSON fit metas with no
    checksum; they must keep loading (upgrade compatibility)."""
    d = str(tmp_path)
    with open(os.path.join(d, "fit-meta-5.json"), "w") as f:
        json.dump({"epoch": 2, "nbatch": 0}, f)
    meta = ckpt.load_fit_meta(d, 5)
    assert meta["epoch"] == 2
