"""Admission control and SLO-aware deadlines for the serving tier.

A production front-end must say **no** cheaply: every request admitted
past the system's capacity makes every other request slower, and a
request whose deadline has already passed wastes device time producing
an answer nobody is waiting for.  This module is the serving tier's
bouncer — typed, HTTP-mappable rejections at the door:

- **Bounded queues.**  Each model lane has a ``max_queue``
  (``MXNET_TPU_SERVING_MAX_QUEUE``); an admit past the bound raises
  :class:`ServerOverloadedError` (HTTP 429).  Backpressure is explicit
  and immediate, never a silently growing queue.
- **Deadlines, checked twice.**  A request may carry ``deadline_ms``
  (default ``MXNET_TPU_SERVING_DEADLINE_MS``; 0 = none).  An
  already-expired deadline is rejected at admission
  (:class:`DeadlineExceededError`, HTTP 504), and the scheduler checks
  AGAIN when the request is pulled for dispatch — a request that
  expired while queued never reaches the device (docs/how_to/
  serving.md "SLO knobs").
- **Drain mode.**  :meth:`AdmissionController.start_drain` stops
  admitting (:class:`ServerDrainingError`, HTTP 503) while everything
  already accepted keeps flowing to completion — the graceful-restart
  half of a rolling deploy.

- **Per-tenant quotas** (PR-16).  Every tenant has token buckets for
  requests/s and generated-tokens/s (``serving/tenancy.py``); a charge
  past the budget raises :class:`QuotaExceededError` (HTTP 429) naming
  the exhausted budget, carrying ``retry_after_s`` — the bucket's
  refill time, which the front-end maps onto a ``Retry-After``
  header.  One tenant exhausting its budget sheds *that tenant*,
  never the lane.

Every rejection increments
``serving_rejected_total{model,reason,tenant}`` with ``reason`` ∈
``overload | deadline | draining | quota | ...`` so shed load is
accounted per tenant, never inferred.  The scheduler consults the
chaos site ``serving.admit`` on every admit (outside the queue lock,
so injected delays stall one caller, not the dispatch loop), letting
fault drills shed or delay at the door deterministically (seeded —
see ``mxnet_tpu/chaos.py``).
"""

from __future__ import annotations

import math
import os
import time

from ..base import MXNetError
from ..observability import metrics as _metrics
from ..ops.kv_cache import CacheExhaustedError
from .tenancy import DEFAULT_TENANT

__all__ = ["ServingError", "ServerOverloadedError", "ServerDrainingError",
           "DeadlineExceededError", "UnknownModelError", "ReplicaDeadError",
           "QuotaExceededError", "InvalidDeadlineError",
           "CacheExhaustedError", "AdmissionController", "deadline_from_ms",
           "default_deadline_ms", "default_retry_after_s",
           "max_queue_default", "reject_reason"]


class ServingError(MXNetError):
    """Base class for typed serving rejections; ``http_status`` maps the
    error onto the wire (``frontend.py`` uses it verbatim)."""

    http_status = 500


class ServerOverloadedError(ServingError):
    """The model's queue is at ``max_queue`` — shed, don't buffer."""

    http_status = 429


class ServerDrainingError(ServingError):
    """The replica is draining: accepted work finishes, new work is
    refused (the rolling-restart window)."""

    http_status = 503


class DeadlineExceededError(ServingError):
    """The request's deadline passed — at admission, while queued, or
    before its batch dispatched.  Expired requests never cost device
    time."""

    http_status = 504


class UnknownModelError(ServingError):
    """No model registered under that name."""

    http_status = 404


class ReplicaDeadError(ServingError):
    """The replica was killed (or fenced) with this request unanswered;
    a router retries it on a peer — the caller only sees this when no
    peer is left."""

    http_status = 503


class QuotaExceededError(ServingError):
    """The tenant's token-bucket budget is exhausted.  ``budget`` names
    which bucket ran dry (``requests`` or ``tokens``) and
    ``retry_after_s`` is the refill time — the ``Retry-After`` hint
    the front-end puts on the wire.  Deliberately NOT a subclass of
    :class:`ServerOverloadedError`: a quota shed is a per-tenant
    verdict, so the failover router must surface it instead of burning
    the budget again on every peer."""

    http_status = 429

    def __init__(self, msg, budget="requests", retry_after_s=None):
        super().__init__(msg)
        self.budget = budget
        self.retry_after_s = retry_after_s


class InvalidDeadlineError(ServingError):
    """``deadline_ms`` was negative or non-finite — a malformed
    request, rejected before it can mint an already-expired deadline
    (0 stays the documented "no deadline" sentinel)."""

    http_status = 400


#: Canonical shed-reason tag per typed rejection — the vocabulary the
#: ``serving.shed`` span attr and the access-log event share.
#: ``CacheExhaustedError`` (429) comes from the generation lane's paged
#: KV cache: it lives in ``ops.kv_cache`` (the allocator can't import
#: the serving tier) but sheds through this same machinery.
_REASONS = {
    ServerOverloadedError: "overload",
    DeadlineExceededError: "deadline",
    ServerDrainingError: "draining",
    ReplicaDeadError: "replica_dead",
    UnknownModelError: "unknown_model",
    CacheExhaustedError: "cache_exhausted",
    QuotaExceededError: "quota",
}


def reject_reason(exc):
    """The canonical shed-reason tag for a typed serving error (or for
    its type), ``None`` for anything that is not a typed rejection."""
    return _REASONS.get(exc if isinstance(exc, type) else type(exc))


#: Shared help/label schema for ``serving_rejected_total`` — every
#: registry that re-registers the family (per-replica isolated
#: registries) must agree on it, so there is exactly one source.
REJECTED_HELP = ("Serving requests shed, by model, reason "
                 "(overload | deadline | draining | quota | ...) and "
                 "tenant")
REJECTED_LABELS = ["model", "reason", "tenant"]

_M_REJECTED = _metrics.counter(
    "serving_rejected_total", REJECTED_HELP, REJECTED_LABELS)


def default_retry_after_s():
    """``MXNET_TPU_SERVING_RETRY_AFTER_S``: the backoff hint (seconds)
    the front-end sends on 429-class sheds that carry no bucket refill
    time of their own (overload, cache exhaustion)."""
    try:
        return float(os.environ.get("MXNET_TPU_SERVING_RETRY_AFTER_S",
                                    "1"))
    except ValueError:
        return 1.0


def retry_after_s(exc):
    """The ``Retry-After`` value (whole seconds, >= 1) for a 429-class
    shed: the quota bucket's refill time when the error carries one,
    the env-default backoff otherwise."""
    hint = getattr(exc, "retry_after_s", None)
    if hint is None:
        hint = default_retry_after_s()
    return max(1, int(math.ceil(float(hint))))


def default_deadline_ms():
    """``MXNET_TPU_SERVING_DEADLINE_MS`` (0 = no default deadline)."""
    try:
        return float(os.environ.get("MXNET_TPU_SERVING_DEADLINE_MS", "0"))
    except ValueError:
        return 0.0


def max_queue_default():
    """``MXNET_TPU_SERVING_MAX_QUEUE`` (per-model lane bound)."""
    try:
        return int(os.environ.get("MXNET_TPU_SERVING_MAX_QUEUE", "256"))
    except ValueError:
        return 256


def deadline_from_ms(deadline_ms=None, now=None):
    """Relative ``deadline_ms`` → absolute monotonic deadline (seconds),
    or None for no deadline.  ``deadline_ms=None`` falls back to the
    ``MXNET_TPU_SERVING_DEADLINE_MS`` default.

    ``0`` is the documented "no deadline" sentinel (the env default and
    the router's no-deadline retry depend on it).  Anything *negative*
    or *non-finite* is a malformed request and raises the typed
    :class:`InvalidDeadlineError` (HTTP 400) instead of minting an
    already-expired — or never-expiring — deadline."""
    if deadline_ms is None:
        deadline_ms = default_deadline_ms()
    try:
        deadline_ms = float(deadline_ms)
    except (TypeError, ValueError):
        raise InvalidDeadlineError(
            "deadline_ms must be a number, got %r" % (deadline_ms,))
    if not math.isfinite(deadline_ms):
        raise InvalidDeadlineError(
            "deadline_ms must be finite, got %r" % (deadline_ms,))
    if deadline_ms < 0:
        raise InvalidDeadlineError(
            "deadline_ms must be >= 0 (0 = no deadline), got %r"
            % (deadline_ms,))
    if deadline_ms == 0:
        return None
    return (time.monotonic() if now is None else now) + deadline_ms / 1e3


class AdmissionController(object):
    """Admission policy for one replica: queue bounds, deadline checks,
    drain mode.  The scheduler consults :meth:`admit` with the lane's
    current depth BEFORE enqueueing and :meth:`expired` again when the
    request is pulled for dispatch."""

    def __init__(self, reject_counter=None):
        # per-replica metric registries (in-process replica groups)
        # resolve their own family; the process-global one is the default
        self._rejected = reject_counter or _M_REJECTED
        self._draining = False

    @property
    def draining(self):
        return self._draining

    def start_drain(self):
        """Stop admitting; everything already queued still completes."""
        self._draining = True

    def stop_drain(self):
        """Re-open admission (a drain that turned out unnecessary)."""
        self._draining = False

    def account(self, model, reason, tenant=DEFAULT_TENANT):
        """Book one shed request without raising (dispatch-side expiry,
        where the error lands on the request future instead)."""
        self._rejected.labels(model, reason, tenant).inc()

    def reject(self, model, reason, detail="", tenant=DEFAULT_TENANT):
        """Account a shed request and raise its typed error."""
        self.account(model, reason, tenant)
        if reason == "draining":
            raise ServerDrainingError(
                "model %r: replica is draining%s" % (model, detail))
        if reason == "deadline":
            raise DeadlineExceededError(
                "model %r: deadline exceeded%s" % (model, detail))
        raise ServerOverloadedError(
            "model %r: queue full%s" % (model, detail))

    def quota_reject(self, model, tenant, budget, wait_s):
        """Account a quota shed and raise the typed 429 naming the
        exhausted budget, with the bucket's refill time as the
        ``Retry-After`` hint."""
        self.account(model, "quota", tenant)
        raise QuotaExceededError(
            "model %r: tenant %r exhausted its %s budget (retry in "
            "%.2fs)" % (model, tenant, budget, wait_s),
            budget=budget, retry_after_s=wait_s)

    def admit(self, model, depth, max_queue, deadline, now=None,
              tenant=DEFAULT_TENANT):
        """Gate one request at the door.  Raises the typed rejection
        (accounted in ``serving_rejected_total``) or returns silently.
        Pure policy — the scheduler fires the ``serving.admit`` chaos
        site before calling, outside its queue lock."""
        if self._draining:
            self.reject(model, "draining", tenant=tenant)
        now = time.monotonic() if now is None else now
        if deadline is not None and now >= deadline:
            self.reject(model, "deadline", " (expired at admission)",
                        tenant=tenant)
        if depth >= max_queue:
            self.reject(model, "overload",
                        " (depth %d >= max_queue %d)" % (depth, max_queue),
                        tenant=tenant)

    @staticmethod
    def expired(deadline, now=None):
        """Second check, at dispatch time: True when the deadline passed
        while the request sat in the queue."""
        if deadline is None:
            return False
        return (time.monotonic() if now is None else now) >= deadline
