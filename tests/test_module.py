"""Module tests incl. train-to-accuracy gates (parity model: reference
``tests/python/unittest/test_module.py`` + ``tests/python/train/test_mlp.py``).

MNIST is replaced by a synthetic separable classification problem (no dataset
downloads in this environment); the convergence gate plays the same role."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def _blobs(n=400, num_class=4, dim=10, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(num_class, dim) * 3.0
    labels = rng.randint(0, num_class, n)
    data = centers[labels] + rng.randn(n, dim)
    return data.astype(np.float32), labels.astype(np.float32)


def _mlp(num_class=4):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=num_class, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_module_bind_forward():
    net = _mlp()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 10))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    batch = mx.io.DataBatch([mx.nd.ones((8, 10))], [mx.nd.zeros((8,))])
    mod.forward(batch, is_train=False)
    out = mod.get_outputs()[0]
    assert out.shape == (8, 4)
    assert_almost_equal(out.asnumpy().sum(axis=1), np.ones(8), rtol=1e-5)


def test_module_fit_convergence():
    """Train-to-accuracy gate (reference tests/python/train/test_mlp.py)."""
    data, labels = _blobs()
    train = mx.io.NDArrayIter(data, labels, batch_size=40, shuffle=True)
    val = mx.io.NDArrayIter(data, labels, batch_size=40)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, eval_data=val, num_epoch=15,
            optimizer="sgd", optimizer_params={"learning_rate": 0.2,
                                               "momentum": 0.9},
            eval_metric="acc",
            initializer=mx.initializer.Xavier())
    score = mod.score(val, "acc")
    assert score[0][1] > 0.95, "MLP failed to converge: %s" % (score,)


def test_module_get_set_params():
    net = _mlp()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 10))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(initializer=mx.initializer.One())
    arg_params, aux_params = mod.get_params()
    assert_almost_equal(arg_params["fc1_weight"].asnumpy(),
                        np.ones((32, 10), np.float32))
    arg_params["fc1_bias"][:] = 5.0
    mod.set_params(arg_params, aux_params)
    a2, _ = mod.get_params()
    assert_almost_equal(a2["fc1_bias"].asnumpy(), np.full((32,), 5.0, np.float32))


def test_module_checkpoint(tmp_path):
    data, labels = _blobs(80)
    train = mx.io.NDArrayIter(data, labels, batch_size=40)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, num_epoch=1, optimizer_params={"learning_rate": 0.1})
    prefix = str(tmp_path / "mlp")
    mod.save_checkpoint(prefix, 1)
    mod2 = mx.mod.Module.load(prefix, 1)
    mod2.bind(data_shapes=[("data", (40, 10))],
              label_shapes=[("softmax_label", (40,))])
    mod2.init_params()
    a1, _ = mod.get_params()
    a2, _ = mod2.get_params()
    for k in a1:
        assert_almost_equal(a1[k].asnumpy(), a2[k].asnumpy())


def test_module_predict():
    data, labels = _blobs(80)
    train = mx.io.NDArrayIter(data, labels, batch_size=40)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, num_epoch=1, optimizer_params={"learning_rate": 0.1})
    pred_iter = mx.io.NDArrayIter(data, None, batch_size=40)
    out = mod.predict(pred_iter)
    assert out.shape == (80, 4)


def test_module_input_grads():
    net = _mlp()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 10))],
             label_shapes=[("softmax_label", (8,))],
             inputs_need_grad=True)
    mod.init_params()
    mod.init_optimizer()
    batch = mx.io.DataBatch([mx.nd.ones((8, 10))], [mx.nd.zeros((8,))])
    mod.forward(batch, is_train=True)
    mod.backward()
    ig = mod.get_input_grads()[0]
    assert ig.shape == (8, 10)
    assert np.abs(ig.asnumpy()).sum() > 0


def test_module_multi_device_dp():
    """Data-parallel over a multi-device mesh (GSPMD replaces
    DataParallelExecutorGroup)."""
    import jax

    ndev = min(4, len(jax.devices()))
    if ndev < 2:
        pytest.skip("needs multiple devices")
    ctxs = [mx.cpu(i) for i in range(ndev)]
    data, labels = _blobs(160)
    train = mx.io.NDArrayIter(data, labels, batch_size=40, shuffle=True)
    mod = mx.mod.Module(_mlp(), context=ctxs)
    mod.fit(train, num_epoch=8, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9},
            initializer=mx.initializer.Xavier())
    score = mod.score(mx.io.NDArrayIter(data, labels, batch_size=40), "acc")
    assert score[0][1] > 0.9, score


def test_module_reshape():
    # reference module.py:reshape — new batch size, params + optimizer kept
    rng = np.random.RandomState(0)
    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Variable("data"), num_hidden=3, name="fc"), name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 5))], label_shapes=[
        ("softmax_label", (8,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    b8 = mx.io.DataBatch([mx.nd.array(rng.rand(8, 5).astype(np.float32))],
                         [mx.nd.array(np.zeros(8, np.float32))])
    mod.forward(b8); mod.backward(); mod.update()
    w_before = mod.get_params()[0]["fc_weight"].asnumpy()
    mom_before = {k: v[0].asnumpy().copy() if isinstance(v, (list, tuple))
                  else v.asnumpy().copy()
                  for k, v in mod._updater.states.items()}
    assert mom_before, "momentum state should exist after one update"

    mod.reshape(data_shapes=[("data", (4, 5))],
                label_shapes=[("softmax_label", (4,))])
    # params and accumulated optimizer state both survive the reshape
    np.testing.assert_array_equal(
        mod.get_params()[0]["fc_weight"].asnumpy(), w_before)
    for k, v in mod._updater.states.items():
        got = v[0].asnumpy() if isinstance(v, (list, tuple)) else v.asnumpy()
        np.testing.assert_array_equal(got, mom_before[k])

    b4 = mx.io.DataBatch([mx.nd.array(rng.rand(4, 5).astype(np.float32))],
                         [mx.nd.array(np.zeros(4, np.float32))])
    mod.forward(b4)
    assert mod.get_outputs()[0].shape == (4, 3)
    mod.backward(); mod.update()
    assert not np.allclose(
        mod.get_params()[0]["fc_weight"].asnumpy(), w_before)
