"""Kaggle NDSB-2 cardiac volume estimation (parity: reference
``example/kaggle-ndsb2/Train.py`` — frame-difference LeNet over a
30-frame cine-MRI sequence, 600-bin CDF target through
``LogisticRegressionOutput``, CRPS scoring with the isotonic
monotonicity fix).

Synthetic stand-in for the DSB-2 data (no-egress): each "study" is a
T-frame loop of a pulsating bright disk on a noisy field; the disk area
oscillates between a diastolic and a systolic extreme, and the target
volume is the systolic (minimum) area.  The network sees consecutive
frame DIFFERENCES (``SliceChannel`` split + pairwise subtraction +
``Concat``, exactly the reference's ``get_lenet`` trick: motion, not
anatomy, carries the signal), and regresses the volume's CDF over
``BINS`` thresholds with a sigmoid cross-entropy head per bin.

CRPS = mean squared difference between the predicted CDF (after
enforcing monotonicity like the reference's ``CRPS``) and the true
step-function CDF.  Gate: the model's CRPS beats the best constant
predictor (the marginal CDF of the training volumes) by a wide margin.

    python examples/kaggle_ndsb2.py
"""

import argparse
import logging
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

import mxnet_tpu as mx

T = 12             # frames per study (reference: 30)
SIDE = 24          # frame side
BINS = 40          # CDF thresholds (reference: 600 ml bins)


def make_studies(rng, n):
    """(n, T, SIDE, SIDE) cine loops + (n,) systolic 'volumes'."""
    xs = rng.uniform(0, 0.3, (n, T, SIDE, SIDE)).astype(np.float32)
    vols = np.zeros(n, np.float32)
    yy, xx = np.mgrid[0:SIDE, 0:SIDE]
    for i in range(n):
        cy, cx = rng.uniform(SIDE * 0.35, SIDE * 0.65, 2)
        r_dia = rng.uniform(4.0, 9.0)            # diastolic radius
        frac = rng.uniform(0.45, 0.85)           # systolic contraction
        r_sys = r_dia * frac
        phase = rng.uniform(0, 2 * np.pi)
        for t in range(T):
            r = (r_dia + r_sys) / 2 \
                + (r_dia - r_sys) / 2 * np.cos(
                    2 * np.pi * t / T + phase)
            mask = ((yy - cy) ** 2 + (xx - cx) ** 2) < r ** 2
            xs[i, t][mask] += rng.uniform(0.8, 1.1)
        vols[i] = np.pi * r_sys ** 2             # systolic area
    return xs, vols


def encode_cdf(vols, lo=0.0, hi=260.0):
    """Volume -> step-CDF over BINS thresholds (reference encode_label)."""
    edges = np.linspace(lo, hi, BINS)
    return (vols[:, None] < edges[None, :]).astype(np.float32), edges


def get_symbol():
    data = mx.sym.Variable("data")               # (B, T, S, S)
    frames = mx.sym.SliceChannel(data, num_outputs=T, axis=1)
    diffs = [frames[i + 1] - frames[i] for i in range(T - 1)]
    net = mx.sym.Concat(*diffs, dim=1)           # (B, T-1, S, S)
    net = mx.sym.Convolution(net, kernel=(5, 5), num_filter=16,
                             name="conv1")
    net = mx.sym.BatchNorm(net, fix_gamma=True)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2),
                         stride=(2, 2))
    net = mx.sym.Convolution(net, kernel=(3, 3), num_filter=16,
                             name="conv2")
    net = mx.sym.BatchNorm(net, fix_gamma=True)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2),
                         stride=(2, 2))
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=64,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=BINS, name="fc2")
    # per-bin sigmoid cross-entropy against the step CDF
    return mx.sym.LogisticRegressionOutput(net, name="softmax")


def crps(label_cdf, pred_cdf):
    """Reference CRPS: isotonic fix along bins, then mean sq diff."""
    pred = pred_cdf.copy()
    np.maximum.accumulate(pred, axis=1, out=pred)
    return float(np.mean((label_cdf - pred) ** 2))


def run(epochs=12, batch=32, n_train=384, n_val=128, seed=0, log=True):
    rng = np.random.RandomState(seed)
    np.random.seed(seed + 1)
    xs, vols = make_studies(rng, n_train)
    xv, volv = make_studies(rng, n_val)
    ys, _ = encode_cdf(vols)
    yv, _ = encode_cdf(volv)

    mod = mx.mod.Module(get_symbol(), context=mx.cpu())
    train = mx.io.NDArrayIter({"data": xs}, {"softmax_label": ys},
                              batch_size=batch, shuffle=False)
    mod.fit(train, num_epoch=epochs, optimizer="adam",
            optimizer_params={"learning_rate": 2e-3},
            initializer=mx.initializer.Xavier())

    val = mx.io.NDArrayIter({"data": xv}, None, batch_size=batch)
    preds = mod.predict(val).asnumpy()
    model_crps = crps(yv, preds)
    # best constant predictor: the training marginal CDF
    const = ys.mean(axis=0, keepdims=True).repeat(n_val, axis=0)
    const_crps = crps(yv, const)
    if log:
        logging.info("CRPS model=%.4f constant-baseline=%.4f",
                     model_crps, const_crps)
    return {"crps": model_crps, "crps_const": const_crps}


def main():
    logging.basicConfig(level=logging.INFO)
    argparse.ArgumentParser().parse_args()
    stats = run()
    print("kaggle_ndsb2: crps=%.4f (const baseline %.4f)"
          % (stats["crps"], stats["crps_const"]))


if __name__ == "__main__":
    main()
