"""graftcheck static-analysis suite: per-rule fixture pairs (bad code
flagged at the right line / good code clean / pragma suppresses), the
JSON reporter schema, the baseline lifecycle, CLI exit codes, and the
tier-1 gate: the real repo must come back with zero unbaselined
findings.

Fixtures are synthetic mini-repos in ``tmp_path`` — ``Project`` takes a
root, so each test builds exactly the tree shape its rule reads
(``docs/env_vars.md`` for the env registry, ``mxnet_tpu/chaos.py`` for
``SITES``, hot-path file names for the metrics rule).
"""

import io
import json
import os
import textwrap
import time

from tools.graftcheck import ALL_RULES, Project, run_rules
from tools.graftcheck.__main__ import main as graftcheck_main
from tools.graftcheck.core import (apply_baseline, load_baseline,
                                   report_json, save_baseline)

# -- mini-repo helpers ------------------------------------------------------

CHAOS_PY = """\
SITES = frozenset({
    "engine.op",
    "kvstore.send",
})


def visit(site, payload=None, **meta):
    return payload
"""

ENV_DOC = """\
# Environment variables

| Variable | Default | Meaning |
|---|---|---|
| `MXNET_TPU_GOOD` | unset | a documented tunable |
"""

# keeps the base doc row alive so the dead-row check stays quiet in
# fixtures that are about something else
BASE_CFG = """\
import os

GOOD = os.environ.get("MXNET_TPU_GOOD", "0")
"""


def _mini(tmp_path, files):
    base = {"mxnet_tpu/chaos.py": CHAOS_PY, "docs/env_vars.md": ENV_DOC,
            "mxnet_tpu/_basecfg.py": BASE_CFG}
    base.update(files)
    for rel, text in base.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return str(tmp_path)


def _run(root, rule):
    project = Project(root)
    return run_rules(project, {rule: ALL_RULES[rule]})


# -- env-var-registry -------------------------------------------------------

def test_envvar_undocumented_read_flagged_at_line(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/cfg.py": """\
        import os

        def knob():
            return os.environ.get("MXNET_TPU_UNDOCUMENTED", "0")
        """})
    findings = _run(root, "env-var-registry")
    assert [(f.path, f.line) for f in findings] == [
        (os.path.join("mxnet_tpu", "cfg.py"), 4)]
    assert "MXNET_TPU_UNDOCUMENTED" in findings[0].message


def test_envvar_documented_read_clean(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/cfg.py": """\
        import os

        def knob():
            return os.environ.get("MXNET_TPU_GOOD", "0")
        """})
    assert _run(root, "env-var-registry") == []


def test_envvar_dead_doc_row_flagged(tmp_path):
    # removing the last read of a documented var (or renaming it in
    # code) must fail the suite at the now-dead doc row
    root = _mini(tmp_path, {"docs/env_vars.md": ENV_DOC + (
        "| `MXNET_TPU_DEAD` | unset | nothing reads this anymore |\n")})
    findings = _run(root, "env-var-registry")
    assert len(findings) == 1
    assert findings[0].path == os.path.join("docs", "env_vars.md")
    assert findings[0].line == 6          # the MXNET_TPU_DEAD table row
    assert "MXNET_TPU_DEAD" in findings[0].message
    assert "dead row" in findings[0].message


def test_envvar_pragma_suppresses(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/cfg.py": """\
        import os

        def knob():
            # launcher-internal, deliberately undocumented
            # graftcheck: disable-next=env-var-registry
            return os.environ.get("MXNET_TPU_UNDOCUMENTED")
        """})
    assert _run(root, "env-var-registry") == []


def test_envvar_test_files_exempt_but_count_as_usage(tmp_path):
    root = _mini(tmp_path, {"tests/test_x.py": """\
        import os

        def test_knob(monkeypatch):
            monkeypatch.setenv("MXNET_TPU_GOOD", "1")
            assert os.environ.get("MXNET_TPU_NOT_A_RUNTIME_READ") is None
        """})
    # reads in tests/ are not flagged, and the mention of the
    # documented name keeps its row alive
    assert _run(root, "env-var-registry") == []


# -- chaos-site -------------------------------------------------------------

def test_chaos_unknown_site_flagged_at_line(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/net.py": """\
        from . import chaos as _chaos

        def send(payload):
            return _chaos.visit("kvstore.sendd", payload)
        """})
    findings = _run(root, "chaos-site")
    assert [(f.path, f.line) for f in findings] == [
        (os.path.join("mxnet_tpu", "net.py"), 4)]
    assert "kvstore.sendd" in findings[0].message


def test_chaos_known_site_clean(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/net.py": """\
        from . import chaos as _chaos

        def send(payload):
            return _chaos.visit("kvstore.send", payload)
        """})
    assert _run(root, "chaos-site") == []


def test_chaos_renamed_site_breaks_call_sites(tmp_path):
    # the acceptance scenario: rename a site in chaos.SITES and every
    # caller still using the old name goes red
    root = _mini(tmp_path, {
        "mxnet_tpu/chaos.py": CHAOS_PY.replace(
            '"kvstore.send"', '"kvstore.tx"'),
        "mxnet_tpu/net.py": """\
        from . import chaos as _chaos

        def send(payload):
            return _chaos.visit("kvstore.send", payload)
        """})
    findings = _run(root, "chaos-site")
    assert len(findings) == 1
    assert findings[0].path == os.path.join("mxnet_tpu", "net.py")


def test_chaos_spec_string_in_test_flagged(tmp_path):
    root = _mini(tmp_path, {"tests/test_chaos_use.py": """\
        def test_inject(monkeypatch):
            monkeypatch.setenv(
                "MXNET_TPU_CHAOS", "kvstore.sned:drop@0.5")
        """})
    findings = _run(root, "chaos-site")
    assert len(findings) == 1
    assert "kvstore.sned" in findings[0].message


def test_chaos_docs_code_block_flagged(tmp_path):
    root = _mini(tmp_path, {"docs/how_to/chaos.md": """\
        # Chaos

        ```python
        chaos.visit("engine.opp")
        ```
        """})
    findings = _run(root, "chaos-site")
    assert [(f.path, f.line) for f in findings] == [
        (os.path.join("docs", "how_to", "chaos.md"), 4)]


# -- metrics-hot-path -------------------------------------------------------

def test_metrics_lookup_in_dispatch_loop_flagged(tmp_path):
    # the acceptance scenario: move a label resolution into the
    # scheduler dispatch loop
    root = _mini(tmp_path, {"mxnet_tpu/serving/scheduler.py": """\
        class Scheduler:
            def _dispatch(self, lane, batch):
                self._m_batch.labels(lane.name).observe(len(batch))
        """})
    findings = _run(root, "metrics-hot-path")
    assert [(f.path, f.line) for f in findings] == [
        (os.path.join("mxnet_tpu", "serving", "scheduler.py"), 3)]
    assert ".labels(" in findings[0].message


def test_metrics_preresolved_handle_clean(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/serving/scheduler.py": """\
        class Scheduler:
            def _dispatch(self, lane, batch):
                lane.m_batch.observe(len(batch))
        """})
    assert _run(root, "metrics-hot-path") == []


def test_metrics_registration_in_engine_push_flagged(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/engine.py": """\
        from .observability.metrics import counter

        def push(fn, ctx):
            counter("engine_push_total", "pushes").inc()
        """})
    findings = _run(root, "metrics-hot-path")
    assert [(f.path, f.line) for f in findings] == [
        (os.path.join("mxnet_tpu", "engine.py"), 4)]


def test_metrics_invalid_name_and_conflict_flagged(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/obs.py": """\
        from .observability.metrics import counter, gauge

        M_BAD = counter("engine-push-total", "invalid char")
        M_A = counter("dup_total", "first", ["op"])
        M_B = gauge("dup_total", "second", ["op"])
        """})
    findings = _run(root, "metrics-hot-path")
    msgs = [(f.line, f.message) for f in findings]
    assert any(line == 3 and "not Prometheus-valid" in m
               for line, m in msgs)
    assert any(line == 5 and "re-registered" in m for line, m in msgs)
    assert len(findings) == 2


def test_metrics_pragma_suppresses(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/serving/scheduler.py": """\
        class Scheduler:
            def _dispatch(self, lane, batch):
                # cold slow-path branch, hit once per model load
                self._m.labels(lane.name).inc()  # graftcheck: disable=metrics-hot-path
        """})
    assert _run(root, "metrics-hot-path") == []


# -- typed-errors -----------------------------------------------------------

def test_typed_errors_bare_runtimeerror_flagged(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/serving/frontend.py": """\
        def admit(req):
            if req is None:
                raise RuntimeError("bad request")
        """})
    findings = _run(root, "typed-errors")
    assert [(f.path, f.line) for f in findings] == [
        (os.path.join("mxnet_tpu", "serving", "frontend.py"), 3)]
    assert "RuntimeError" in findings[0].message


def test_typed_errors_valueerror_in_wire_fn_flagged(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/kvstore_wire.py": """\
        def _recv_msg(sock):
            raise ValueError("truncated")
        """})
    findings = _run(root, "typed-errors")
    assert [(f.path, f.line) for f in findings] == [
        (os.path.join("mxnet_tpu", "kvstore_wire.py"), 2)]


def test_typed_errors_good_cases_clean(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/kvstore_wire.py": """\
        from .base import TruncatedMessageError

        def _recv_msg(sock):
            raise TruncatedMessageError("peer died mid-frame")

        def __init__(self, addrs):
            # constructor validation is NOT wire-path: ValueError ok
            if not addrs:
                raise ValueError("need at least one address")
        """})
    assert _run(root, "typed-errors") == []


def test_typed_errors_out_of_scope_module_clean(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/visualization.py": """\
        def plot(g):
            raise RuntimeError("no display")
        """})
    assert _run(root, "typed-errors") == []


def test_typed_errors_pragma_suppresses(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/serving/frontend.py": """\
        def admit(req):
            # stdlib http.server contract requires a bare error here
            raise RuntimeError("x")  # graftcheck: disable=typed-errors
        """})
    assert _run(root, "typed-errors") == []


# -- lock-discipline --------------------------------------------------------

THREADED_BAD = """\
import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.state = 0

    def start(self):
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        self.state = 1

    def poke(self):
        self.state = 2
"""


def test_lock_discipline_unguarded_writes_flagged(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/w.py": THREADED_BAD})
    findings = _run(root, "lock-discipline")
    assert [(f.path, f.line) for f in findings] == [
        (os.path.join("mxnet_tpu", "w.py"), 13),
        (os.path.join("mxnet_tpu", "w.py"), 16)]
    assert all("state" in f.message for f in findings)


def test_lock_discipline_guarded_writes_clean(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/w.py": """\
        import threading


        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.state = 0

            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                with self._lock:
                    self.state = 1

            def poke(self):
                with self._lock:
                    self.state = 2
        """})
    assert _run(root, "lock-discipline") == []


def test_lock_discipline_locked_suffix_exempt(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/w.py": """\
        import threading


        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.state = 0

            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                with self._lock:
                    self._advance_locked()

            def _advance_locked(self):
                # caller holds self._lock (the *_locked convention)
                self.state = 1
        """})
    assert _run(root, "lock-discipline") == []


def test_lock_discipline_non_threaded_class_clean(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/w.py": """\
        class Plain:
            def __init__(self):
                self.state = 0

            def poke(self):
                self.state = 2
        """})
    assert _run(root, "lock-discipline") == []


def test_lock_discipline_pragma_suppresses(tmp_path):
    bad = THREADED_BAD.replace(
        "        self.state = 1",
        "        self.state = 1  # graftcheck: disable=lock-discipline"
    ).replace(
        "        self.state = 2",
        "        self.state = 2  # graftcheck: disable=lock-discipline")
    root = _mini(tmp_path, {"mxnet_tpu/w.py": bad})
    assert _run(root, "lock-discipline") == []


# -- jit-purity -------------------------------------------------------------

def test_jit_purity_time_call_flagged(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/step.py": """\
        import time

        import jax


        def step(x):
            t0 = time.time()
            return x + t0


        step_fn = jax.jit(step)
        """})
    findings = _run(root, "jit-purity")
    assert [(f.path, f.line) for f in findings] == [
        (os.path.join("mxnet_tpu", "step.py"), 7)]
    assert "time.time" in findings[0].message


def test_jit_purity_pure_fn_clean(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/step.py": """\
        import jax


        def step(x):
            return x * 2


        step_fn = jax.jit(step)
        """})
    assert _run(root, "jit-purity") == []


def test_jit_purity_scan_lambda_print_flagged(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/scan.py": """\
        from jax import lax


        def run(xs):
            return lax.scan(lambda c, x: (c, print(x)), 0, xs)
        """})
    findings = _run(root, "jit-purity")
    assert len(findings) == 1
    assert findings[0].line == 5
    assert "print()" in findings[0].message


def test_jit_purity_impure_outside_traced_fn_clean(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/step.py": """\
        import time

        import jax


        def step(x):
            return x * 2


        t0 = time.time()
        step_fn = jax.jit(step)
        """})
    assert _run(root, "jit-purity") == []


def test_jit_purity_pragma_suppresses(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/step.py": """\
        import os

        import jax


        def step(x):
            # debug-only trace knob, read once at trace time on purpose
            flag = os.environ.get("DEBUG")  # graftcheck: disable=jit-purity
            return x


        step_fn = jax.jit(step)
        """})
    assert _run(root, "jit-purity") == []


# -- golden-metrics ---------------------------------------------------------

def test_golden_unregistered_family_flagged(tmp_path):
    root = _mini(tmp_path, {"tests/golden/expo.txt": """\
        # TYPE engine_push_total counter
        engine_push_total 3
        """})
    findings = _run(root, "golden-metrics")
    assert [(f.path, f.line) for f in findings] == [
        (os.path.join("tests", "golden", "expo.txt"), 1)]
    assert "engine_push_total" in findings[0].message


def test_golden_registered_family_clean(tmp_path):
    root = _mini(tmp_path, {
        "mxnet_tpu/obs.py": """\
        from .observability.metrics import counter

        M_PUSH = counter("engine_push_total", "pushes")
        """,
        "tests/golden/expo.txt": """\
        # TYPE engine_push_total counter
        engine_push_total 3
        """})
    assert _run(root, "golden-metrics") == []


def test_golden_demo_prefix_exempt_and_stray_series_flagged(tmp_path):
    root = _mini(tmp_path, {"tests/golden/expo.txt": """\
        # TYPE demo_requests_total counter
        demo_requests_total{code="200"} 7
        stray_series_total 1
        """})
    findings = _run(root, "golden-metrics")
    assert [(f.path, f.line) for f in findings] == [
        (os.path.join("tests", "golden", "expo.txt"), 3)]
    assert "stray_series_total" in findings[0].message


# -- pragma forms -----------------------------------------------------------

def test_pragma_disable_next_and_file(tmp_path):
    root = _mini(tmp_path, {
        "mxnet_tpu/a.py": """\
        import os

        # graftcheck: disable-next=env-var-registry
        V = os.environ.get("MXNET_TPU_NOT_DOCUMENTED")
        """,
        "mxnet_tpu/b.py": """\
        # graftcheck: disable-file=env-var-registry
        import os

        V = os.environ.get("MXNET_TPU_ALSO_NOT_DOCUMENTED")
        """})
    assert _run(root, "env-var-registry") == []


def test_pragma_other_rule_does_not_suppress(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/a.py": """\
        import os

        V = os.environ.get("MXNET_TPU_X")  # graftcheck: disable=chaos-site
        """})
    assert len(_run(root, "env-var-registry")) == 1


# -- parse errors surface, never hide --------------------------------------

def test_syntax_error_yields_parse_finding(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/broken.py": "def f(:\n"})
    findings = _run(root, "env-var-registry")
    assert [(f.rule, f.path) for f in findings] == [
        ("parse", os.path.join("mxnet_tpu", "broken.py"))]


# -- baseline lifecycle -----------------------------------------------------

def test_baseline_grandfathers_and_reports_stale(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/cfg.py": """\
        import os

        V = os.environ.get("MXNET_TPU_LEGACY")
        """})
    findings = _run(root, "env-var-registry")
    assert len(findings) == 1

    baseline_path = str(tmp_path / "baseline.txt")
    save_baseline(baseline_path, findings)
    baseline = load_baseline(baseline_path)
    fresh, grandfathered, stale = apply_baseline(findings, baseline)
    assert fresh == [] and len(grandfathered) == 1 and stale == []

    # line moves do not resurrect a grandfathered finding
    moved = [type(f)(f.path, f.line + 40, f.rule, f.message)
             for f in findings]
    fresh, grandfathered, _ = apply_baseline(moved, baseline)
    assert fresh == [] and len(grandfathered) == 1

    # a fixed finding leaves a stale entry the report calls out
    fresh, grandfathered, stale = apply_baseline([], baseline)
    assert stale and stale[0][0] == "env-var-registry"


# -- JSON reporter ----------------------------------------------------------

def test_json_report_schema(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/cfg.py": """\
        import os

        V = os.environ.get("MXNET_TPU_MYSTERY")
        """})
    findings = _run(root, "env-var-registry")
    buf = io.StringIO()
    report_json(findings, [], [], {"env-var-registry": None}, buf)
    doc = json.loads(buf.getvalue())
    assert doc["version"] == 1
    assert doc["rules"] == ["env-var-registry"]
    assert doc["counts"] == {"total": 1, "unbaselined": 1, "baselined": 0}
    (f,) = doc["findings"]
    assert set(f) == {"path", "line", "rule", "message", "baselined"}
    assert f["rule"] == "env-var-registry" and f["baselined"] is False


# -- CLI --------------------------------------------------------------------

def test_cli_exit_codes_and_update_baseline(tmp_path, capsys):
    root = _mini(tmp_path, {"mxnet_tpu/cfg.py": """\
        import os

        V = os.environ.get("MXNET_TPU_MYSTERY")
        """})
    baseline = str(tmp_path / "baseline.txt")

    assert graftcheck_main(
        ["--root", root, "--baseline", baseline]) == 1
    out = capsys.readouterr().out
    assert "mxnet_tpu%scfg.py:3" % os.sep in out

    assert graftcheck_main(
        ["--root", root, "--baseline", baseline,
         "--update-baseline"]) == 0
    capsys.readouterr()
    assert graftcheck_main(
        ["--root", root, "--baseline", baseline]) == 0
    assert "1 baselined finding(s) suppressed" in capsys.readouterr().out

    assert graftcheck_main(["--rule", "no-such-rule"]) == 2


def test_cli_json_output(tmp_path, capsys):
    root = _mini(tmp_path, {"mxnet_tpu/cfg.py": "X = 1\n"})
    rc = graftcheck_main(
        ["--root", root, "--baseline", str(tmp_path / "b.txt"),
         "--rule", "chaos-site", "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["rules"] == ["chaos-site"] and doc["findings"] == []


# -- atomic-write -----------------------------------------------------------

def test_atomic_write_durable_module_flagged(tmp_path):
    """ANY write-mode open in a durable-state module is flagged; reads
    and the atomic helpers' own tmp writes are exempt."""
    root = _mini(tmp_path, {"mxnet_tpu/snapshot.py": """\
        def save(path, data):
            with open(path, "wb") as f:
                f.write(data)

        def load(path):
            with open(path, "rb") as f:
                return f.read()

        def atomic_write_bytes(path, data):
            with open(path + ".tmp", "wb") as f:
                f.write(data)
        """})
    findings = _run(root, "atomic-write")
    assert [(f.path, f.line) for f in findings] == [
        (os.path.join("mxnet_tpu", "snapshot.py"), 2)]
    assert "durable-state module" in findings[0].message


def test_atomic_write_token_path_flagged_elsewhere(tmp_path):
    """Outside the durable modules, only writes whose path expression
    names durable-state tokens are flagged."""
    root = _mini(tmp_path, {"mxnet_tpu/other.py": """\
        def dump(d, log_path):
            with open(d + "/manifest.json", "w") as f:
                f.write("{}")
            with open(log_path, "w") as f:
                f.write("scratch log, not durable state")
        """})
    findings = _run(root, "atomic-write")
    assert [(f.path, f.line) for f in findings] == [
        (os.path.join("mxnet_tpu", "other.py"), 2)]


def test_atomic_write_pragma_suppresses(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/other.py": """\
        def dump(d):
            # staged into a .tmp dir; one rename commits the bundle
            with open(d + "/manifest.json", "w") as f:  # graftcheck: disable=atomic-write
                f.write("{}")
        """})
    assert _run(root, "atomic-write") == []


# -- fused-parity -----------------------------------------------------------

def test_fused_parity_orphan_variant_flagged(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/ops/fused/k.py": """\
        from ..registry import register_variant

        def fused_foo(x):
            return x
        register_variant("foo", "fused", fused_foo, backends=("tpu",))
        """})
    findings = _run(root, "fused-parity")
    assert [(f.path, f.line) for f in findings] == [
        (os.path.join("mxnet_tpu", "ops", "fused", "k.py"), 5)]
    assert "foo:fused" in findings[0].message
    assert "register_parity" in findings[0].message


def test_fused_parity_matched_pair_clean(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/ops/fused/k.py": """\
        from ..registry import register_variant
        from .parity import register_parity

        def fused_foo(x):
            return x
        register_variant("foo", "fused", fused_foo, backends=("tpu",))

        def _case(case):
            return (lambda x: x), fused_foo, (case,)
        register_parity("foo", "fused", _case, grid=(1, 2))
        """})
    assert _run(root, "fused-parity") == []


def test_fused_parity_non_literal_name_flagged(tmp_path):
    # a computed op name defeats the static pairing this rule exists
    # to give reviewers — flagged even if a parity twin might exist
    root = _mini(tmp_path, {"mxnet_tpu/ops/fused/k.py": """\
        from ..registry import register_variant

        OP = "foo"
        register_variant(OP, "fused", lambda x: x)
        """})
    findings = _run(root, "fused-parity")
    assert [(f.path, f.line) for f in findings] == [
        (os.path.join("mxnet_tpu", "ops", "fused", "k.py"), 4)]
    assert "literal" in findings[0].message


def test_fused_parity_pragma_suppresses(tmp_path):
    root = _mini(tmp_path, {"mxnet_tpu/ops/fused/k.py": """\
        from ..registry import register_variant

        # experiment-only kernel, parity twin lands with the real PR
        # graftcheck: disable-next=fused-parity
        register_variant("foo", "fused", lambda x: x)
        """})
    assert _run(root, "fused-parity") == []


def test_fused_parity_test_fixtures_exempt(tmp_path):
    # tests may register deliberately broken variants for the harness
    # to catch; only runtime files are in scope
    root = _mini(tmp_path, {"tests/test_k.py": """\
        from mxnet_tpu.ops.registry import register_variant

        register_variant("foo", "broken", lambda x: x + 1)
        """})
    assert _run(root, "fused-parity") == []


# -- the tier-1 gate: this repo stays clean ---------------------------------

def test_whole_repo_zero_unbaselined(capsys):
    """The actual repo passes its own analyzer with no unbaselined
    findings, within the interactive budget the Makefile relies on."""
    t0 = time.monotonic()
    rc = graftcheck_main([])
    elapsed = time.monotonic() - t0
    out = capsys.readouterr().out
    assert rc == 0, "unbaselined graftcheck findings:\n%s" % out
    assert elapsed < 30.0, "graftcheck exceeded its 30s budget"
