"""Multi-process dist_tpu kvstore worker script: the TPU-native fused
sync mode must match dist_sync EXACTLY (reference exact-arithmetic test
strategy: ``tests/nightly/dist_sync_kvstore.py:14-45``), while never
routing weights through a host-side updater.

Three tiers, all exact:
  1. accumulate (no optimizer) — the dist_sync default-updater behavior;
  2. sgd-momentum update-on-push parity vs a dist_sync store walking the
     same schedule on the same pushes (bitwise on every pull);
  3. adam parity (exercises the on-device t/bias-correction path).

Run: python tools/launch.py -n 2 python tests/dist/dist_tpu_kvstore.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import mxnet_tpu as mx  # noqa: E402  (bootstraps jax.distributed)


def _parity(optimizer_name, shape, rank, nworkers, nrepeat=3, atol=0.0,
            **opt_kw):
    kv_sync = mx.kv.create("dist_sync")
    kv_tpu = mx.kv.create("dist_tpu")
    init = mx.nd.array(np.arange(np.prod(shape), dtype=np.float32)
                       .reshape(shape) / 7.0)
    kv_sync.init("w", init)
    kv_tpu.init("w", init)
    # separate instances: each store owns its own schedule counters
    kv_sync.set_optimizer(mx.optimizer.create(optimizer_name, **opt_kw))
    kv_tpu.set_optimizer(mx.optimizer.create(optimizer_name, **opt_kw))
    out_s, out_t = mx.nd.zeros(shape), mx.nd.zeros(shape)
    for i in range(nrepeat):
        # integer-valued, rank- and step-dependent gradients: the
        # cross-worker sum is exact, so any deviation is an update-math
        # or reduce-semantics bug, not float noise
        g = mx.nd.ones(shape) * float((rank + 1) * (i + 1))
        kv_sync.push("w", g)
        kv_tpu.push("w", g)
        kv_sync.pull("w", out=out_s)
        kv_tpu.pull("w", out=out_t)
        if atol:  # adam: XLA constant-folded vs runtime pow(b, t), 1 ulp
            np.testing.assert_allclose(
                out_s.asnumpy(), out_t.asnumpy(), atol=atol, rtol=0,
                err_msg="%s step %d: dist_tpu != dist_sync"
                        % (optimizer_name, i))
        else:
            np.testing.assert_array_equal(
                out_s.asnumpy(), out_t.asnumpy(),
                err_msg="%s step %d: dist_tpu != dist_sync"
                        % (optimizer_name, i))
        kv_sync.barrier()
    # the weight must actually have moved
    assert not np.allclose(out_t.asnumpy(), init.asnumpy())


def main():
    kv = mx.kv.create("dist_tpu")
    rank, nworkers = kv.rank, kv.num_workers
    assert nworkers == int(os.environ.get("MXNET_TPU_NUM_PROCS", "1")), \
        (nworkers, os.environ.get("MXNET_TPU_NUM_PROCS"))

    # -- tier 1: accumulate semantics (dist_sync's default updater) ----
    shape = (3, 4)
    kv.init("3", mx.nd.ones(shape))
    nrepeat = 3
    for _ in range(nrepeat):
        kv.push("3", mx.nd.ones(shape) * (rank + 1))
        kv.barrier()
    expected = 1 + nrepeat * sum(range(1, nworkers + 1))
    out = mx.nd.zeros(shape)
    kv.pull("3", out=out)
    np.testing.assert_array_equal(out.asnumpy(),
                                  np.full(shape, expected, np.float32))

    # -- tier 2/3: fused update-on-push parity vs dist_sync ------------
    _parity("sgd", (4, 5), rank, nworkers,
            learning_rate=0.1, momentum=0.9, wd=1e-3,
            rescale_grad=1.0 / nworkers)
    _parity("adam", (2, 8), rank, nworkers, atol=2e-6,
            learning_rate=0.05, rescale_grad=1.0 / nworkers)

    # -- tier 4: rank-0-wins init (kvstore_dist.h:40-44 semantics) -----
    # ranks init DIVERGENT values; every rank must observe rank 0's
    kv.init("b", mx.nd.ones(shape) * float(100 + rank))
    out_b = mx.nd.zeros(shape)
    kv.pull("b", out=out_b)
    np.testing.assert_array_equal(out_b.asnumpy(),
                                  np.full(shape, 100.0, np.float32))

    sys.stdout.write("worker %d/%d: dist_tpu kvstore OK (expected=%d)\n"
                     % (rank, nworkers, expected))
    sys.stdout.flush()


if __name__ == "__main__":
    main()
