"""NDArray tests (parity model: reference ``tests/python/unittest/test_ndarray.py``)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal, default_context


def test_ndarray_creation():
    a = mx.nd.zeros((3, 4))
    assert a.shape == (3, 4)
    assert a.asnumpy().sum() == 0
    b = mx.nd.ones((2, 3))
    assert b.asnumpy().sum() == 6
    c = mx.nd.full((2, 2), 3.5)
    assert_almost_equal(c.asnumpy(), np.full((2, 2), 3.5, np.float32))
    d = mx.nd.array([[1, 2], [3, 4]])
    assert d.dtype == np.float32
    assert_almost_equal(d.asnumpy(), np.array([[1, 2], [3, 4]], np.float32))


def test_ndarray_elementwise():
    np.random.seed(0)
    a_np = np.random.randn(4, 5).astype(np.float32)
    b_np = np.random.randn(4, 5).astype(np.float32)
    a, b = mx.nd.array(a_np), mx.nd.array(b_np)
    assert_almost_equal((a + b).asnumpy(), a_np + b_np)
    assert_almost_equal((a - b).asnumpy(), a_np - b_np)
    assert_almost_equal((a * b).asnumpy(), a_np * b_np)
    assert_almost_equal((a / b).asnumpy(), a_np / b_np, rtol=1e-4)
    assert_almost_equal((a + 2).asnumpy(), a_np + 2)
    assert_almost_equal((2 * a).asnumpy(), 2 * a_np)
    assert_almost_equal((-a).asnumpy(), -a_np)


def test_ndarray_inplace():
    a = mx.nd.ones((2, 3))
    a += 2
    assert_almost_equal(a.asnumpy(), np.full((2, 3), 3, np.float32))
    a *= 2
    assert_almost_equal(a.asnumpy(), np.full((2, 3), 6, np.float32))
    a[:] = 1.5
    assert_almost_equal(a.asnumpy(), np.full((2, 3), 1.5, np.float32))


def test_ndarray_indexing():
    a_np = np.arange(24, dtype=np.float32).reshape(4, 6)
    a = mx.nd.array(a_np)
    assert_almost_equal(a[1].asnumpy(), a_np[1])
    assert_almost_equal(a[1:3].asnumpy(), a_np[1:3])
    a[0] = 0.0
    a_np[0] = 0.0
    assert_almost_equal(a.asnumpy(), a_np)


def test_ndarray_ops():
    a_np = np.random.randn(3, 4).astype(np.float32)
    a = mx.nd.array(a_np)
    assert_almost_equal(mx.nd.exp(a).asnumpy(), np.exp(a_np), rtol=1e-5)
    assert_almost_equal(mx.nd.square(a).asnumpy(), a_np ** 2, rtol=1e-5)
    assert_almost_equal(mx.nd.sum(a).asnumpy(), a_np.sum().reshape(()), rtol=1e-5)
    assert_almost_equal(
        mx.nd.sum(a, axis=1).asnumpy(), a_np.sum(axis=1), rtol=1e-5)
    assert_almost_equal(mx.nd.transpose(a).asnumpy(), a_np.T)
    r = mx.nd.Reshape(a, shape=(4, 3))
    assert r.shape == (4, 3)


def test_ndarray_dot():
    a_np = np.random.randn(3, 4).astype(np.float32)
    b_np = np.random.randn(4, 5).astype(np.float32)
    out = mx.nd.dot(mx.nd.array(a_np), mx.nd.array(b_np))
    assert_almost_equal(out.asnumpy(), a_np @ b_np, rtol=1e-4)


def test_ndarray_copy_context():
    a = mx.nd.ones((2, 2), ctx=mx.cpu())
    b = a.copyto(mx.cpu(0))
    assert_almost_equal(a.asnumpy(), b.asnumpy())
    c = a.as_in_context(mx.cpu(0))
    assert c.context == mx.cpu(0) or c is a


def test_ndarray_saveload(tmp_path):
    fname = str(tmp_path / "nd.npz")
    data = {"w": mx.nd.ones((3, 3)), "b": mx.nd.zeros((3,))}
    mx.nd.save(fname, data)
    loaded = mx.nd.load(fname)
    assert set(loaded) == {"w", "b"}
    assert_almost_equal(loaded["w"].asnumpy(), np.ones((3, 3), np.float32))
    lst = [mx.nd.ones((2,)), mx.nd.zeros((3,))]
    mx.nd.save(fname, lst)
    loaded = mx.nd.load(fname)
    assert isinstance(loaded, list) and len(loaded) == 2


def test_ndarray_onehot():
    idx = mx.nd.array([0, 2, 1])
    out = mx.nd.one_hot(idx, depth=3)
    assert_almost_equal(out.asnumpy(), np.eye(3, dtype=np.float32)[[0, 2, 1]])


def test_random_reproducible():
    mx.random.seed(7)
    a = mx.nd.uniform(shape=(5,)).asnumpy()
    mx.random.seed(7)
    b = mx.nd.uniform(shape=(5,)).asnumpy()
    assert_almost_equal(a, b)
