"""Server-process entry point for ``tools/launch.py -s N`` (parity: the
reference's ``DMLC_ROLE=server`` processes running
``KVStoreDistServer::Run``, ``src/kvstore/kvstore_dist_server.h``).

The launcher hands this process its port/identity/secret via env
(``MXNET_TPU_SERVER_PORT``, ``MXNET_TPU_SERVER_ID``,
``MXNET_TPU_PS_SECRET``) — the dmlc tracker env contract.  With
``MXNET_TPU_SERVER_PRIMARY=<addr>`` set (``tools/launch.py -r N``), the
process enters that primary's replica group as a hot standby: snapshot
state transfer, then the live update stream.  With
``MXNET_TPU_METRICS_PORT`` set (``tools/launch.py
--metrics-port-base``), the process also serves its own ``/metrics``
endpoint as a federation scrape target.  The process serves until a
worker sends the ``shutdown`` op or the launcher reaps it after the
workers exit.
"""

import logging
import os
import time

from .kvstore_async import AsyncServer


def main():
    logging.basicConfig(level=logging.INFO)
    port = int(os.environ.get("MXNET_TPU_SERVER_PORT", "0"))
    server_id = int(os.environ.get("MXNET_TPU_SERVER_ID", "0"))
    server = AsyncServer(port=port, server_id=server_id).start()
    # federation scrape target: every server process exposes its own
    # /metrics when the launcher (--metrics-port-base) or the job hands
    # it a port; failure to bind must not take down the shard
    metrics = None
    watchdog = None
    if os.environ.get("MXNET_TPU_WATCHDOG", "").lower() not in (
            "", "0", "false", "no"):
        # default SLO rules over this process's own registry; terminal
        # alerts route through the flight recorder (when enabled)
        from .observability import Watchdog, default_rules

        watchdog = Watchdog(default_rules())
        watchdog.start()
    if os.environ.get("MXNET_TPU_METRICS_PORT"):
        try:
            from .observability import start_metrics_server

            metrics = start_metrics_server(watchdog=watchdog)
            logging.info("async PS shard %d metrics at %s", server_id,
                         metrics.url)
        except OSError:
            logging.exception("async PS shard %d: /metrics endpoint "
                              "failed to bind (continuing without)",
                              server_id)
    addr_file = os.environ.get("MXNET_TPU_SERVER_ADDR_FILE")
    if addr_file:
        # port 0 = kernel-assigned (no probe-then-bind race); report the
        # actual address to the launcher atomically
        tmp = addr_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(server.address)
        os.replace(tmp, addr_file)
    primary = os.environ.get("MXNET_TPU_SERVER_PRIMARY")
    if primary:
        # hot standby: state-transfer from the shard's primary, then ride
        # its update stream.  A restarted replica uses the same path to
        # REJOIN a running job — retry briefly in case the primary is
        # still binding.
        deadline = time.monotonic() + 60
        while True:
            try:
                server.rejoin(primary)
                break
            except (ConnectionError, OSError, EOFError):
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.5)
    logging.info("async PS shard %d serving on %s (%s)", server_id,
                 server.address, server.role)
    server.wait_shutdown()
    server.stop()
    if watchdog is not None:
        watchdog.stop()
    if metrics is not None:
        metrics.close()


if __name__ == "__main__":
    main()
