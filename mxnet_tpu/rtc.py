"""Runtime kernel compilation (parity: reference ``python/mxnet/rtc.py`` +
``src/common/mxrtc.cc`` — ``MXRtc`` compiles user CUDA source strings with
NVRTC and launches them on NDArrays).

TPU equivalent: the user supplies **Python source for a JAX/Pallas kernel**;
it is compiled (exec + jit) once at construction and launched on NDArrays
with the same ``push`` call shape as the reference.  This preserves the
capability — inject a custom kernel at runtime without rebuilding the
framework — with XLA/Mosaic playing NVRTC's role.
"""

from __future__ import annotations

from .base import MXNetError
from .ndarray import NDArray, array

__all__ = ["Rtc"]


class Rtc(object):
    """Runtime-compiled kernel.

    Parameters
    ----------
    name : str — function to extract from the compiled source.
    inputs/outputs : sequence of str — argument names (kept for parity with
        the reference signature; arity-checked at push).
    source : str — Python source defining ``name`` as a jax-traceable
        function ``f(*inputs) -> output or tuple(outputs)``.  The namespace
        exposes ``jnp``, ``jax``, ``lax``, and ``pl``/``plgrid`` (Pallas)
        so both plain-XLA and Pallas kernels compile.

    Example
    -------
    >>> rtc = Rtc('axpy', ['x', 'y'], ['out'], '''
    ... def axpy(x, y):
    ...     return 2.0 * x + y
    ... ''')
    >>> out = rtc.push([a, b], grid=None)
    """

    def __init__(self, name, inputs, outputs, source):
        import jax
        import jax.numpy as jnp
        from jax import lax

        ns = {"jax": jax, "jnp": jnp, "lax": lax}
        try:
            import jax.experimental.pallas as pl

            ns["pl"] = pl
        except ImportError:
            pass
        try:
            exec(compile(source, "<mx.rtc>", "exec"), ns)  # noqa: S102
        except SyntaxError as e:
            raise MXNetError("rtc source failed to compile: %s" % e)
        if name not in ns:
            raise MXNetError("rtc source does not define %r" % name)
        self.name = name
        self._inputs = list(inputs)
        self._outputs = list(outputs)
        self._fn = jax.jit(ns[name])

    def push(self, ins, outs=None, grid_dim_x=None, grid_dim_y=None,
             grid_dim_z=None, block_dim_x=None, block_dim_y=None,
             block_dim_z=None, **_ignored):
        """Run the kernel (parity: ``Rtc.push``).  Grid/block args are
        accepted for signature parity and ignored — XLA/Mosaic choose the
        tiling.  Returns the output NDArray(s); when ``outs`` is given the
        results are also written into them (the reference mutates outs)."""
        if len(ins) != len(self._inputs):
            raise MXNetError("expected %d inputs, got %d"
                             % (len(self._inputs), len(ins)))
        vals = [i._data if isinstance(i, NDArray) else i for i in ins]
        result = self._fn(*vals)
        if not isinstance(result, tuple):
            result = (result,)
        if len(result) != len(self._outputs):
            raise MXNetError("kernel returned %d outputs, declared %d"
                             % (len(result), len(self._outputs)))
        wrapped = [array(r) for r in result]
        if outs is not None:
            if len(outs) != len(wrapped):
                raise MXNetError("expected %d outs, got %d"
                                 % (len(wrapped), len(outs)))
            for o, r in zip(outs, wrapped):
                if tuple(o.shape) != tuple(r.shape):
                    raise MXNetError(
                        "out shape %s != kernel output shape %s"
                        % (o.shape, r.shape))
                o._set_data(r._data.astype(o.dtype))
        return wrapped if len(wrapped) > 1 else wrapped[0]
