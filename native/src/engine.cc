/*!
 * Threaded dependency engine — host-side async scheduler.
 *
 * Reference behavior being matched (not copied): every op is pushed with
 * const (read) and mutable (write) var lists; the engine runs it when its
 * dependencies clear, serializing writers and parallelizing readers per var
 * (reference src/engine/threaded_engine.{h,cc} dependency algorithms
 * AppendRead/WriteDependency, CompleteRead/WriteDependency;
 * include/mxnet/engine.h:75-250 for the interface).
 *
 * TPU-first framing: XLA/PJRT already parallelizes *device* work, so this
 * engine's job is the host half of the pipeline — record IO, decode,
 * batch staging, checkpoint writes, host-side kvstore reductions — with
 * separate worker pools per FnProperty (normal / IO / copy), mirroring the
 * per-device pools of threaded_engine_perdevice.cc:55-105 at host scope.
 *
 * Engine selection via MXTPU_ENGINE_TYPE:
 *   ThreadedEngine (default) | NaiveEngine (synchronous, for debugging) —
 * same idea as MXNET_ENGINE_TYPE (src/engine/engine.cc:13-39).
 */
#include <atomic>
#include <condition_variable>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

#include "mxtpu/c_api.h"

namespace mxtpu {

void ProfilerRecord(const char *name, const char *cat, int64_t start_us,
                    int64_t end_us, int tid);
bool ProfilerRunning();
int64_t NowUs();

struct OprBlock;

// Request waiting on a var.
struct VarReq {
  OprBlock *opr;
  bool is_write;
};

// A dependency variable.  State machine under `m`: some readers granted, or
// one writer granted; waiters queue in arrival order (so a read arriving
// after a queued write waits — sequential consistency per var).
struct Var {
  std::mutex m;
  int granted_reads = 0;
  bool granted_write = false;
  bool to_delete = false;
  std::deque<VarReq> q;
};

struct OprBlock {
  std::function<void()> fn;
  std::function<void()> deleter;  // runs after completion (may be empty)
  std::vector<Var *> const_vars;
  std::vector<Var *> mutable_vars;
  std::atomic<int> wait{0};
  int priority = 0;
  int prop = MXTPU_PROP_NORMAL;
  std::string name;
};

class ThreadPool;

class Engine {
 public:
  static Engine *Get();

  Var *NewVar() { return new Var(); }

  void Push(OprBlock *opr);
  void DeleteVar(Var *var);
  void WaitForVar(Var *var);
  void WaitAll();
  bool naive() const { return naive_; }
  int num_workers() const { return n_workers_; }
  long pending() const { return pending_.load(); }

  // called by workers
  void Execute(OprBlock *opr);

 private:
  Engine();
  ~Engine();
  void Dispatch(OprBlock *opr);
  // Returns true if granted immediately.
  bool Request(Var *var, OprBlock *opr, bool is_write,
               std::vector<OprBlock *> *ready);
  void Release(Var *var, bool was_write, std::vector<OprBlock *> *ready);
  static void DecWait(OprBlock *opr, std::vector<OprBlock *> *ready) {
    if (opr->wait.fetch_sub(1) == 1) ready->push_back(opr);
  }

  bool naive_ = false;
  int n_workers_ = 0;
  ThreadPool *pools_[3] = {nullptr, nullptr, nullptr};
  std::atomic<long> pending_{0};
  std::mutex all_m_;
  std::condition_variable all_cv_;
};

// Priority FIFO thread pool.
class ThreadPool {
 public:
  ThreadPool(int n, const char *tag) : tag_(tag) {
    for (int i = 0; i < n; ++i)
      threads_.emplace_back([this, i] { Run(i); });
  }
  ~ThreadPool() {
    {
      std::lock_guard<std::mutex> lk(m_);
      shutdown_ = true;
    }
    cv_.notify_all();
    for (auto &t : threads_) t.join();
  }
  void Enqueue(OprBlock *opr) {
    {
      std::lock_guard<std::mutex> lk(m_);
      q_.push(Item{-opr->priority, seq_++, opr});
    }
    cv_.notify_one();
  }
  int size() const { return (int)threads_.size(); }

 private:
  struct Item {
    int neg_priority;
    uint64_t seq;
    OprBlock *opr;
    bool operator>(const Item &o) const {
      if (neg_priority != o.neg_priority) return neg_priority > o.neg_priority;
      return seq > o.seq;
    }
  };
  void Run(int idx) {
    (void)idx;
    for (;;) {
      OprBlock *opr;
      {
        std::unique_lock<std::mutex> lk(m_);
        cv_.wait(lk, [this] { return shutdown_ || !q_.empty(); });
        if (shutdown_ && q_.empty()) return;
        opr = q_.top().opr;
        q_.pop();
      }
      Engine::Get()->Execute(opr);
    }
  }
  const char *tag_;
  std::mutex m_;
  std::condition_variable cv_;
  std::priority_queue<Item, std::vector<Item>, std::greater<Item>> q_;
  uint64_t seq_ = 0;
  bool shutdown_ = false;
  std::vector<std::thread> threads_;
};

int EnvInt(const char *name, int dflt) {
  const char *v = std::getenv(name);
  return v ? std::atoi(v) : dflt;
}

Engine::Engine() {
  const char *ty = std::getenv("MXTPU_ENGINE_TYPE");
  naive_ = ty && std::strcmp(ty, "NaiveEngine") == 0;
  if (!naive_) {
    int n = EnvInt("MXTPU_CPU_WORKER_NTHREADS", 4);
    int nio = EnvInt("MXTPU_IO_NTHREADS", 2);
    int ncopy = EnvInt("MXTPU_COPY_NTHREADS", 2);
    pools_[MXTPU_PROP_NORMAL] = new ThreadPool(n, "worker");
    pools_[MXTPU_PROP_IO] = new ThreadPool(nio, "io");
    pools_[MXTPU_PROP_COPY] = new ThreadPool(ncopy, "copy");
    n_workers_ = n + nio + ncopy;
  }
}

Engine::~Engine() {
  // Process-lifetime singleton; pools leak intentionally at exit (threads may
  // still be draining — same stance as the reference engine singletons).
}

Engine *Engine::Get() {
  static Engine *inst = new Engine();
  return inst;
}

bool Engine::Request(Var *var, OprBlock *opr, bool is_write,
                     std::vector<OprBlock *> *ready) {
  std::lock_guard<std::mutex> lk(var->m);
  if (var->q.empty() &&
      (is_write ? (!var->granted_write && var->granted_reads == 0)
                : !var->granted_write)) {
    if (is_write)
      var->granted_write = true;
    else
      ++var->granted_reads;
    DecWait(opr, ready);
    return true;
  }
  var->q.push_back(VarReq{opr, is_write});
  return false;
}

void Engine::Release(Var *var, bool was_write,
                     std::vector<OprBlock *> *ready) {
  bool destroy = false;
  {
    std::lock_guard<std::mutex> lk(var->m);
    if (was_write)
      var->granted_write = false;
    else
      --var->granted_reads;
    // Drain in arrival order: a write needs exclusivity; reads drain in a
    // batch.  (Reference: VersionedVarBlock queue walk in
    // threaded_engine.cc CompleteReadDependency/CompleteWriteDependency.)
    while (!var->q.empty()) {
      VarReq &front = var->q.front();
      if (front.is_write) {
        if (var->granted_write || var->granted_reads != 0) break;
        var->granted_write = true;
        DecWait(front.opr, ready);
        var->q.pop_front();
        break;  // writer is exclusive
      }
      if (var->granted_write) break;
      ++var->granted_reads;
      DecWait(front.opr, ready);
      var->q.pop_front();
    }
    destroy = var->to_delete && var->q.empty() && !var->granted_write &&
              var->granted_reads == 0;
  }
  if (destroy) delete var;
}

void Engine::Dispatch(OprBlock *opr) {
  if (naive_ || pools_[opr->prop] == nullptr) {
    Execute(opr);
  } else {
    pools_[opr->prop]->Enqueue(opr);
  }
}

void Engine::Push(OprBlock *opr) {
  pending_.fetch_add(1);
  if (naive_) {
    // Synchronous: deps are trivially clear (everything before us already
    // ran on this thread).  Matches NaiveEngine semantics.
    Execute(opr);
    return;
  }
  int ndeps = (int)(opr->const_vars.size() + opr->mutable_vars.size());
  opr->wait.store(ndeps + 1);
  std::vector<OprBlock *> ready;
  for (Var *v : opr->const_vars) Request(v, opr, false, &ready);
  for (Var *v : opr->mutable_vars) Request(v, opr, true, &ready);
  DecWait(opr, &ready);  // the +1 guard
  for (OprBlock *r : ready) Dispatch(r);
}

void Engine::Execute(OprBlock *opr) {
  int64_t t0 = 0;
  bool prof = ProfilerRunning();
  if (prof) t0 = NowUs();
  if (opr->fn) opr->fn();
  if (prof) {
    static std::atomic<int> tid_seq{0};
    thread_local int tid = tid_seq.fetch_add(1);
    ProfilerRecord(opr->name.empty() ? "opr" : opr->name.c_str(), "engine",
                   t0, NowUs(), tid);
  }
  // completion: release deps, possibly readying successors.  Naive mode
  // never called Request() in Push(), so releasing here would underflow
  // granted_reads / clear a never-set granted_write.
  std::vector<OprBlock *> ready;
  if (!naive_) {
    for (Var *v : opr->const_vars) Release(v, false, &ready);
    for (Var *v : opr->mutable_vars) Release(v, true, &ready);
  }
  if (opr->deleter) opr->deleter();
  delete opr;
  for (OprBlock *r : ready) Dispatch(r);
  if (pending_.fetch_sub(1) == 1) {
    std::lock_guard<std::mutex> lk(all_m_);
    all_cv_.notify_all();
  }
}

void Engine::DeleteVar(Var *var) {
  if (naive_) {
    delete var;
    pending_.fetch_add(1);
    pending_.fetch_sub(1);
    return;
  }
  // Push an exclusive (write) op that marks the var dead; the var frees when
  // its queue fully drains (reference Engine::DeleteVariable semantics).
  OprBlock *opr = new OprBlock();
  opr->fn = [var] { var->to_delete = true; };
  opr->mutable_vars.push_back(var);
  opr->name = "delete_var";
  Push(opr);
}

void Engine::WaitForVar(Var *var) {
  if (naive_) return;
  std::mutex m;
  std::condition_variable cv;
  bool done = false;
  OprBlock *opr = new OprBlock();
  opr->fn = [&] {
    std::lock_guard<std::mutex> lk(m);
    done = true;
    cv.notify_all();
  };
  opr->const_vars.push_back(var);
  opr->name = "wait_for_var";
  Push(opr);
  std::unique_lock<std::mutex> lk(m);
  cv.wait(lk, [&] { return done; });
}

void Engine::WaitAll() {
  if (naive_) return;
  std::unique_lock<std::mutex> lk(all_m_);
  all_cv_.wait(lk, [this] { return pending_.load() == 0; });
}

}  // namespace mxtpu

/* ---------------- C ABI ---------------- */

extern "C" {

MXTPUVarHandle mxtpu_var_new(void) {
  return (MXTPUVarHandle)::mxtpu::Engine::Get()->NewVar();
}

void mxtpu_var_delete(MXTPUVarHandle var) {
  ::mxtpu::Engine::Get()->DeleteVar((::mxtpu::Var *)var);
}

void mxtpu_push(MXTPUFn fn, void *param, MXTPUFn deleter,
                const MXTPUVarHandle *const_vars, int n_const,
                const MXTPUVarHandle *mutable_vars, int n_mutable,
                int priority, int prop, const char *opr_name) {
  auto *opr = new ::mxtpu::OprBlock();
  if (fn) opr->fn = [fn, param] { fn(param); };
  if (deleter) opr->deleter = [deleter, param] { deleter(param); };
  for (int i = 0; i < n_const; ++i)
    opr->const_vars.push_back((::mxtpu::Var *)const_vars[i]);
  for (int i = 0; i < n_mutable; ++i)
    opr->mutable_vars.push_back((::mxtpu::Var *)mutable_vars[i]);
  opr->priority = priority;
  opr->prop = (prop >= 0 && prop <= 2) ? prop : 0;
  if (opr_name) opr->name = opr_name;
  ::mxtpu::Engine::Get()->Push(opr);
}

void mxtpu_wait_for_var(MXTPUVarHandle var) {
  ::mxtpu::Engine::Get()->WaitForVar((::mxtpu::Var *)var);
}

void mxtpu_wait_all(void) { ::mxtpu::Engine::Get()->WaitAll(); }

int mxtpu_engine_type(void) {
  return ::mxtpu::Engine::Get()->naive() ? 1 : 0;
}

int mxtpu_engine_num_workers(void) {
  return ::mxtpu::Engine::Get()->num_workers();
}

long mxtpu_engine_pending(void) { return ::mxtpu::Engine::Get()->pending(); }

const char *mxtpu_version(void) { return "0.1.0"; }

}  // extern "C"
