"""Deterministic fault injection for the async runtime.

Production-scale training is defined by how the system behaves when
things fail, so failure must be a *testable* code path: this module is a
seeded, per-site fault registry that the runtime consults at the places
where real systems actually break —

========================  ==================================================
site                      planted at
========================  ==================================================
``engine.op``             dependency-engine op execution (``engine.push``)
``kvstore.send``          PS wire send (``kvstore_async._send_msg``)
``kvstore.recv``          PS wire receive (``kvstore_async._recv_msg``)
``kvstore.call``          worker RPC attempt (``AsyncClient._call``)
``kvstore.server_kill``   PS server dispatch entry (``AsyncServer.dispatch``)
                          — a fired rule KILLS that server abruptly (op
                          names are ``s<id>:<role>:<op>`` so ``match`` can
                          target e.g. ``s0:primary:push``)
``kvstore.repl_drop``     primary→follower replication send (one lost
                          stream frame; re-sent and deduped by log seqno)
``kvstore.repl_delay``    primary→follower replication send (stretches
                          the replication-lag window)
``checkpoint.write``      sharded + two-file checkpoint writes
``serving.admit``         serving request admission
                          (``serving.Scheduler.submit``; ``name`` is the
                          model, so ``match`` can shed one tenant)
``serving.dispatch``      serving batch dispatch, just before the device
                          call (``name`` is ``<model>:<bucket>``; retried
                          ``MXNET_TPU_SERVING_RETRIES`` times, then failed
                          requests fail over to a peer replica)
``kvstore.resize_drop``   elastic re-striping transfer/cutover steps
                          (``elastic.ResizePlan``; ``name`` is
                          ``prepare:<key>`` / ``commit:<shard>`` — a fired
                          rule aborts the plan cleanly at the old epoch,
                          no key orphaned)
``serving.scale``         serving-group scale action entry
                          (``ReplicaGroup.grow``/``shrink``; ``name`` is
                          ``grow:<group>`` / ``shrink:<group>`` — a fired
                          rule aborts the action before any membership
                          change)
``serving.decode``        generation decode-step dispatch, just before the
                          device call (``GenerationScheduler``; ``name`` is
                          ``<model>:<bucket>``; retried
                          ``MXNET_TPU_SERVING_RETRIES`` times — cache
                          writes happen only after a successful step, so a
                          retry can never corrupt another sequence's
                          blocks)
``serving.route``         replica selection in the KV-affinity router
                          (``serving.routing.KVAffinityRouter``; fires
                          once per candidate replica, ``name`` is
                          ``<model>:<replica index>`` — a fired rule
                          makes THAT replica unroutable for this
                          attempt, so ``drop``/``raise`` drill the
                          spill-to-peer and re-prefill fallback paths;
                          ``delay`` stretches the routing step)
``serving.kv_alloc``      paged KV-cache block allocation
                          (``PagedKVCache.allocate``; ``name`` is the
                          sequence id; ``raise``/``drop`` surface as the
                          typed 429 ``CacheExhaustedError`` path, ``delay``
                          stretches the admission window)
``storage.write``         durable-state file write (``durable.
                          atomic_write_bytes`` — snapshot shards,
                          manifests, fit-meta sidecars; ``name`` is the
                          destination path).  ``corrupt`` is a torn
                          write / bit flip in the payload about to hit
                          disk, ``drop`` is a full disk
                          (``OSError(ENOSPC)``), ``raise`` a failed
                          write, ``delay`` a slow fsync —
                          ``chaos.corrupt_file`` with this site is the
                          post-commit bit-rot counterpart
``data.read``             RecordIO record read (``MXRecordIO.read``;
                          ``name`` is the stream's uri).  ``corrupt``
                          garbles the record header so the magic check
                          trips; ``drop`` raises the typed
                          ``CorruptMessageError`` the production
                          skip-and-count handler catches; ``delay``
                          stretches the stream-stall window
``ops.fused``             fused-kernel variant dispatch
                          (``ops.registry``; ``name`` is
                          ``<op>:<variant>``).  ``drop``/``raise`` fire
                          inside the variant path, so the dispatch seam
                          falls back to stock exactly once and books
                          ``ops_fused_fallback_total``; ``corrupt``
                          garbles the variant's output bytes as seen by
                          the parity harness (``ops/fused/parity.py``),
                          which must catch the mismatch — the
                          falsifiability drill for the whole tier
========================  ==================================================

Four failure modes:

* ``raise`` — raise :class:`ChaosError` at the site (a crashed op / a
  failed write).
* ``drop`` — raise the site's *native* loss exception (connection reset
  on send, EOF on recv, socket timeout on call) so the production retry
  path — not a test-only path — handles it.  At ``engine.op`` /
  ``checkpoint.write`` a drop silently skips the work (a lost write).
* ``delay`` — sleep (bounded, sub-second by default) to surface
  ordering and timeout windows.
* ``corrupt`` — deterministically flip bytes in the payload passing
  through the site (wire frames, checkpoint files).

Every rule owns a ``random.Random(seed)``, so a failure schedule is a
pure function of (seed, visit sequence): a test that proves recovery
under 30% message drop proves the *same* schedule on every run.

Configuration is either programmatic::

    with chaos.inject("kvstore.send", "drop", prob=0.3, seed=7):
        ...   # every _send_msg flips a seeded coin

or environment-driven for soak runs (no code changes)::

    MXNET_TPU_CHAOS="kvstore.send:drop:0.3:seed=7,engine.op:raise:0.05"

The hot-path cost when idle is one dict lookup per site visit.
"""

from __future__ import annotations

import os
import random
import threading
import time

from .observability import metrics as _metrics

__all__ = ["ChaosError", "ChaosDrop", "inject", "clear", "visit",
           "corrupt_file", "rules", "SITES"]

_M_FIRED = _metrics.counter(
    "chaos_fired_total", "Chaos-injection rules fired, by site", ["site"])

SITES = frozenset({
    "engine.op", "kvstore.send", "kvstore.recv", "kvstore.call",
    "kvstore.server_kill", "kvstore.repl_drop", "kvstore.repl_delay",
    "kvstore.resize_drop", "checkpoint.write", "storage.write",
    "serving.admit", "serving.dispatch", "serving.scale",
    "serving.decode", "serving.kv_alloc", "serving.route", "data.read",
    "ops.fused",
})


class ChaosError(RuntimeError):
    """Injected failure (mode=``raise``)."""


class ChaosDrop(ChaosError):
    """Injected loss at a site with no native loss exception — the
    instrumentation point treats it as 'the work silently never
    happened' (skip the engine op, skip the checkpoint write)."""


def _drop_exc(site):
    """The exception a real loss at this site would produce, so drops
    exercise the production recovery path rather than a bespoke one."""
    import socket

    if site == "kvstore.send":
        return ConnectionResetError("chaos: dropped on send")
    if site == "kvstore.recv":
        return EOFError("chaos: dropped on receive")
    if site == "kvstore.call":
        return socket.timeout("chaos: call timed out")
    if site == "kvstore.repl_drop":
        return ConnectionResetError("chaos: replication frame dropped")
    if site == "kvstore.resize_drop":
        return ConnectionResetError("chaos: resize transfer dropped")
    if site == "data.read":
        from . import base

        return base.CorruptMessageError("chaos: record dropped mid-read")
    if site == "storage.write":
        import errno

        return OSError(errno.ENOSPC, "chaos: no space left on device")
    return ChaosDrop("chaos: dropped at %s" % site)


class _Rule:
    """One injection rule; owns its seeded RNG so the failure schedule
    is deterministic per (seed, visit sequence)."""

    __slots__ = ("site", "mode", "prob", "seed", "delay", "match",
                 "limit", "fires", "visits", "_rng")

    def __init__(self, site, mode, prob=1.0, seed=0, delay=0.05,
                 match=None, limit=None):
        if site not in SITES:
            raise ValueError("unknown chaos site %r (have %s)"
                             % (site, sorted(SITES)))
        if mode not in ("drop", "delay", "raise", "corrupt"):
            raise ValueError("unknown chaos mode %r" % mode)
        self.site = site
        self.mode = mode
        self.prob = float(prob)
        self.seed = int(seed)
        self.delay = float(delay)
        self.match = match
        self.limit = None if limit is None else int(limit)
        self.fires = 0
        self.visits = 0
        self._rng = random.Random(self.seed)

    def should_fire(self, name):
        if self.match is not None and self.match not in (name or ""):
            return False
        if self.limit is not None and self.fires >= self.limit:
            return False
        self.visits += 1
        # always draw, even for prob=1: keeps the schedule a function of
        # the visit sequence alone, independent of the prob value
        if self._rng.random() >= self.prob:
            return False
        self.fires += 1
        return True

    def corrupt_bytes(self, payload):
        """Flip a few deterministic bytes; never changes the length (a
        truncation would be a different failure class — framing)."""
        buf = bytearray(payload)
        if not buf:
            return bytes(buf)
        for _ in range(min(8, len(buf))):
            pos = self._rng.randrange(len(buf))
            buf[pos] ^= 0x5A
        return bytes(buf)

    def describe(self):
        return {"site": self.site, "mode": self.mode, "prob": self.prob,
                "seed": self.seed, "visits": self.visits,
                "fires": self.fires}


_lock = threading.Lock()
_rules = []          # programmatic rules, in registration order
_env_rules = []      # rules parsed from MXNET_TPU_CHAOS
_env_cache = None    # the env string the cached _env_rules came from


def _parse_env(value):
    """``site:mode[:prob][:key=val]...`` comma-separated."""
    out = []
    for part in value.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 2:
            raise ValueError(
                "MXNET_TPU_CHAOS entry %r: need at least site:mode" % part)
        site, mode = fields[0], fields[1]
        kwargs = {}
        for extra in fields[2:]:
            if "=" in extra:
                k, v = extra.split("=", 1)
                if k not in ("seed", "delay", "match", "limit", "prob"):
                    raise ValueError(
                        "MXNET_TPU_CHAOS entry %r: unknown key %r"
                        % (part, k))
                kwargs[k] = v if k == "match" else float(v)
            else:
                kwargs["prob"] = float(extra)
        for k in ("seed", "limit"):
            if k in kwargs:
                kwargs[k] = int(kwargs[k])
        out.append(_Rule(site, mode, **kwargs))
    return out


def _active_rules(site):
    """Rules for one site, env rules refreshed lazily so tests and jobs
    can (re)configure without re-importing anything."""
    global _env_rules, _env_cache

    env = os.environ.get("MXNET_TPU_CHAOS")
    if env != _env_cache:
        with _lock:
            if env != _env_cache:
                _env_rules = _parse_env(env) if env else []
                _env_cache = env
    return [r for r in _rules + _env_rules if r.site == site]


class _Injection:
    """Handle returned by :func:`inject`; context manager removes the
    rule on exit.  ``.fires``/``.visits`` expose the realized schedule."""

    def __init__(self, rule):
        self._rule = rule

    @property
    def fires(self):
        return self._rule.fires

    @property
    def visits(self):
        return self._rule.visits

    def remove(self):
        with _lock:
            if self._rule in _rules:
                _rules.remove(self._rule)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.remove()
        return False


def inject(site, mode, prob=1.0, seed=0, delay=0.05, match=None,
           limit=None):
    """Register an injection rule; returns a removable handle that is
    also a context manager.

    ``prob``   per-visit fire probability (seeded coin).
    ``seed``   the rule's private RNG seed — the whole failure schedule.
    ``delay``  sleep seconds for ``delay`` mode (keep sub-second in tests).
    ``match``  only fire when the site's op name contains this substring.
    ``limit``  stop firing after this many injections.
    """
    rule = _Rule(site, mode, prob=prob, seed=seed, delay=delay,
                 match=match, limit=limit)
    with _lock:
        _rules.append(rule)
    return _Injection(rule)


def clear():
    """Remove every programmatic rule (env rules follow the env var)."""
    with _lock:
        del _rules[:]


def rules():
    """Snapshot of active rules (programmatic + env) for observability."""
    env_sites = _active_rules  # force env refresh via any site
    _ = env_sites("engine.op")
    with _lock:
        return [r.describe() for r in _rules + _env_rules]


def visit(site, payload=None, name=None):
    """Consult the registry at an instrumented site.

    May sleep (``delay``), raise (``raise`` → :class:`ChaosError`;
    ``drop`` → the site's native loss exception), or return a corrupted
    copy of ``payload`` (``corrupt``, only when ``payload`` is bytes-like
    — corrupt rules are inert at sites that pass no payload).
    Returns ``payload`` (possibly transformed) otherwise.
    """
    matched = _active_rules(site)
    if not matched:
        return payload
    with _lock:
        for rule in matched:
            if rule.mode == "corrupt" and payload is None:
                continue
            if not rule.should_fire(name):
                continue
            _M_FIRED.labels(site).inc()
            if rule.mode == "delay":
                time.sleep(rule.delay)
            elif rule.mode == "raise":
                raise ChaosError(
                    "chaos: injected failure at %s (op=%r, seed=%d, "
                    "fire #%d)" % (site, name, rule.seed, rule.fires))
            elif rule.mode == "drop":
                raise _drop_exc(site)
            else:  # corrupt
                payload = rule.corrupt_bytes(payload)
    return payload


def corrupt_file(site, path):
    """File-payload counterpart of ``visit``'s corrupt mode: when a
    corrupt rule on ``site`` fires, garble the largest file under
    ``path`` (a file or a directory tree) in place.  Returns the path
    corrupted, or None."""
    matched = [r for r in _active_rules(site) if r.mode == "corrupt"]
    if not matched:
        return None
    with _lock:
        rule = next((r for r in matched if r.should_fire(None)), None)
        if rule is None:
            return None
        _M_FIRED.labels(site).inc()
        target = path
        if os.path.isdir(path):
            best = None
            for root, _dirs, files in os.walk(path):
                for f in files:
                    p = os.path.join(root, f)
                    try:
                        size = os.path.getsize(p)
                    except OSError:
                        continue
                    if best is None or size > best[0]:
                        best = (size, p)
            if best is None:
                return None
            target = best[1]
        try:
            with open(target, "r+b") as f:
                data = f.read()
                f.seek(0)
                f.write(rule.corrupt_bytes(data))
        except OSError:
            return None
        return target
