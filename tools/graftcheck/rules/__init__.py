"""Rule registry: ``ALL_RULES`` maps rule name → check function.

A check function takes a :class:`~tools.graftcheck.core.Project` and
yields :class:`~tools.graftcheck.core.Finding` objects.  Adding a rule =
adding a module here and one entry below (see
docs/how_to/static_analysis.md "Adding a rule").
"""

from .atomic_write import check_atomic_write
from .envvars import check_env_var_registry
from .chaos_sites import check_chaos_sites
from .metrics_discipline import check_metrics_hot_path
from .typed_errors import check_typed_errors
from .lock_discipline import check_lock_discipline
from .jit_purity import check_jit_purity
from .golden_metrics import check_golden_metrics
from .fused_parity import check_fused_parity

ALL_RULES = {
    "env-var-registry": check_env_var_registry,
    "chaos-site": check_chaos_sites,
    "metrics-hot-path": check_metrics_hot_path,
    "typed-errors": check_typed_errors,
    "lock-discipline": check_lock_discipline,
    "jit-purity": check_jit_purity,
    "golden-metrics": check_golden_metrics,
    "atomic-write": check_atomic_write,
    "fused-parity": check_fused_parity,
}

__all__ = ["ALL_RULES"]
