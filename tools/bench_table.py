"""Capture the full perf table vs the reference's published P100 numbers.

Reproduces BENCH_TABLE.md: inference throughput for the six
benchmark_score networks (reference docs/how_to/perf.md:116-147) and
training throughput rows (perf.md:181-188 +
example/image-classification/README.md:145-156).

Run on the TPU chip:  python tools/bench_table.py [--out BENCH_TABLE.md]

Also the perf TREND GATE over the driver-verified history
(``python tools/bench_table.py --trend`` / ``make bench-trend``): pure
JSON over ``BENCH_r*.json`` — no accelerator, no fit — comparing the
newest round's tracked keys against the best prior round and exiting
nonzero on a >10% regression.
"""

import argparse
import json
import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(_HERE)
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "examples", "image_classification"))

import numpy as np

# P100 columns from BASELINE.md (reference docs/how_to/perf.md)
P100_INFER = {"alexnet": 4883.77, "vgg": 854.4, "inception-bn": 1197.74,
              "inception-v3": 493.72, "resnet-50": 713.17,
              "resnet-152": 294.17}
P100_TRAIN = {"resnet-50": 181.53, "inception-v3": 129.98}
K80_TRAIN = {"resnet-18": 185.0, "resnet-50": 109.0, "resnet-152": 57.0,
             "inception-bn": 152.0}

# trend-gate tracked keys: True = higher is better.  A key is only
# gated when BOTH the newest round and some prior round carry it — the
# bench schema is additive (older rows simply lack mfu/goodput_ratio)
TREND_KEYS = {"value": True, "tokens_per_sec": True, "mfu": True,
              "goodput_ratio": True,
              "step_ms_p50": False, "step_ms_p99": False,
              # schema-5 serving keys (BENCH_SERVING=1 rounds)
              "requests_per_sec": True, "batch_occupancy": True,
              "request_ms_p50": False, "request_ms_p99": False,
              # schema-8 observability keys (BENCH_SERVING=1 rounds)
              "slo_availability": True,
              "request_trace_overhead_pct": False,
              # schema-9 continuous-training keys (BENCH_CONTINUOUS=1)
              "stream_mb_per_sec": True, "data_wait_pct": False,
              "swap_downtime_ms": False,
              # schema-10 generation keys (BENCH_GENERATE=1 rounds);
              # "tokens_per_sec" above already covers the headline
              "tokens_per_sec_per_user": True,
              "inter_token_ms_p99": False, "prefill_ms_p50": False,
              "kv_cache_occupancy": True,
              # schema-11 wire keys (BENCH_WIRE=1 rounds): bytes and
              # codec share are gated down-is-good — the binary wire
              # must SHRINK them; fewer RPCs per flush would also be
              # an improvement, but p50 fan-out is topology-bound, so
              # it rides the same down-is-good direction as a canary
              "kv_bytes_per_step": False,
              "kv_header_overhead_pct": False,
              "kv_codec_ms_share": False,
              "kv_rpcs_per_flush_p50": False,
              # schema-12 fairness keys (BENCH_FAIRNESS=1 rounds):
              # isolation ratio is down-is-good (1.0 = the saturating
              # tenant cost the innocent one nothing); shed rate and
              # affinity hits are up-is-good — the quota biting and
              # sessions landing on their KV blocks
              "fairness_p99_ratio": False,
              "quota_shed_rate": True,
              "kv_affinity_hit_ratio": True,
              # schema-13 wire keys (BENCH_WIRE=1 rounds): compression
              # ratio is up-is-good (dense bytes in / wire bytes out),
              # coalesce savings count the RPCs the fused push_pull
              # never sent — also up-is-good
              "kv_compress_ratio": True,
              "kv_coalesce_rpcs_saved": True,
              # schema-14 durability keys (BENCH_SNAPSHOT=1 rounds):
              # all three are down-is-good latencies; frozen_ms is the
              # one that blocks training, so a regression there is a
              # direct goodput loss
              "snapshot_save_ms": False,
              "snapshot_restore_ms": False,
              "snapshot_frozen_ms": False,
              # schema-15 fused-kernel keys (BENCH_KERNELS=1 rounds):
              # kernel latencies are down-is-good; decode tokens/sec is
              # up-is-good.  fused_opt_step_ms is the lane's measured
              # CPU claim, so a regression there un-earns the fusion;
              # stock_opt_step_ms is the eager comparator and is NOT
              # trended (it measures dispatch overhead, not our code)
              "attn_prefill_ms": False,
              "paged_decode_tokens_per_sec": True,
              "fused_opt_step_ms": False,
              # schema-16 memory keys (BENCH_MEMORY=1 rounds): the
              # ledger reconcile is the gate (1.0 = books explain the
              # live-array truth), occupancy at peak hold and device
              # headroom are both up-is-good capacity signals
              "memory_ledger_reconciles": True,
              "kv_cache_occupancy_pct": True,
              "memory_headroom_ratio": True}
TREND_TOLERANCE = 0.10


def load_bench_rounds(root=ROOT):
    """The ``BENCH_r*.json`` parsed rows as a round-sorted
    ``[(round, row)]`` list.  Zero-value captures (tunnel-down rounds —
    an outage is not a perf baseline) are dropped; rounds sharing a
    ``git_sha`` are re-measurements of one commit, so only the
    best-value one stands (schema<3 rows carry no sha and each stand
    alone)."""
    import glob
    import re

    rounds = []
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                row = json.load(f).get("parsed", {})
        except Exception:
            continue
        try:
            if float(row.get("value", 0) or 0) <= 0.0:
                continue
        except (TypeError, ValueError):
            continue
        rounds.append((int(m.group(1)), row))
    rounds.sort()
    best_by_sha = {}
    for n, row in rounds:
        sha = row.get("git_sha")
        key = sha if sha and sha != "unknown" else "round-%d" % n
        prev = best_by_sha.get(key)
        if prev is None or float(row.get("value", 0)) > float(
                prev[1].get("value", 0)):
            best_by_sha[key] = (n, row)
    return sorted(best_by_sha.values())


def trend_gate(rounds=None, tolerance=TREND_TOLERANCE):
    """Gate the newest round against the best prior value of every
    tracked key.  Returns ``(ok, report_lines)``; ``ok`` is False when
    any key shared by both sides regresses beyond ``tolerance`` in its
    bad direction (throughput/mfu/goodput down, latency up)."""
    if rounds is None:
        rounds = load_bench_rounds()
    lines = []
    if len(rounds) < 2:
        lines.append("trend: %d usable round(s) — nothing to compare"
                     % len(rounds))
        return True, lines
    latest_n, latest = rounds[-1]
    prior = rounds[:-1]
    ok = True
    for key in sorted(TREND_KEYS):
        higher_better = TREND_KEYS[key]
        try:
            cur = float(latest[key])
        except (KeyError, TypeError, ValueError):
            continue
        vals = []
        for n, row in prior:
            try:
                vals.append((float(row[key]), n))
            except (KeyError, TypeError, ValueError):
                continue
        if not vals:
            lines.append("trend %-16s r%02d %.6g (new key; no prior "
                         "round carries it)" % (key, latest_n, cur))
            continue
        best, best_n = max(vals) if higher_better else min(vals)
        if higher_better:
            regressed = best > 0 and cur < best * (1.0 - tolerance)
        else:
            regressed = cur > best * (1.0 + tolerance)
        delta = (cur / best - 1.0) if best else 0.0
        lines.append("trend %-16s r%02d %.6g vs best r%02d %.6g "
                     "(%+.1f%%)%s" % (key, latest_n, cur, best_n, best,
                                      100.0 * delta,
                                      "  REGRESSED" if regressed else ""))
        if regressed:
            ok = False
    return ok, lines


def bench_train(network, batch, dtype, steps=20, num_layers=None,
                stem=None):
    import jax
    import mxnet_tpu  # noqa: F401
    from jax.sharding import Mesh
    from mxnet_tpu import models
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    kwargs = {"dtype": dtype}
    image_shape = (3, 299, 299) if network == "inception-v3" else (3, 224, 224)
    if num_layers:
        kwargs["num_layers"] = num_layers
    if network.startswith("resnet"):
        kwargs["layout"] = "NHWC"  # TPU-preferred; others are NCHW graphs
        if stem:
            kwargs["stem"] = stem
    sym = models.get_symbol(network, num_classes=1000,
                            image_shape=image_shape, **kwargs)
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    tr = ShardedTrainer(
        sym, mesh, data_shapes={"data": (batch,) + image_shape},
        label_shapes={"softmax_label": (batch,)},
        momentum=0.9, learning_rate=0.1, wd=1e-4, rescale_grad=1.0 / batch)
    params, moms, aux = tr.init(seed=0)
    data = tr.place_batch({
        "data": np.random.uniform(-1, 1, (batch,) + image_shape)
        .astype(np.float32),
        "softmax_label": np.random.randint(0, 1000, (batch,))
        .astype(np.float32)})
    step = tr.step_fn()
    key = __import__("jax").random.PRNGKey(0)

    def sync(tree):
        leaf = __import__("jax").tree_util.tree_leaves(tree)[0]
        return np.asarray(__import__("jax").numpy.ravel(leaf)[0])

    outs, params, moms, aux = step(params, moms, aux, data, key)
    sync(outs)
    t0 = time.perf_counter()
    for _ in range(steps):
        outs, params, moms, aux = step(params, moms, aux, data, key)
    sync(outs)
    return batch * steps / (time.perf_counter() - t0)


def bench_transformer_row(extra_env=None):
    """Run the transformer-LM bench (bench.py BENCH_MODEL=transformer —
    one implementation, reused) and return its parsed JSON line.

    Goes through bench.py's GUARDED entry (no BENCH_INNER): the guard
    owns the wedged-tunnel kill (process group, grandchild pipes) and the
    silent-CPU-fallback detection; this wrapper only parses.  Never
    raises — a failure becomes an {"error": ...} row so the already-
    captured table still renders."""
    import subprocess

    env = dict(os.environ, BENCH_MODEL="transformer", **(extra_env or {}))
    try:
        r = subprocess.run([sys.executable, os.path.join(ROOT, "bench.py")],
                           capture_output=True, text=True, env=env,
                           timeout=1200)
    except subprocess.TimeoutExpired:
        return {"error": "bench.py guard did not return within 1200s"}
    except Exception as exc:
        return {"error": repr(exc)[:200]}
    try:
        row = json.loads(r.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": (r.stderr or "no output").strip()[-200:]}
    if row.get("tunnel_down") or float(row.get("value", 0)) <= 0:
        return {"error": row.get("error", "bench reported zero throughput")}
    return row


def _capture_quantize_bench(script, metric_prefix, extra_args=()):
    """Run an examples/quantize_*.py --benchmark subprocess and parse its
    {fp32, bf16, int8} JSON lines.  A partial capture (crash after the
    fp32 line) must not render fabricated 0.0 rows as measurements, so
    anything short of all three tags returns {'error': ...}."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, os.path.join(ROOT, "examples", script),
             "--benchmark", "--tpus", "1", *extra_args],
            capture_output=True, text=True, timeout=1800, cwd=ROOT)
    except subprocess.TimeoutExpired:
        return {"error": "%s --benchmark timed out" % script}
    rows = {}
    for line in r.stdout.splitlines():
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if str(d.get("metric", "")).startswith(metric_prefix):
            rows[d["metric"].rsplit("_", 1)[1]] = float(d["value"])
    if not {"fp32", "int8", "bf16"}.issubset(rows):
        return {"error": "partial capture %s: %s" % (
            sorted(rows), (r.stderr or "no output").strip()[-250:])}
    return rows


def bench_int8_rows():
    """int8 PTQ ResNet-50 inference vs fp32/bf16 on the same device
    (examples/quantize_resnet.py --benchmark; the chip-measured MODEL
    row for the op-level int8 claim).  Returns {tag: img_s} or
    {'error': ...}."""
    return _capture_quantize_bench("quantize_resnet.py", "resnet50_infer_")


def bench_lm_int8_rows(batch=32, seq=1024):
    """int8 PTQ transformer-LM inference rows
    (examples/quantize_transformer.py --benchmark): fp32, bf16,
    int8 full (FFN pairs + vocab head quantized), and int8sel (vocab
    head only — the recommended configuration; FFN int8 measured to
    regress at these shapes, docs/PERF.md "int8 on the transformer").
    Attention runs bf16 in every row (it lives inside the fused op).
    b32: the throughput-oriented inference batch (the b8 bench geometry
    is attention/dispatch-bound enough that the int8 delta sits inside
    tunnel noise)."""
    rows = _capture_quantize_bench(
        "quantize_transformer.py", "lm_infer_",
        ("--batch", str(batch), "--seq", str(seq)))
    if "error" not in rows:
        rows["batch"], rows["seq"] = batch, seq
    return rows


def bench_moe_rows():
    """Single-chip MoE row: the MoE transformer (experts folded to one
    device; routing/capacity/dispatch execute for real) vs the dense FFN
    at the same geometry.  T=1024: larger totals exceed what the
    tunnel's remote-compile helper will build for the MoE graph (an
    environment limit — the indexed dispatch itself is O(T*E))."""
    moe = bench_transformer_row({"BENCH_FFN": "moe", "BENCH_SEQ": "1024"})
    dense = bench_transformer_row({"BENCH_SEQ": "1024"})
    return {"moe": moe, "dense": dense}


def render(infer_rows, train_rows, chip, lm_row=None, int8_rows=None,
           moe_rows=None, lm_int8_rows=None):
    """Render the captured rows as the BENCH_TABLE.md markdown
    (pure function so the formatting rules are unit-testable:
    None renders as fail, ratios only from real bf16 values)."""
    lines = [
        "# Perf table — one %s chip vs the reference's published GPUs" % chip,
        "",
        "Generated by `python tools/bench_table.py` (synthetic data, same",
        "methodology as the reference's `benchmark_score.py` / "
        "`train_imagenet.py --benchmark`).",
        "Every number below is reproducible from the machine-readable",
        "capture written alongside (`BENCH_TABLE.json`, same run).  The",
        "driver-verified headline (`BENCH_r*.json`, from `bench.py`) is",
        "the same config as the resnet-50 b128 bf16 **s2d** training row;",
        "bench.py's longer captures (50 steps, repeated) land a few",
        "percent above this table's 20-step best-of-2 samples — the",
        "tunneled device's run-to-run spread (±5-10%, docs/PERF.md).",
        "",
        "## Inference (images/sec; P100 column is batch 32)",
        "",
        "| network | batch | fp32 | bf16 | P100 fp32 | bf16 vs P100 |",
        "|---|---|---|---|---|---|",
    ]
    for r in infer_rows:
        p100 = P100_INFER.get(r["net"])
        bf16 = r.get("bfloat16")
        ratio = ("%.1f×" % (bf16 / p100)) if (bf16 is not None and p100) \
            else "—"
        lines.append("| %s | %d | %s | %s | %.2f | %s |" % (
            r["net"], r.get("batch", 32),
            "%.1f" % r["float32"] if r["float32"] is not None else "fail",
            "%.1f" % bf16 if bf16 is not None else "fail",
            p100 or 0.0, ratio))
    big_alex = next((r for r in infer_rows
                     if r["net"] == "alexnet" and r.get("batch") == 256
                     and r.get("bfloat16") is not None), None)
    if big_alex:
        lines += [
            "",
            "Batch-32 alexnet (and to a lesser degree every sub-2ms step)",
            "is bound by per-call dispatch latency on the tunneled PJRT",
            "device, not compute — at batch 256 the same model reaches "
            "%.1f×" % (big_alex["bfloat16"] / P100_INFER["alexnet"]),
            "the P100 once the step amortizes the round-trip.",
        ]
    lines += [
        "",
        "## Training (images/sec)",
        "",
        "| network | batch | dtype | stem | img/s | P100 fp32 | K80 fp32 "
        "| vs P100 |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in train_rows:
        p100 = P100_TRAIN.get(r["net"])
        k80 = K80_TRAIN.get(r["net"])
        v = r["img_s"]
        ratio = ("%.1f×" % (v / p100)) if (v is not None and p100) else "—"
        lines.append("| %s | %d | %s | %s | %s | %s | %s | %s |" % (
            r["net"], r["batch"], r["dtype"], r.get("stem") or "—",
            "%.1f" % v if v is not None else "fail",
            "%.2f" % p100 if p100 else "—",
            "%.0f" % k80 if k80 else "—", ratio))
    if int8_rows and "error" not in int8_rows:
        bf16 = int8_rows.get("bf16")
        i8 = int8_rows.get("int8")
        lines += [
            "",
            "## int8 PTQ inference (model-level; resnet-50 b128 NHWC)",
            "",
            "| path | img/s | vs bf16 |",
            "|---|---|---|",
            "| fp32 | %.1f | — |" % int8_rows.get("fp32", 0.0),
            "| bf16 | %.1f | 1.0× |" % (bf16 or 0.0),
            "| int8 (PTQ: BN fold + symmetric calib, "
            "`contrib.quantization`) | %.1f | %s |" % (
                i8 or 0.0,
                "%.2f×" % (i8 / bf16) if (i8 and bf16) else "—"),
            "",
            "Accuracy: the PTQ pipeline is gated end-to-end in",
            "`tests/test_examples_round3.py::test_quantize_resnet_example`",
            "(int8 top-1 within a point of fp32 on the trained gate",
            "model).  Capture: `examples/quantize_resnet.py --benchmark`.",
        ]
    elif int8_rows:
        lines += ["", "int8 row FAILED: %s" % int8_rows["error"][:200]]
    if lm_int8_rows and "error" not in lm_int8_rows:
        bf16 = lm_int8_rows.get("bf16")
        i8 = lm_int8_rows.get("int8")
        lines += [
            "",
            "## int8 PTQ inference — transformer LM (12L d1024, b%d "
            "T%d)" % (lm_int8_rows.get("batch", 32),
                      lm_int8_rows.get("seq", 1024)),
            "",
            "| path | tokens/s | vs bf16 |",
            "|---|---|---|",
            "| fp32 | %.0f | — |" % lm_int8_rows.get("fp32", 0.0),
            "| bf16 | %.0f | 1.0× |" % (bf16 or 0.0),
            "| int8 full (PTQ FFN + vocab head) | %.0f | %s |" % (
                i8 or 0.0,
                "%.2f×" % (i8 / bf16) if (i8 and bf16) else "—"),
        ]
        i8s = lm_int8_rows.get("int8sel")
        if i8s:
            lines.append(
                "| int8 selective (vocab head only — recommended) "
                "| %.0f | %s |" % (
                    i8s, "%.2f×" % (i8s / bf16) if bf16 else "—"))
        lines += [
            "",
            "Attention runs bf16 in every row (it lives inside the",
            "fused op).  FFN int8 regresses at these shapes — the",
            "decomposition is in docs/PERF.md \"int8 on the",
            "transformer\".  Accuracy gated in",
            "`tests/test_examples_round3.py::`",
            "`test_quantize_transformer_example`.  Capture:",
            "`examples/quantize_transformer.py --benchmark --batch 32`.",
        ]
    elif lm_int8_rows:
        lines += ["", "int8 LM row FAILED: %s"
                  % lm_int8_rows["error"][:200]]
    if moe_rows and "error" not in moe_rows.get("moe", {"error": 1}) \
            and "error" not in moe_rows.get("dense", {"error": 1}):
        m = moe_rows["moe"]
        d = moe_rows["dense"]
        mc, dc = m.get("config", {}), d.get("config", {})
        ratio = (m["value"] / d["value"]) if d.get("value") else None
        lines += [
            "",
            "## Mixture-of-Experts LM training (single chip: experts",
            "folded to one device, routing/capacity/dispatch execute)",
            "",
            "| ffn | params (active) | tokens/s | MFU (active) "
            "| vs dense |",
            "|---|---|---|---|---|",
            "| dense | %.0fM | %.0f | %.1f%% | 1.0× |" % (
                d.get("n_params", 0) / 1e6, d["value"],
                100 * d.get("mfu", 0.0)),
            "| moe %d-expert top-%d | %.0fM (%.0fM) | %.0f | %.1f%% "
            "| %s |" % (
                mc.get("experts", 0), mc.get("top_k", 0),
                m.get("n_params", 0) / 1e6,
                m.get("n_params_active", 0) / 1e6, m["value"],
                100 * m.get("mfu", 0.0),
                "%.2f×" % ratio if ratio else "—"),
            "",
            "Same %dL d%d T%d b%d geometry; a top-%d-routed token does"
            % (mc.get("layers", 0), mc.get("d_model", 0),
               mc.get("seq", 0), mc.get("batch", 0), mc.get("top_k", 0)),
            "the FFN FLOPs of top_k experts, so `vs dense` reflects the",
            "routing+dispatch overhead.  Capture: `BENCH_MODEL=transformer",
            "BENCH_FFN=moe BENCH_SEQ=%d python bench.py`."
            % mc.get("seq", 0),
        ]
    elif moe_rows:
        lines += ["", "MoE row FAILED: %s" % str(
            moe_rows.get("moe", {}).get("error")
            or moe_rows.get("dense", {}).get("error", ""))[:200]]
    # only a REAL chip capture lands in the table (a silent CPU fallback
    # reports *_cpu_smoke_throughput and must not pose as a TPU row)
    if lm_row and lm_row.get("metric") == "transformer_lm_train_throughput":
        cfg = lm_row.get("config", {})
        lines += [
            "",
            "## Transformer LM training (no reference row: the 2017",
            "reference predates attention models — beyond-parity surface)",
            "",
            "| model | batch | seq | tokens/s | MFU |",
            "|---|---|---|---|---|",
            "| %dL d%d (%.0fM params, Pallas flash attention) "
            "| %d | %d | %.0f | %.1f%% |" % (
                cfg.get("layers", 0), cfg.get("d_model", 0),
                lm_row.get("n_params", 0) / 1e6, cfg.get("batch", 0),
                cfg.get("seq", 0), lm_row["value"],
                100.0 * lm_row.get("mfu", 0.0)),
            "",
            "MFU = tokens/s x (6N + 12·L·T·d) / chip bf16 peak (PaLM",
            "accounting); capture with `BENCH_MODEL=transformer python",
            "bench.py`.",
        ]
    lines += [
        "",
        "Reference sources: `docs/how_to/perf.md:116-147` (P100 inference),",
        "`perf.md:181-188` (P100 training), "
        "`example/image-classification/README.md:145-156` (K80 training).",
        "Training uses the fused fwd+bwd+SGD-momentum sharded step; resnet",
        "rows are NHWC, others NCHW. See docs/PERF.md for the roofline.",
        "",
    ]
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(ROOT, "BENCH_TABLE.md"))
    ap.add_argument("--num-batches", type=int, default=10)
    ap.add_argument("--train-steps", type=int, default=20)
    ap.add_argument("--best-of", type=int, default=2,
                    help="repeat every measurement (inference AND training "
                    "rows) and keep the max — sub-2ms steps over the "
                    "tunneled device see transient dispatch stalls that "
                    "can halve a single capture")
    ap.add_argument("--trend", action="store_true",
                    help="no measurement: gate the BENCH_r*.json history "
                    "— exit 1 if the newest round regresses any tracked "
                    "key beyond --trend-tolerance vs the best prior round")
    ap.add_argument("--trend-tolerance", type=float,
                    default=TREND_TOLERANCE)
    args = ap.parse_args()

    if args.trend:
        ok, lines = trend_gate(tolerance=args.trend_tolerance)
        print("\n".join(lines))
        sys.exit(0 if ok else 1)

    import jax
    import mxnet_tpu as mx
    from benchmark_score import score

    dev = mx.tpu(0) if jax.default_backend() == "tpu" else mx.cpu()
    chip = jax.devices()[0].device_kind

    infer_rows = []
    # (net, batch): batch 32 matches the reference's P100 table; alexnet
    # additionally at 256 because its sub-ms step is per-call-latency
    # bound at 32 (see the table footnote)
    for net, batch in [("alexnet", 32), ("alexnet", 256), ("vgg", 32),
                       ("inception-bn", 32), ("inception-v3", 32),
                       ("resnet-50", 32), ("resnet-152", 32)]:
        row = {"net": net, "batch": batch}
        for dtype in ("float32", "bfloat16"):
            t0 = time.time()
            # best-of keeps any successful sample; one retry round covers
            # the tunnel's sporadic mid-read drop (INTERNAL ... body closed)
            for attempt in (0, 1):
                samples = []
                err = None
                for _ in range(max(args.best_of, 1)):
                    try:
                        samples.append(score(net, dev, batch,
                                             args.num_batches, dtype=dtype))
                    except Exception as exc:
                        err = str(exc)[:200]
                if samples:
                    row[dtype] = max(samples)
                    row.get("err", {}).pop(dtype, None)
                    break
                row[dtype] = None
                row.setdefault("err", {})[dtype] = err
                if attempt == 0:
                    time.sleep(5)
            print("infer %s b%d %s: %s (%.0fs)" % (net, batch, dtype,
                                                   row[dtype],
                                                   time.time() - t0),
                  flush=True)
        infer_rows.append(row)

    # stem column: resnet rows name their stem explicitly so every row is
    # reproducible against bench.py (whose TPU default is s2d) — the
    # bench-default config (resnet-50 b128 bf16 s2d) IS a table row, so
    # BENCH_r*.json and this table can no longer disagree unexplained
    train_cfgs = [
        ("resnet-18", 32, "bfloat16", 18, "conv7"),
        ("resnet-50", 32, "bfloat16", 50, "conv7"),
        ("resnet-50", 32, "float32", 50, "conv7"),
        ("resnet-50", 128, "bfloat16", 50, "conv7"),
        ("resnet-50", 128, "bfloat16", 50, "s2d"),
        ("resnet-152", 32, "bfloat16", 152, "conv7"),
        ("inception-bn", 32, "bfloat16", None, None),
        ("inception-v3", 32, "bfloat16", None, None),
    ]
    train_rows = []
    for net, batch, dtype, layers, stem in train_cfgs:
        t0 = time.time()
        try:
            v = max(bench_train(net, batch, dtype, steps=args.train_steps,
                                num_layers=layers, stem=stem)
                    for _ in range(max(args.best_of, 1)))
        except Exception as exc:
            v = None
            print("train %s FAILED: %s" % (net, str(exc)[:200]), flush=True)
        train_rows.append({"net": net, "batch": batch, "dtype": dtype,
                           "stem": stem, "img_s": v})
        print("train %s b%d %s %s: %s (%.0fs)" % (net, batch, dtype, stem,
                                                  v, time.time() - t0),
              flush=True)

    t0 = time.time()
    lm_row = bench_transformer_row()
    print("transformer LM: %s (%.0fs)" % (lm_row, time.time() - t0),
          flush=True)
    t0 = time.time()
    int8_rows = bench_int8_rows()
    print("int8 resnet-50: %s (%.0fs)" % (int8_rows, time.time() - t0),
          flush=True)
    t0 = time.time()
    lm_int8_rows = bench_lm_int8_rows()
    print("int8 transformer-LM: %s (%.0fs)" % (lm_int8_rows,
                                               time.time() - t0),
          flush=True)
    t0 = time.time()
    moe_rows = bench_moe_rows()
    print("moe transformer: %s (%.0fs)" % (moe_rows, time.time() - t0),
          flush=True)

    table = render(infer_rows, train_rows, chip, lm_row=lm_row,
                   int8_rows=int8_rows, moe_rows=moe_rows,
                   lm_int8_rows=lm_int8_rows)
    with open(args.out, "w") as fh:
        fh.write(table)
    capture = {"chip": chip, "infer": infer_rows, "train": train_rows,
               "transformer_lm": lm_row, "int8": int8_rows,
               "lm_int8": lm_int8_rows, "moe": moe_rows}
    cap_path = os.path.splitext(args.out)[0] + ".json"
    with open(cap_path, "w") as fh:
        json.dump(capture, fh, indent=1, default=str)
    print("wrote", args.out, "and", cap_path)
    print(json.dumps(capture, default=str))


if __name__ == "__main__":
    main()
