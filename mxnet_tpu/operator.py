"""Custom operators in Python (parity: reference ``python/mxnet/operator.py``
— ``CustomOp``/``CustomOpProp`` registered through ``MXCustomOpRegister``).

The reference routes custom-op forward/backward through C callbacks under the
engine.  Here a registered CustomOp becomes a host computation embedded in the
XLA graph via ``jax.pure_callback`` (ordering is guaranteed by dataflow —
the callback's outputs feed the consumers), with gradients routed back through
a ``jax.custom_vjp`` whose bwd calls the op's ``backward``.
"""

from __future__ import annotations

import functools
from typing import Dict, List

import numpy as _np

from .base import MXNetError
from .ndarray import NDArray, array

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered"]

_CUSTOM_OPS: Dict[str, type] = {}


class CustomOp(object):
    """Base class for python custom operators (parity: ``operator.py:CustomOp``)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError()

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError()

    def assign(self, dst, req, src):
        if req == "null":
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src


class CustomOpProp(object):
    """Properties of a custom op (parity: ``operator.py:CustomOpProp``)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def list_outputs(self):
        return ["output"]

    def list_arguments(self):
        return ["data"]

    def list_auxiliary_states(self):
        return []

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


def register(reg_name):
    """Register a CustomOpProp class under ``op_type`` (parity:
    ``operator.py:register``).  Creates the ``Custom``-op plumbing so
    ``mx.nd.Custom(..., op_type=reg_name)`` / ``mx.sym.Custom`` work."""

    def do_register(prop_cls):
        _CUSTOM_OPS[reg_name] = prop_cls
        return prop_cls

    return do_register


def get_all_registered():
    return dict(_CUSTOM_OPS)


def _get_prop(op_type, kwargs):
    if op_type not in _CUSTOM_OPS:
        raise MXNetError("custom op %r is not registered" % op_type)
    str_kwargs = {k: str(v) for k, v in kwargs.items()}
    return _CUSTOM_OPS[op_type](**str_kwargs)


# ----------------------------------------------------------------------
# the host-callback 'Custom' op, registered in the main registry
# ----------------------------------------------------------------------


def _custom_impl(attrs, *inputs):
    import jax
    import jax.numpy as jnp

    op_type = attrs["op_type"]
    extra = attrs.get("_kwargs") or {}
    if not isinstance(extra, dict):  # canonicalized to tuple-of-pairs by jit cache
        extra = dict(extra)
    prop = _get_prop(op_type, extra)
    n_out = len(prop.list_outputs())
    n_args = len(prop.list_arguments())
    n_aux = len(prop.list_auxiliary_states())
    in_shapes = [tuple(x.shape) for x in inputs[:n_args]]
    ishapes, oshapes, ashapes = prop.infer_shape([list(s) for s in in_shapes])
    in_types = [x.dtype for x in inputs[:n_args]]
    itypes, otypes, atypes = prop.infer_type(in_types)

    out_structs = [
        jax.ShapeDtypeStruct(tuple(s), _np.dtype(t))
        for s, t in zip(oshapes, otypes)
    ]

    @jax.custom_vjp
    def run(*xs):
        def host_fwd(*arrs):
            cop = prop.create_operator(None, in_shapes, in_types)
            in_nd = [array(_np.asarray(a)) for a in arrs[:n_args]]
            aux_nd = [array(_np.asarray(a)) for a in arrs[n_args:]]
            out_nd = [array(_np.zeros(s.shape, s.dtype)) for s in out_structs]
            cop.forward(True, ["write"] * n_out, in_nd, out_nd, aux_nd)
            return tuple(o.asnumpy() for o in out_nd)

        return jax.pure_callback(host_fwd, tuple(out_structs), *xs)

    def fwd(*xs):
        outs = run(*xs)
        return outs, (xs, outs)

    def bwd(res, gs):
        xs, outs = res

        def host_bwd(*arrs):
            k = len(xs)
            xs_np = arrs[:k]
            outs_np = arrs[k : k + n_out]
            gs_np = arrs[k + n_out :]
            cop = prop.create_operator(None, in_shapes, in_types)
            in_nd = [array(_np.asarray(a)) for a in xs_np[:n_args]]
            aux_nd = [array(_np.asarray(a)) for a in xs_np[n_args:]]
            out_nd = [array(_np.asarray(a)) for a in outs_np]
            ograd_nd = [array(_np.asarray(a)) for a in gs_np]
            igrad_nd = [array(_np.zeros(a.shape, a.dtype)) for a in xs_np[:n_args]]
            cop.backward(["write"] * n_args, ograd_nd, in_nd, out_nd, igrad_nd,
                         aux_nd)
            return tuple(g.asnumpy() for g in igrad_nd)

        in_structs = [jax.ShapeDtypeStruct(tuple(x.shape), _np.dtype(x.dtype))
                      for x in xs[:n_args]]
        grads = jax.pure_callback(host_bwd, tuple(in_structs), *(xs + outs + gs))
        # aux inputs receive zero cotangent
        zeros_aux = tuple(jnp.zeros_like(x) for x in xs[n_args:])
        return tuple(grads) + zeros_aux

    run.defvjp(fwd, bwd)
    out = run(*inputs)
    return out if len(out) > 1 else out[0]


def _register_custom_host_op():
    from .ops.registry import Op, ParamSpec as P, register_op

    def n_outputs(attrs):
        extra = attrs.get("_kwargs") or {}
        if not isinstance(extra, dict):
            extra = dict(extra)
        prop = _get_prop(attrs["op_type"], extra)
        return len(prop.list_outputs())

    op = Op(
        "Custom",
        _custom_impl,
        variable_args=True,
        num_outputs=n_outputs,
        collect_extra=True,
        params={"op_type": P("str", None, required=True), "_kwargs": P("any", None)},
    )
    register_op(op)


_register_custom_host_op()


# ----------------------------------------------------------------------
# legacy python-op API (reference operator.py:PythonOp/NumpyOp/NDArrayOp)
# implemented as adapters over the CustomOp host
# ----------------------------------------------------------------------

import ctypes as _ctypes
import itertools as _itertools

c_int_p = _ctypes.POINTER(_ctypes.c_int)  # reference-compat ctypes alias

_legacy_seq = _itertools.count()


class PythonOp(object):
    """Base class for legacy python operators (parity:
    ``operator.py:PythonOp``).  ``get_symbol`` builds a CustomOp-backed
    symbol delegating to this object's forward/backward/infer_shape."""

    _ref_holder = []

    def __init__(self, need_top_grad=True):
        self.info_ = None
        self.need_top_grad_ = need_top_grad

    def __call__(self, *args, **kwargs):
        return self.get_symbol(*args, **kwargs)

    def get_symbol(self, *args, **kwargs):
        raise NotImplementedError("Must override this")

    def forward(self, in_data, out_data):
        out_data[0][:] = in_data[0]

    def backward(self, out_grad, in_data, out_data, in_grad):
        in_grad[0][:] = 1.0

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]]

    def list_outputs(self):
        return ["output"]

    def list_arguments(self):
        return ["data"]

    def need_top_grad(self):
        return self.need_top_grad_

    # -- adapter plumbing ----------------------------------------------
    def _register_custom(self, numpy_arrays):
        # one registration per instance: repeated get_symbol calls (per
        # bucket/epoch loops) must not grow the registry unboundedly
        cached = getattr(self, "_legacy_op_type", None)
        if cached is not None:
            return cached
        outer = self
        op_type = "_legacy_python_op_%d" % next(_legacy_seq)
        self._legacy_op_type = op_type

        class _Adapter(CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                if numpy_arrays:
                    import numpy as _np

                    ins = [d.asnumpy() for d in in_data]
                    # writable copies: asnumpy views of jax buffers are
                    # read-only, and the legacy contract is in-place writes
                    outs = [_np.array(d.asnumpy()) for d in out_data]
                    outer.forward(in_data=ins, out_data=outs)
                    from . import ndarray as nd

                    for dst, r, val in zip(out_data, req, outs):
                        self.assign(dst, r, nd.array(val))
                else:
                    outer.forward(in_data=in_data, out_data=out_data)

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                if numpy_arrays:
                    import numpy as _np

                    ogs = [d.asnumpy() for d in out_grad]
                    ins = [d.asnumpy() for d in in_data]
                    outs = [d.asnumpy() for d in out_data]
                    igs = [_np.array(d.asnumpy()) for d in in_grad]
                    outer.backward(out_grad=ogs, in_data=ins, out_data=outs,
                                   in_grad=igs)
                    from . import ndarray as nd

                    for dst, r, val in zip(in_grad, req, igs):
                        self.assign(dst, r, nd.array(val))
                else:
                    outer.backward(out_grad=out_grad, in_data=in_data,
                                   out_data=out_data, in_grad=in_grad)

        class _Prop(CustomOpProp):
            def __init__(self):
                super().__init__(need_top_grad=outer.need_top_grad())

            def list_arguments(self):
                return outer.list_arguments()

            def list_outputs(self):
                return outer.list_outputs()

            def infer_shape(self, in_shape):
                ishape, oshape = outer.infer_shape(in_shape)
                return ishape, oshape, []

            def create_operator(self, ctx, shapes, dtypes):
                return _Adapter()

        register(op_type)(_Prop)
        PythonOp._ref_holder.append(self)
        return op_type


class NumpyOp(PythonOp):
    """Legacy numpy operator (parity: ``operator.py:NumpyOp``): forward/
    backward receive numpy arrays."""

    def get_symbol(self, *args, **kwargs):
        from . import symbol as sym

        op_type = self._register_custom(numpy_arrays=True)
        return sym.Custom(*args, op_type=op_type, **kwargs)


class NDArrayOp(PythonOp):
    """Legacy NDArray operator (parity: ``operator.py:NDArrayOp``):
    forward/backward receive NDArrays."""

    def get_symbol(self, *args, **kwargs):
        from . import symbol as sym

        op_type = self._register_custom(numpy_arrays=False)
        return sym.Custom(*args, op_type=op_type, **kwargs)
