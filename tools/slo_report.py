"""``make slo`` / ``python tools/slo_report.py``: SLO error budgets.

Prints one row per SLO — objective, good/bad totals, error rate, and
the fraction of error budget remaining — from a metrics exposition:

    python tools/slo_report.py                      # self-contained demo
    python tools/slo_report.py --url http://host:9100/metrics
    python tools/slo_report.py --file metrics.prom

Exit status is the contract: **nonzero when any budget is exhausted**,
so the report slots into CI and release gates as-is.  The default mode
is a self-contained demo — a tiny numpy-backed model behind the
continuous-batching scheduler answers a burst of requests, then the
budgets are read back from the metrics the serving tier emitted
(``--breach`` sheds traffic against a drained replica first, proving
the nonzero-exit path).
"""

import argparse
import json
import os
import sys
import urllib.request

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MXNET_TPU_METRICS", "1")


def format_slo_table(rows):
    """The report as an aligned text table (one row per SLO)."""
    head = ("slo", "kind", "objective", "good", "bad", "error_rate",
            "burn", "budget_left", "state")
    table = [head]
    for r in rows:
        table.append((
            r["slo"], r["kind"], "%.4f" % r["objective"],
            "%d" % r["good"], "%d" % r["bad"],
            "%.5f" % r["error_rate"], "%.2fx" % r["budget_consumed"],
            "%.4f" % r["budget_remaining"],
            "EXHAUSTED" if r["exhausted"] else "ok"))
    widths = [max(len(row[i]) for row in table)
              for i in range(len(head))]
    return "\n".join("  ".join(c.ljust(w) for c, w in zip(row, widths))
                     for row in table)


def _demo_source(breach):
    """Drive a tiny serving stack so the registry has something to
    report on; with ``breach`` the replica drains first and traffic is
    shed, exhausting the availability budget."""
    import numpy as np

    from mxnet_tpu import serving

    class _SumBackend(serving.Backend):
        # pure-numpy backend: no compile, no accelerator — the point is
        # the metrics, not the model
        input_shapes = {"data": (4,)}
        buckets = None

        def infer(self, batch):
            return [batch["data"].sum(axis=1, keepdims=True)], False

    sched = serving.Scheduler(name="slo-demo")
    sched.register("demo", _SumBackend(), buckets=[1, 4])
    row = np.ones(4, dtype=np.float32)
    for _ in range(32):
        sched.request("demo", {"data": row})
    if breach:
        sched.drain()
        for _ in range(8):
            try:
                sched.submit("demo", {"data": row})
            except serving.ServingError:
                pass
    sched.close()
    return None      # report() reads the process-global registry


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", default=None,
                    help="scrape this /metrics endpoint")
    ap.add_argument("--file", default=None,
                    help="read exposition text from this file")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw /slo JSON instead of the table")
    ap.add_argument("--breach", action="store_true",
                    help="demo mode only: shed traffic first so the "
                         "availability budget exhausts (exit 1)")
    args = ap.parse_args(argv)

    from mxnet_tpu.observability import slo as _slo

    if args.url:
        with urllib.request.urlopen(args.url, timeout=10) as resp:
            source = resp.read().decode("utf-8")
    elif args.file:
        with open(args.file, encoding="utf-8") as f:
            source = f.read()
    else:
        source = _demo_source(args.breach)

    report = _slo.report(source)
    if report.get("disabled"):
        print("metrics are disabled (MXNET_TPU_METRICS=0): no budgets "
              "to report")
        return 0
    rows = report["slos"]
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(format_slo_table(rows))
    exhausted = [r["slo"] for r in rows if r["exhausted"]]
    if exhausted:
        print("error budget EXHAUSTED: %s" % ", ".join(exhausted))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
