"""``make dr``: the disaster-recovery drill — kill the ENTIRE cluster
mid-fit, cold-restart at a different PS shard count from the latest
durable snapshot, and continue training bitwise-equal to a run that was
never interrupted.

The drill drives the PR-18 durability subsystem end to end on the CPU
backend:

1. a reference ``ShardedTrainer.fit(kvstore=)`` run on a 2-shard PS
   trains 2 epochs uninterrupted and records the final parameters;
2. the DR run starts identically, and mid-epoch-0 its batch callback
   (a) proves the ``storage.write`` chaos site: a seeded ENOSPC aborts
   a snapshot attempt cleanly (native ``OSError``, no staging litter,
   nothing visible), (b) takes two committed snapshots of the live PS
   via ``kv.snapshot()`` — consistent seqno-barrier cuts whose frozen
   window must stay bounded — (c) flips one byte in the NEWEST
   snapshot's largest shard record (silent bit rot), then (d) kills the
   whole cluster: the fit dies and every server stops;
3. a COLD restart brings up 3 fresh shards (different topology), and
   ``snapshot.restore_latest`` must quarantine the corrupt newest
   snapshot — exactly one ``snapshot.quarantined`` event and one flight
   bundle naming the bad shard file — then restore the intact one,
   re-striping 2→3;
4. the fit resumes from the exact killed batch (roster fast-forward)
   and its final parameters must equal the reference run's
   **bitwise** — every update landed exactly once, on every shard
   layout.

Exits non-zero on any miss.  Run:  python tools/dr_drill.py
"""

import errno
import json
import os
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MXNET_TPU_METRICS", "1")
# tmpfs-friendly: the drill measures protocol correctness, not disk
os.environ.setdefault("MXNET_TPU_SNAPSHOT_FSYNC", "0")

B, D = 8, 6
KILL_AT_BATCH = 2          # batches of epoch 0 completed before the kill
FROZEN_BOUND_MS = 500.0    # the consistent cut must stay this cheap


def _mlp(mx):
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=16,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=8, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _fit(mx, kv, roster=None, callback=None):
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from mxnet_tpu.io import NDArrayIter
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    rs = np.random.RandomState(3)
    it = NDArrayIter({"data": rs.randn(32, D).astype(np.float32)},
                     {"softmax_label": rs.randint(0, 8, (32,)).astype(
                         np.float32)}, batch_size=B)
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    tr = ShardedTrainer(_mlp(mx), mesh, data_shapes={"data": (B, D)},
                        label_shapes={"softmax_label": (B,)},
                        rescale_grad=1.0 / B)
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1,
                                      rescale_grad=1.0 / B, wd=0.0))
    (params, _, _), _ = tr.fit(it, num_epoch=2, seed=5, log_every=0,
                               kvstore=kv, roster=roster,
                               batch_end_callback=callback)
    return params


def _servers(ka, n, base=0):
    return [ka.AsyncServer(secret="dr", server_id=base + i).start()
            for i in range(n)]


def _make_kv(mx, ka, addrs):
    os.environ["MXNET_TPU_ASYNC_PS_ADDRS"] = ",".join(addrs)
    ka.reset_membership()
    kv = mx.kv.create("dist_async")
    assert kv._async is not None
    return kv


def _flip_byte(path):
    with open(path, "r+b") as f:
        f.seek(-8, os.SEEK_END)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0x5A]))


class _ClusterKilled(Exception):
    pass


def main():
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import chaos
    from mxnet_tpu import elastic
    from mxnet_tpu import kvstore_async as ka
    from mxnet_tpu import observability as obs
    from mxnet_tpu import snapshot

    flight_dir = tempfile.mkdtemp(prefix="mxtpu_dr_flight_")
    snap_dir = tempfile.mkdtemp(prefix="mxtpu_dr_snaps_")
    os.environ["MXNET_TPU_FLIGHT_DIR"] = flight_dir
    os.environ["MXNET_TPU_PS_SECRET"] = "dr"

    failures = []

    # -- reference: 2 shards, never interrupted -------------------------
    ref = _servers(ka, 2)
    try:
        kv_ref = _make_kv(mx, ka, [s.address for s in ref])
        p_ref = _fit(mx, kv_ref)
        kv_ref._async.shutdown()
    finally:
        for s in ref:
            s.stop()

    # -- DR run: same fit, killed whole-cluster mid-epoch-0 -------------
    servers = _servers(ka, 2, base=10)
    frozen = []

    def drill(bep):
        if bep.epoch != 0 or bep.nbatch != KILL_AT_BATCH:
            return
        # (a) seeded ENOSPC mid-snapshot: clean abort, nothing visible
        with chaos.inject("storage.write", "drop", limit=1):
            try:
                kv.snapshot(snap_dir, step=1)
                raise AssertionError("seeded ENOSPC did not abort")
            except OSError as e:
                if e.errno != errno.ENOSPC:
                    raise
        if snapshot.list_snapshots(snap_dir) or any(
                n.endswith(".tmp") for n in os.listdir(snap_dir)):
            raise AssertionError("aborted save left something behind")
        # (b) two committed consistent cuts of the live PS
        for step in (1, 2):
            r = kv.snapshot(snap_dir, step=step)
            frozen.append(r["frozen_ms"])
        # (c) silent bit rot in the newest snapshot's largest shard
        shard_files = [
            (os.path.getsize(os.path.join(snap_dir, "snap-2", n)), n)
            for n in os.listdir(os.path.join(snap_dir, "snap-2"))
            if n.endswith(".bin")]
        victim = max(shard_files)[1]
        _flip_byte(os.path.join(snap_dir, "snap-2", victim))
        drill.victim = victim
        # (d) kill the entire cluster mid-fit
        for s in servers:
            s.stop()
        raise _ClusterKilled()

    try:
        kv = _make_kv(mx, ka, [s.address for s in servers])
        try:
            _fit(mx, kv, callback=drill)
            failures.append("the kill callback never fired")
        except _ClusterKilled:
            pass
    finally:
        for s in servers:
            s.stop()

    obs.clear_events()

    # -- cold restart: 3 fresh shards, restore from the snapshot ladder -
    servers2 = _servers(ka, 3, base=20)
    try:
        kv2 = _make_kv(mx, ka, [s.address for s in servers2])
        restored = snapshot.restore_latest(snap_dir, kv2._async,
                                           secret="dr")
        roster = elastic.WorkerRoster(ranks=[0])
        roster.mark_progress(0, KILL_AT_BATCH)   # resume at the kill point
        p_dr = _fit(mx, kv2, roster=roster)
        kv2._async.shutdown()
    finally:
        for s in servers2:
            s.stop()

    # -- the acceptance bars --------------------------------------------
    if restored["step"] != 1 or restored["saved_shards"] != 2 \
            or restored["restored_shards"] != 3:
        failures.append("restore took the wrong path: %r" % (restored,))

    worst = 0.0
    for n in sorted(p_ref):
        a, b = np.asarray(p_ref[n]), np.asarray(p_dr[n])
        if a.size:
            worst = max(worst, float(np.max(np.abs(
                a.astype(np.float64) - b.astype(np.float64)))))
        if not np.array_equal(a, b):
            failures.append("continuation not bitwise-equal on %s" % n)

    evs = obs.events(kind="snapshot.quarantined")
    if len(evs) != 1:
        failures.append("expected exactly 1 quarantine event, saw %d"
                        % len(evs))
    if not os.path.isdir(os.path.join(snap_dir, "snap-2.quarantined")):
        failures.append("corrupt snapshot was not quarantined on disk")
    bundles = [d for d in os.listdir(flight_dir)
               if d.startswith("flight_snapshot_quarantined")]
    named = []
    for d in bundles:
        with open(os.path.join(flight_dir, d, "manifest.json")) as f:
            named.append(json.load(f)["extra"].get("file"))
    if len(bundles) != 1 or named != [drill.victim]:
        failures.append("flight bundle must name the bad shard "
                        "(bundles=%r files=%r want=%r)"
                        % (bundles, named, drill.victim))

    if not frozen or any(f is None or f > FROZEN_BOUND_MS
                         for f in frozen):
        failures.append("frozen window unbounded: %r ms" % (frozen,))

    print("dr drill: whole-cluster kill mid-fit -> cold 2->3 restore")
    print("  snapshots: 1 aborted by seeded ENOSPC, 2 committed, "
          "1 bit-rotted")
    print("  frozen windows: %s ms"
          % ", ".join("%.2f" % f for f in frozen))
    print("  quarantined: snap-2 (bad shard: %s), restored: snap-%d "
          "onto %d shards" % (drill.victim, restored["step"],
                              restored["restored_shards"]))
    print("  continuation vs uninterrupted: max |delta| = %.3g "
          "(bitwise %s)" % (worst, "EQUAL" if worst == 0.0 else "MISS"))
    if failures:
        for f in failures:
            print("FAIL: %s" % f, file=sys.stderr)
        return 1
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
