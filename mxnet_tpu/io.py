"""Data iterators (parity: reference ``python/mxnet/io.py`` + ``src/io/*``).

The reference's C++ pipeline is ``InputSplit -> decode/augment (OMP) ->
BatchLoader -> Prefetcher (dmlc::ThreadedIter)``.  Here the structure is the
same but host-side: python iterators with a threaded double-buffering
``PrefetchingIter``, feeding device transfer via ``jax.device_put`` (the
PJRT H2D copy replaces the engine's copy workers).  RecordIO-backed image
iterators live in ``image.py``/``recordio.py``.
"""

from __future__ import annotations

import gzip
import os
import queue
import struct
import threading
from collections import namedtuple

import numpy as _np

from .base import MXNetError, mx_dtype
from .ndarray import NDArray, array

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "MNISTIter", "CSVIter", "ImageRecordIter",
           "MXDataIter", "batch_arrays"]


def batch_arrays(batch, data_iter=None, input_names=None):
    """Flatten a ``DataBatch`` into ``(arrays, data_names)`` — the hook
    training loops and the async prefetch feeder share for turning iterator
    output into graph feeds.

    ``arrays`` maps input name -> host ``numpy`` array (data then label,
    descriptor order); ``data_names`` is the subset of names that came from
    ``provide_data`` (so callers can split labels back out for metrics).
    Descriptors are taken from the batch when set, else from ``data_iter``
    (``NDArrayIter`` populates only the iter-level ``provide_*``).  When
    ``input_names`` is given, names outside it are dropped — a loop feeding
    a graph passes the graph's input set so extra iterator outputs (e.g.
    unused labels) don't become unexpected feeds."""
    ddescs = list(batch.provide_data
                  or getattr(data_iter, "provide_data", None) or [])
    ldescs = list(batch.provide_label
                  or getattr(data_iter, "provide_label", None) or [])
    arrays, data_names = {}, set()
    vals = list(batch.data or []) + list(batch.label or [])
    for i, (desc, v) in enumerate(zip(ddescs + ldescs, vals)):
        name = desc[0] if isinstance(desc, (tuple, list)) else desc.name
        if input_names is None or name in input_names:
            arrays[name] = (v.asnumpy() if hasattr(v, "asnumpy")
                            else _np.asarray(v))
            if i < len(ddescs):
                data_names.add(name)
    return arrays, data_names


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Data description: name/shape (+dtype/layout), parity ``io.py:DataDesc``."""

    def __new__(cls, name, shape, dtype=_np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch(object):
    """One batch (parity: ``io.py:DataBatch``)."""

    def __init__(self, data, label=None, pad=None, index=None, bucket_key=None,
                 provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter(object):
    """Base iterator (parity: ``io.py:DataIter``)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        pass

    def getdata(self):
        pass

    def getlabel(self):
        pass

    def getindex(self):
        return None

    def getpad(self):
        pass


def _init_data(data, allow_empty, default_name):
    """Normalize input data (parity: ``io.py:_init_data``)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (_np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of them "
                        "or dict with them as values")
    out = {}
    for k, v in data.items():
        if isinstance(v, NDArray):
            out[k] = v.asnumpy()
        else:
            out[k] = _np.asarray(v)
    return list(sorted(out.items()))


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (parity: ``io.py:NDArrayIter``)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label", seed=None):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.num_data = self.data[0][1].shape[0]

        if shuffle:
            idx = _np.arange(self.num_data)
            (_np.random if seed is None
             else _np.random.RandomState(seed)).shuffle(idx)
            self.data = [(k, v[idx]) for k, v in self.data]
            self.label = [(k, v[idx]) for k, v in self.label]
        if last_batch_handle == "discard":
            new_n = self.num_data - self.num_data % batch_size
            self.data = [(k, v[:new_n]) for k, v in self.data]
            self.label = [(k, v[:new_n]) for k, v in self.label]
            self.num_data = new_n

        self.data_list = [v for _, v in self.data] + [v for _, v in self.label]
        self.num_source = len(self.data_list)
        assert self.num_data >= batch_size, "batch_size needs to be smaller than data size"
        self.cursor = -batch_size
        self.last_batch_handle = last_batch_handle

    @property
    def provide_data(self):
        return [
            DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])), v.dtype)
            for k, v in self.data
        ]

    @property
    def provide_label(self):
        return [
            DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])), v.dtype)
            for k, v in self.label
        ]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.last_batch_handle == "roll_over" and self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor - self.num_data)
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=None)
        raise StopIteration

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self.cursor + self.batch_size <= self.num_data:
            return [array(v[self.cursor : self.cursor + self.batch_size])
                    for _, v in data_source]
        pad = self.batch_size - self.num_data + self.cursor
        return [array(_np.concatenate((v[self.cursor :], v[:pad]), axis=0))
                for _, v in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Clamp/extend another iterator to ``size`` batches per epoch
    (parity: ``io.py:ResizeIter``).

    When the wrapped iterator runs dry mid-epoch it is restarted, so
    ``size`` may exceed its natural length."""

    _MIRRORED = ("provide_data", "provide_label", "batch_size",
                 "default_bucket_key")

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        for attr in self._MIRRORED:
            if hasattr(data_iter, attr):
                setattr(self, attr, getattr(data_iter, attr))

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def _pull_wrapping(self):
        try:
            return self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            return self.data_iter.next()

    def iter_next(self):
        if self.cur >= self.size:
            return False
        self.current_batch = self._pull_wrapping()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Double-buffering prefetcher (parity: ``io.py:PrefetchingIter``, the
    ``dmlc::ThreadedIter`` equivalent).

    Each upstream fetch is an op pushed to the dependency engine's IO lane
    with the slot's variable as its write dep (``engine.py`` →
    ``native/src/engine.cc``): the engine's IO worker pool overlaps the
    fetch with the main thread's device work, and ``wait_for_var`` is the
    consume-side synchronization — the reference's PrefetcherIter structure
    (``iter_prefetcher.h:129``) on the host engine instead of ad-hoc
    threads."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0][1][0]
        from . import engine as _engine

        self._engine = _engine
        self._vars = [_engine.new_variable() for _ in range(self.n_iter)]
        self.current_batch = None
        self.next_batch = [None for _ in range(self.n_iter)]
        self._errors = [None for _ in range(self.n_iter)]
        self._poisoned = False
        self._push_all()

    def _push_fetch(self, i):
        def fetch():
            try:
                self.next_batch[i] = self.iters[i].next()
            except StopIteration:
                self.next_batch[i] = None
            except BaseException as exc:  # surface on the consumer side:
                # leaving the previous batch in the slot would silently
                # re-serve stale data forever
                self.next_batch[i] = None
                self._errors[i] = exc

        def lost():
            # chaos dropped the fetch op: the slot still holds its PREVIOUS
            # batch, which iter_next would silently re-serve — record the
            # loss so the consumer raises instead (reset() recovers)
            self._errors[i] = RuntimeError(
                "prefetch op for slot %d was lost before running (chaos "
                "injection / silent drop) — the slot's data is stale" % i)

        if self._engine.in_worker():
            # nested prefetchers: running on the bounded IO pool already —
            # scheduling another IO op and waiting on it could starve the
            # pool, so degrade to a synchronous fetch
            fetch()
            return
        self._engine.push(fetch, mutable_vars=[self._vars[i]],
                          prop=self._engine.FnProperty.IO,
                          name="prefetch%d" % i, on_drop=lost)

    def _push_all(self):
        for i in range(self.n_iter):
            self._push_fetch(i)

    def __del__(self):
        try:
            for v in self._vars:
                self._engine.wait_for_var(v)
                self._engine.delete_variable(v)
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([
            [DataDesc(r[x.name], x.shape, x.dtype)
             if isinstance(x, DataDesc) else DataDesc(*x)
             for x in i.provide_data]
            for r, i in zip(self.rename_data, self.iters)
        ], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([
            [DataDesc(r[x.name], x.shape, x.dtype)
             if isinstance(x, DataDesc) else DataDesc(*x)
             for x in i.provide_label]
            for r, i in zip(self.rename_label, self.iters)
        ], [])

    def reset(self):
        for v in self._vars:
            self._engine.wait_for_var(v)
        # recovery point after a surfaced upstream error: the upstream
        # reset + fresh fetches below leave every slot consistent again
        self._poisoned = False
        self._errors = [None for _ in range(self.n_iter)]
        for i in self.iters:
            i.reset()
        self._push_all()

    def iter_next(self):
        if self._poisoned:
            raise RuntimeError(
                "PrefetchingIter previously surfaced an upstream error; "
                "its slots are undefined — call reset() to recover "
                "(a bare retry would mimic a clean end-of-epoch)")
        for v in self._vars:
            self._engine.wait_for_var(v)
        for i, exc in enumerate(self._errors):
            if exc is not None:
                self._errors[i] = None
                self._poisoned = True
                raise exc
        if self.next_batch[0] is None:
            for i in self.next_batch:
                assert i is None, "Number of entry mismatches between iterators"
            return False
        for batch in self.next_batch:
            assert batch.pad == self.next_batch[0].pad, (
                "Number of entry mismatches between iterators")
        self.current_batch = DataBatch(
            sum([batch.data for batch in self.next_batch], []),
            sum([batch.label for batch in self.next_batch], []),
            self.next_batch[0].pad,
            self.next_batch[0].index,
            provide_data=self.provide_data,
            provide_label=self.provide_label,
        )
        self._push_all()
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


def _read_idx(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dtype_code = (magic >> 8) & 0xFF
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        dt = {0x08: _np.uint8, 0x09: _np.int8, 0x0B: _np.int16,
              0x0C: _np.int32, 0x0D: _np.float32, 0x0E: _np.float64}[dtype_code]
        return _np.frombuffer(f.read(), dtype=dt).reshape(shape)


class MNISTIter(NDArrayIter):
    """MNIST idx-format iterator (parity: reference ``src/io/iter_mnist.cc``).

    Reads the standard idx(.gz) files; ``flat`` controls (batch, 784) vs
    (batch, 1, 28, 28) layout, matching the reference's ``flat`` param.
    """

    def __init__(self, image="train-images-idx3-ubyte", label="train-labels-idx1-ubyte",
                 batch_size=128, shuffle=True, flat=False, silent=False, seed=0,
                 input_shape=None, **kwargs):
        for cand in (image, image + ".gz"):
            if os.path.exists(cand):
                image = cand
                break
        else:
            raise MXNetError("MNIST image file not found: %s" % image)
        for cand in (label, label + ".gz"):
            if os.path.exists(cand):
                label = cand
                break
        else:
            raise MXNetError("MNIST label file not found: %s" % label)
        img = _read_idx(image).astype(_np.float32) / 255.0
        lab = _read_idx(label).astype(_np.float32)
        if flat:
            img = img.reshape(img.shape[0], -1)
        else:
            img = img.reshape(img.shape[0], 1, img.shape[1], img.shape[2])
        if input_shape is not None:
            img = img.reshape((img.shape[0],) + tuple(input_shape))
        super().__init__(img, lab, batch_size=batch_size, shuffle=shuffle,
                         last_batch_handle="discard", seed=seed)


class CSVIter(NDArrayIter):
    """CSV iterator (parity: reference ``src/io/iter_csv.cc``)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        data = _np.loadtxt(data_csv, delimiter=",", dtype=_np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = _np.loadtxt(label_csv, delimiter=",", dtype=_np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
            if label_shape == (1,):
                label = label.reshape(-1)
        super().__init__(data, label, batch_size=batch_size,
                         last_batch_handle="pad" if round_batch else "discard")


def ImageRecordIter(path_imgrec, data_shape, batch_size, path_imgidx=None,
                    mean_r=0.0, mean_g=0.0, mean_b=0.0, std_r=1.0, std_g=1.0,
                    std_b=1.0, rand_crop=False, rand_mirror=False,
                    resize=0, shuffle=False, preprocess_threads=4,
                    num_parts=1, part_index=0, prefetch_buffer=4,
                    label_width=1, data_name="data",
                    label_name="softmax_label", **kwargs):
    """RecordIO image iterator with the C-iterator parameter surface
    (parity: reference ``src/io/iter_image_recordio_2.cc:559-579`` /
    ``ImageRecordIter`` registration).

    Decoding/augmentation runs through ``mx.image.ImageIter`` wrapped in a
    ``PrefetchingIter`` for double-buffering — the role of the reference's
    ``PrefetcherIter`` + OMP decode threads (``iter_prefetcher.h:129``).
    When the native C++ loader extension is built it takes over the decode
    path transparently.
    """
    from .image import ImageIter

    mean = None
    if mean_r or mean_g or mean_b:
        mean = _np.array([mean_r, mean_g, mean_b])
    std = None
    if (std_r, std_g, std_b) != (1.0, 1.0, 1.0):
        std = _np.array([std_r, std_g, std_b])
    inner = ImageIter(
        batch_size=batch_size, data_shape=data_shape,
        label_width=label_width, path_imgrec=path_imgrec,
        path_imgidx=path_imgidx, shuffle=shuffle, part_index=part_index,
        num_parts=num_parts, data_name=data_name, label_name=label_name,
        resize=resize, rand_crop=rand_crop, rand_mirror=rand_mirror,
        mean=mean, std=std, **kwargs,
    )
    return PrefetchingIter(inner)


class MXDataIter(DataIter):
    """Wrapper giving a backend-provided iterator the DataIter protocol
    (parity: ``io.py:MXDataIter`` — the reference wraps a C++ iterator
    handle; here the 'handle' is any object with the DataIter protocol,
    e.g. an iterator produced by the registered factory functions).  Kept
    for user code that isinstance-checks or subclasses MXDataIter."""

    def __init__(self, handle, data_name="data", label_name="softmax_label",
                 **_):
        super().__init__()
        self.handle = handle
        self._data_name = data_name
        self._label_name = label_name

    @property
    def provide_data(self):
        return self.handle.provide_data

    @property
    def provide_label(self):
        return self.handle.provide_label

    def reset(self):
        self.handle.reset()

    def next(self):
        if not self.iter_next():
            raise StopIteration
        return self._cur

    def iter_next(self):
        try:
            self._cur = self.handle.next()
            return True
        except StopIteration:
            return False

    def getdata(self):
        return self._cur.data

    def getlabel(self):
        return self._cur.label

    def getindex(self):
        return getattr(self._cur, "index", None)

    def getpad(self):
        pad = getattr(self._cur, "pad", None)
        return 0 if pad is None else pad
