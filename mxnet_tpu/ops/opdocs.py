"""Operator documentation — the registry's ``describe()`` text.

The reference attaches human descriptions to every op at registration
(``NNVM_REGISTER_OP(...).describe(...)``; e.g. ``src/operator/
tensor/elemwise_unary_op.cc``) and its Python frontend reflects them into
docstrings (``python/mxnet/ndarray.py`` autogen docs).  Here the compute
rules live in Python, so ops that need commentary carry a real docstring on
the compute fn; the mechanical families (scalar arithmetic, broadcast
binaries, unary math, samplers) get their text from this module instead of
192 near-identical docstrings.

:func:`describe` is the single lookup both frontends and the docs
generator use; a CI gate (``tests/test_docs.py``) walks the registry and
fails on any op that resolves to no description — a newly registered op
must be documented to land.
"""

from __future__ import annotations

# --- explicit descriptions (ops whose compute fn carries no docstring) ---

OPDOCS = {
    # -- NN layers -----------------------------------------------------
    "Activation": "Element-wise activation: `act_type` selects relu, "
        "sigmoid, tanh or softrelu (softplus). Lowers to one fused VPU "
        "elementwise op.",
    "BatchNorm": "Batch normalization over all axes but `axis` (default "
        "the channel axis 1). Training mode normalizes with batch "
        "statistics and updates the `moving_mean`/`moving_var` auxiliary "
        "states by `momentum`; inference (or `use_global_stats`) uses the "
        "moving statistics. `fix_gamma` pins gamma to 1 and zeroes its "
        "gradient, matching the reference convention for conv stems.",
    "BilinearSampler": "Sample `data` (NCHW) at the normalized "
        "coordinates in `grid` ([-1,1], shape (N,2,Hout,Wout)) with "
        "bilinear interpolation; out-of-range samples read zero-padding. "
        "The sampling half of SpatialTransformer.",
    "BlockGrad": "Identity in the forward pass; stops the gradient (the "
        "backward pass sees zero cotangent through this node).",
    "Cast": "Cast every element to `dtype`. On TPU, `float32 -> bfloat16` "
        "casts mark matmul/conv inputs for MXU-rate execution.",
    "Concat": "Join inputs along existing axis `dim`; all other "
        "dimensions must match. Variable-arity (`num_args` inputs).",
    "Convolution": "N-D convolution (1/2/3-D from `kernel` rank) with "
        "`num_filter` output channels, `stride`/`dilate`/`pad`, grouped "
        "when `num_group` > 1. NCHW/NCDHW layouts. Lowers to "
        "`lax.conv_general_dilated`, which XLA tiles onto the MXU; the "
        "cuDNN tuning attrs (`cudnn_*`, `workspace`) are accepted for "
        "graph compatibility and ignored.",
    "Crop": "Crop the spatial (last two) dims of the first input to "
        "`h_w`, or to the reference shape of a second input symbol; "
        "`offset` fixes the top-left corner, `center_crop` centers it.",
    "Custom": "Invoke a user-registered CustomOp (`mx.operator."
        "register`): forward/backward run as host callbacks with "
        "`num_inputs`/`num_outputs` declared by the CustomOpProp. "
        "The escape hatch for python-defined ops inside jitted graphs.",
    "Deconvolution": "Transposed convolution (gradient of Convolution "
        "w.r.t. its input) — upsamples by `stride`; `adj`/`target_shape` "
        "disambiguate the output size. Lowers to "
        "`lax.conv_transpose`-style dilated convolution on the MXU.",
    "Dropout": "Randomly zero a fraction `p` of elements during training "
        "and rescale the survivors by 1/(1-p); identity at inference. "
        "Driven by the framework PRNG stream (`mx.random.seed`).",
    "Embedding": "Look up integer indices in a (`input_dim`, "
        "`output_dim`) weight table. The gradient scatters into the "
        "table; under tensor parallelism the table row-shards over the "
        "model axis.",
    "Flatten": "Collapse all dimensions but the first into one: "
        "(d0, d1, ..., dk) -> (d0, d1*...*dk).",
    "FullyConnected": "Affine layer `Y = X W^T + b` with `num_hidden` "
        "output features; `flatten` collapses trailing input dims first, "
        "`no_bias` drops `b`. One MXU matmul; fp32 master weights cast "
        "to the activation dtype at use.",
    "GridGenerator": "Generate a sampling grid for BilinearSampler: "
        "`affine` maps a 6-dof theta per sample to `target_shape` "
        "coordinates; `warp` converts a dense flow field to coordinates.",
    "IdentityAttachKLSparseReg": "Identity whose backward adds the "
        "gradient of a KL sparseness penalty (`penalty` * KL(rho || "
        "rho_hat)) on the sigmoid mean activation tracked in the "
        "`moving_avg` aux (sparse-autoencoder regularizer).",
    "InstanceNorm": "Normalize each sample over its spatial dims per "
        "channel (contrast normalization), then scale/shift by "
        "gamma/beta.",
    "L2Normalization": "Scale elements so the L2 norm over the selected "
        "scope is 1: whole `instance`, per-`channel`, or per-`spatial` "
        "position.",
    "LRN": "Local response normalization across `nsize` adjacent "
        "channels (AlexNet-era): x / (knorm + alpha/n * sum x^2)^beta.",
    "LeakyReLU": "Leaky/parametric ReLU family: `leaky` (fixed `slope`), "
        "`elu`, `prelu` (learned slope), `rrelu` (random slope in "
        "[`lower_bound`, `upper_bound`] during training).",
    "LinearRegressionOutput": "L2 regression head: forward is identity "
        "on `data`; backward emits `(data - label) * grad_scale` "
        "directly (no head gradient needed), the reference loss-layer "
        "contract.",
    "LogisticRegressionOutput": "Sigmoid regression head: forward is "
        "sigmoid(data); backward emits `(sigmoid(data) - label) * "
        "grad_scale` directly.",
    "MAERegressionOutput": "L1 regression head: forward is identity; "
        "backward emits `sign(data - label) * grad_scale` directly.",
    "MakeLoss": "Turn any symbol into a loss: forward passes `data` "
        "through; backward seeds the gradient with `grad_scale` "
        "(normalized by batch/valid count per `normalization`) instead "
        "of an incoming cotangent.",
    "Pad": "Pad the spatial dims by `pad_width` (edge pairs, "
        "2*ndim values) in `constant` (with `constant_value`), `edge` "
        "or `reflect` mode.",
    "Pooling": "Spatial pooling over `kernel` windows: `max`, `avg` or "
        "`sum`; `global_pool` reduces the whole map. "
        "`pooling_convention` picks the reference's `valid` (floor) or "
        "`full` (ceil) output-size rule. Lowers to "
        "`lax.reduce_window`.",
    "RNN": "Fused multi-layer RNN (`mode`: rnn_relu/rnn_tanh/lstm/gru) "
        "over a (T, N, C) sequence with packed `parameters`, matching "
        "the reference's cuDNN-RNN layout (gate order, bias pairs, "
        "`bidirectional` concat). Optionally emits final states "
        "(`state_outputs`); lowers to a `lax.scan` of MXU gate matmuls. "
        "See also mx.rnn cells (LSTMCell/GRUCell/FusedRNNCell).",
    "ROIPooling": "Max-pool each region of interest (batch_idx, x1, y1, "
        "x2, y2 scaled by `spatial_scale`) to a fixed `pooled_size` "
        "grid — the Fast-R-CNN head input.",
    "Reshape": "Reshape preserving element order. `shape` supports the "
        "reference's special codes: 0 copies an input dim, -1 infers, "
        "-2 copies the remainder, -3 merges two dims, -4 splits a dim "
        "(with `reverse` applying codes right-to-left).",
    "SVMOutput": "Margin (hinge) classification head over class scores: "
        "L1 hinge or squared (`use_linear=False`) hinge with margin and "
        "`regularization_coefficient`; backward needs no head gradient.",
    "SequenceLast": "Select the last valid time step of a (T, N, ...) "
        "sequence — per-sample positions from `sequence_length` when "
        "`use_sequence_length`.",
    "SequenceMask": "Zero (or set to `value`) all time steps past each "
        "sample's `sequence_length` in a (T, N, ...) sequence.",
    "SequenceReverse": "Reverse the time axis of a (T, N, ...) sequence; "
        "with `use_sequence_length`, reverse only each sample's valid "
        "prefix in place.",
    "SliceChannel": "Split along `axis` into `num_outputs` equal parts "
        "(`squeeze_axis` drops the now-size-1 axis). The multi-output "
        "inverse of Concat.",
    "SoftmaxActivation": "Softmax as a plain activation (no loss "
        "semantics): per-`instance` over the trailing axis, or per "
        "spatial position over channels (`mode='channel'`).",
    "SoftmaxOutput": "Softmax cross-entropy classification head: forward "
        "is softmax probabilities; backward emits `(p - onehot(label))` "
        "scaled by `grad_scale` and `normalization` directly — no head "
        "gradient, the reference loss-layer contract. `multi_output` "
        "treats dim 1 as classes with one label per remaining position; "
        "`ignore_label` (+`use_ignore`) masks positions; `smooth_alpha` "
        "label-smooths.",
    "SpatialTransformer": "Spatial transformer network: GridGenerator on "
        "the 6-dof `loc` predictions + BilinearSampler on `data`, "
        "end-to-end differentiable.",
    "SwapAxis": "Exchange dimensions `dim1` and `dim2`.",
    "TorchModule": "Host-callback bridge to a torch module: the "
        "AST-whitelisted `module` spec constructs the torch layer, "
        "`num_params` weight slots ride as graph inputs, and backward "
        "calls torch.autograd on the host. Training-capable interop "
        "(plugin/torch parity).",
    "UpSampling": "Upsample spatial dims by `scale`: `nearest` repeats "
        "pixels; `bilinear` uses a (learnable) Deconvolution kernel "
        "initialized to bilinear interpolation.",

    # -- array creation ------------------------------------------------
    "_arange": "Evenly spaced values in [start, stop) with `step`, each "
        "value repeated `repeat` times.",
    "_full": "A `shape` array filled with `value`.",
    "_ones": "A `shape` array of ones.",
    "_zeros": "A `shape` array of zeros.",
    "ones_like": "An array of ones with the input's shape and dtype.",
    "zeros_like": "An array of zeros with the input's shape and dtype.",
    "one_hot": "Expand integer indices to one-hot vectors of length "
        "`depth` (`on_value`/`off_value` fill the hit/miss slots).",

    # -- basic tensor manipulation ------------------------------------
    "_copy": "Identity copy of the input.",
    "expand_dims": "Insert a new size-1 dimension at `axis`.",
    "slice": "Slice `[begin, end)` per dimension (None leaves a "
        "dimension unsliced).",
    "slice_axis": "Slice `[begin, end)` along a single `axis` (None end "
        "= to the end; negatives allowed).",
    "take": "Gather slices of `a` along `axis` at integer `indices`; "
        "`mode` clips or wraps out-of-range indices.",
    "batch_take": "Per-row gather: `out[i] = a[i, indices[i]]`.",
    "pick": "Per-position gather along `axis`: `out[i] = "
        "data[i, index[i]]` (e.g. per-sample class probabilities).",
    "where": "Element-wise select: `condition ? x : y`.",
    "reverse": "Reverse the order of elements along `axis`.",
    "tile": "Repeat the whole array `reps` times per dimension.",
    "repeat": "Repeat each element `repeats` times along `axis` "
        "(flattened when `axis` is None).",
    "stack": "Join same-shape inputs along a NEW axis at `axis`.",
    "transpose": "Permute dimensions by `axes` (reversed when empty).",
    "broadcast_to": "Broadcast size-1 dimensions up to `shape` "
        "(0 keeps the input dim).",
    "broadcast_axis": "Broadcast the given size-1 `axis` (or axes) up "
        "to `size`.",
    "sort": "Sort values along `axis` (`is_ascend` picks direction).",
    "argsort": "Indices that would sort `data` along `axis`, as floats "
        "(reference dtype convention).",
    "argmax": "Index of the maximum along `axis` (float output; "
        "`keepdims` preserves the reduced axis).",
    "argmin": "Index of the minimum along `axis` (float output).",
    "argmax_channel": "Per-row argmax over the trailing axis of a 2-D "
        "input — the reference's channel-argmax shortcut.",
    "topk": "Top `k` along `axis`: returns indices (`ret_typ='indices'`),"
        " values, both, or a 0/1 mask; `is_ascend` flips to bottom-k.",
    "clip": "Clamp every element into [`a_min`, `a_max`].",

    # -- matmul --------------------------------------------------------
    "dot": "Matrix/tensor product contracting lhs's last axis with "
        "rhs's first (`transpose_a`/`transpose_b` pre-transpose 2-D "
        "operands). The MXU primitive: keep operands bf16 and large.",
    "batch_dot": "Batched matrix product over matching leading batch "
        "dims: `out[i] = lhs[i] @ rhs[i]`.",

    # -- losses / misc -------------------------------------------------
    "softmax": "Softmax over `axis` with `temperature` scaling.",
    "log_softmax": "Numerically stable log(softmax) over `axis`.",
    "softmax_cross_entropy": "Scalar summed cross-entropy between row "
        "logits and integer labels — the imperative loss helper.",
    "norm": "Scalar L2 (Frobenius) norm of the whole array.",
    "add_n": "Element-wise sum of N same-shape inputs in one fused op.",
    "negative": "Element-wise negation.",
    "logical_not": "Element-wise logical NOT (1.0 where x == 0).",
    "abs": "Element-wise absolute value.",
    "sign": "Element-wise sign (-1, 0, +1).",

    # -- fused optimizer updates --------------------------------------
    "sgd_update": "Fused SGD step: `w -= lr * (rescale*clip(grad) + "
        "wd*w)`. All `*_update` ops apply in one kernel on-device — the "
        "TPU form of the reference's two-operand mshadow updates — and "
        "drive mx.optimizer, KVStore updaters and ShardedTrainer alike.",
    "sgd_mom_update": "Fused SGD-with-momentum step: `m = momentum*m - "
        "lr*(rescale*clip(grad) + wd*w); w += m`. Returns (weight, mom).",
    "adam_update": "Fused Adam step with bias correction `t`: updates "
        "first/second moment states and the weight in one kernel. "
        "Returns (weight, mean, var).",
    "rmsprop_update": "Fused RMSProp (Tieleman-Hinton) step: running "
        "squared-gradient state `n`, step size lr/sqrt(n+eps).",
    "rmspropalex_update": "Fused RMSPropAlex (Graves) step: states n, g "
        "and momentum delta; the non-centered variant's stabler cousin.",

    # -- quantization --------------------------------------------------
    "_contrib_dequantize": "Map int8/uint8 values back to float with the "
        "affine range [`min_range`, `max_range`] calibrated at quantize "
        "time.",
}

# -- mechanical families (generated text, one source of truth each) ----

_UNARY = {
    "arccos": "inverse cosine", "arccosh": "inverse hyperbolic cosine",
    "arcsin": "inverse sine", "arcsinh": "inverse hyperbolic sine",
    "arctan": "inverse tangent", "arctanh": "inverse hyperbolic tangent",
    "cos": "cosine", "cosh": "hyperbolic cosine",
    "sin": "sine (radians)", "sinh": "hyperbolic sine",
    "tan": "tangent", "tanh": "hyperbolic tangent",
    "exp": "exponential", "expm1": "exp(x) - 1 (accurate near zero)",
    "log": "natural logarithm", "log10": "base-10 logarithm",
    "log2": "base-2 logarithm",
    "log1p": "log(1 + x) (accurate near zero)",
    "sqrt": "square root", "rsqrt": "reciprocal square root",
    "square": "square", "reciprocal": "reciprocal (1/x)",
    "ceil": "ceiling", "floor": "floor (round down)",
    "round": "round half away from zero",
    "rint": "round to nearest even integer",
    "fix": "truncation toward zero",
    "gamma": "gamma function", "gammaln": "log of |gamma(x)|",
    "degrees": "radians-to-degrees conversion",
    "radians": "degrees-to-radians conversion",
    "relu": "rectified linear unit max(x, 0)",
    "sigmoid": "logistic sigmoid 1/(1+exp(-x))",
    "softsign": "softsign x/(1+|x|)",
}
for _n, _d in _UNARY.items():
    OPDOCS.setdefault(_n, "Element-wise %s." % _d)

_BINARY = {
    "add": "addition", "plus": "addition", "sub": "subtraction",
    "minus": "subtraction", "mul": "multiplication", "div": "division",
    "mod": "modulo", "power": "power (lhs ** rhs)",
    "maximum": "maximum", "minimum": "minimum",
    "hypot": "hypotenuse sqrt(lhs^2 + rhs^2)",
    "equal": "equality comparison (1.0/0.0)",
    "not_equal": "inequality comparison (1.0/0.0)",
    "greater": "greater-than comparison (1.0/0.0)",
    "greater_equal": "greater-or-equal comparison (1.0/0.0)",
    "lesser": "less-than comparison (1.0/0.0)",
    "lesser_equal": "less-or-equal comparison (1.0/0.0)",
}
for _n, _d in _BINARY.items():
    OPDOCS.setdefault("elemwise_%s" % _n,
                      "Element-wise %s of two same-shape arrays." % _d)
    OPDOCS.setdefault("broadcast_%s" % _n,
                      "Element-wise %s with numpy-style broadcasting of "
                      "size-1 dimensions." % _d)
    OPDOCS.setdefault("_%s" % _n,
                      "Element-wise %s of two same-shape arrays." % _d)
    OPDOCS.setdefault("_%s_scalar" % _n,
                      "Element-wise %s with a scalar operand." % _d)
for _n, _d in (("rdiv", "division"), ("rminus", "subtraction"),
               ("rmod", "modulo"), ("rpower", "power")):
    OPDOCS.setdefault("_%s_scalar" % _n,
                      "Element-wise reversed %s with a scalar operand "
                      "(scalar op x)." % _d)

_DISTS = {
    "uniform": "uniform distribution on [low, high)",
    "normal": "normal (Gaussian) distribution with mean `loc` and "
              "standard deviation `scale`",
    "gamma": "gamma distribution with shape `alpha` and scale `beta`",
    "exponential": "exponential distribution with rate `lam`",
    "poisson": "Poisson distribution with rate `lam` (float output)",
    "negative_binomial": "negative binomial distribution with `k` "
                         "failures and success probability `p`",
    "generalized_negative_binomial": "generalized negative binomial "
                                     "distribution with mean `mu` and "
                                     "dispersion `alpha`",
}
_SAMPLE_SHORT = {"negative_binomial": "negbinomial",
                 "generalized_negative_binomial": "gennegbinomial"}
for _n, _d in _DISTS.items():
    OPDOCS.setdefault(
        "_random_%s" % _n,
        "Draw a `shape` array from the %s. Seeded by the framework PRNG "
        "stream (`mx.random.seed`)." % _d)
    OPDOCS.setdefault(
        "_sample_%s" % _SAMPLE_SHORT.get(_n, _n),
        "Draw `shape` samples per row of per-distribution parameter "
        "arrays from the %s (output shape = param shape + `shape`)." % _d)

_REDUCE = {
    "sum": "sum", "mean": "arithmetic mean", "prod": "product",
    "max": "maximum", "min": "minimum",
    "nansum": "sum ignoring NaNs", "nanprod": "product ignoring NaNs",
}
for _n, _d in _REDUCE.items():
    OPDOCS.setdefault(
        _n, "Reduce by %s over `axis` (all axes when unset; `exclude` "
        "inverts the axis set; `keepdims` keeps reduced axes as size "
        "1)." % _d)


def describe(op):
    """The human description for a registered op: the compute fn's
    docstring when it has one, else this module's entry.  Raises KeyError
    for an undocumented op — the CI gate turns that into a failing test."""
    doc = (op.fn.__doc__ or "").strip()
    if doc:
        return doc
    return OPDOCS[op.name]


def op_io_summary(op):
    """Structured input/aux/output description shared by every doc
    renderer (frontend docstrings AND the generated ops.md), so the two
    surfaces cannot drift: returns a dict with

    * ``inputs``  — list of input names, or the strings
      ``"<variable>"`` / ``"<none>"`` for variable-arity / creation ops
    * ``inputs_note`` — extra caveat when the effective list is
      attr-dependent (or None)
    * ``aux``     — auxiliary state names
    * ``outputs`` — list of output names, an int count, the string
      ``"<attr-dependent>"``, or None for the common single output
    """
    if op.variable_args:
        inputs, note = "<variable>", None
    elif op.arg_names:
        inputs = list(op.arg_names)
        note = ("the effective input list depends on attrs; omitted "
                "inputs auto-create Variables"
                if op.input_names_fn is not None else None)
    else:
        inputs, note = "<none>", None
    if callable(op.num_outputs):
        outputs = "<attr-dependent>"
    elif op.num_outputs != 1:
        outputs = list(op.output_names) if op.output_names \
            else op.num_outputs
    else:
        outputs = None
    return {"inputs": inputs, "inputs_note": note,
            "aux": list(op.aux_names), "outputs": outputs}


def op_doc(op, aliases=()):
    """Full reflected docstring for a frontend op function: description,
    tensor inputs, auxiliary states, outputs, and the attribute table from
    the ParamSpecs — the reference's registry-reflected docstring pattern
    (``python/mxnet/ndarray.py`` autogen docs)."""
    try:
        desc = describe(op)
    except KeyError:
        desc = "(undocumented op)"
    lines = [desc, ""]
    io = op_io_summary(op)
    if io["inputs"] == "<variable>":
        lines.append("Inputs: variable arity (`num_args` tensors).")
    elif io["inputs"] == "<none>":
        lines.append("Inputs: none (creation op).")
    else:
        lines.append("Inputs: %s." % ", ".join(
            "`%s`" % a for a in io["inputs"]))
        if io["inputs_note"]:
            lines.append("(%s)" % io["inputs_note"])
    if io["aux"]:
        lines.append("Auxiliary states: %s (mutated by training "
                     "forward)." % ", ".join(
                         "`%s`" % a for a in io["aux"]))
    if io["outputs"] == "<attr-dependent>":
        lines.append("Outputs: attr-dependent count.")
    elif io["outputs"] is not None:
        names = (", ".join(io["outputs"])
                 if isinstance(io["outputs"], list)
                 else str(io["outputs"]))
        lines.append("Outputs: %s." % names)
    if op.params:
        lines.append("")
        lines.append("Attributes:")
        for name in sorted(op.params):
            spec = op.params[name]
            bits = [spec.type]
            if spec.required:
                bits.append("required")
            else:
                bits.append("default=%r" % (spec.default,))
            if spec.enum:
                bits.append("one of %s" % (tuple(spec.enum),))
            lines.append("    %s : %s" % (name, ", ".join(bits)))
    if aliases:
        lines.append("")
        lines.append("Aliases: %s." % ", ".join(sorted(aliases)))
    return "\n".join(lines)
