"""Watchdog-driven autoscaler: the policy engine that closes the alert
loop (ROADMAP item 4, PR-6's missing half).

The PR-6 :class:`~.watchdog.Watchdog` can only *alert* on
``queue_saturation`` / ``request_p99_slo`` / ``straggler`` breaches.
This module makes those alerts *act*:

* a watched alert that stays active for ``MXNET_TPU_AUTOSCALE_SUSTAIN_S``
  drives a **scale-up** (one transient blip never resizes a cluster);
* no watched alert for ``MXNET_TPU_AUTOSCALE_IDLE_S`` drives a
  **drain-and-shrink** (capacity follows load down as well as up);
* every action is rate-limited by ``MXNET_TPU_AUTOSCALE_COOLDOWN_S``
  (scale → re-observe → maybe scale again, never a thundering herd),
  bounded by ``MXNET_TPU_AUTOSCALE_MIN``/``MXNET_TPU_AUTOSCALE_MAX``,
  counted in ``cluster_autoscale_actions_total{action}``, and
  flight-recorded with the TRIGGERING RULE in the bundle manifest, so a
  3am resize is attributable to the exact SLO breach that caused it.

The engine is deliberately mechanism-free: ``scale_up``/``scale_down``
are caller-supplied actuators — ``serving.ReplicaGroup.grow``/
``shrink`` for the serving tier, an ``elastic.ResizePlan`` driver for PS
shards, a rank join/drain for workers.  Actuators return a dict
(``{"epoch": N, ...}``) whose epoch lands in the flight bundle — every
action is epoch-fenced by the mechanism it drives, and the fence is
recorded.

Clock injection (``clock=``) makes the sustain/cooldown/idle windows
testable without sleeping, exactly like ``Watchdog.evaluate(now=)``.
"""

from __future__ import annotations

import os
import threading
import time as _time

from .events import emit as _emit_event
from . import flight_recorder as _flight
from . import metrics as _metrics
from . import slo as _slo

__all__ = ["Autoscaler", "ScaleAction", "WATCHED_RULES"]

# the alert names that mean "capacity is short": the PR-6 stock rule
# set, the generation lane's inter-token-latency SLO (a slow decode
# step stalls every live sequence — that is a capacity signal for a
# generation replica group), sustained KV-cache block pressure (a
# nearly-full pool means CacheExhaustedError sheds are imminent and
# more replicas mean more block pools), plus the SLO fast-burn rules —
# an error budget dying fast is a capacity signal, not just a page
WATCHED_RULES = ("queue_saturation", "request_p99_slo", "straggler",
                 "inter_token_p99",
                 "kv_cache_pressure") + _slo.FAST_BURN_RULES

_M_ACTIONS = _metrics.counter(
    "cluster_autoscale_actions_total",
    "Autoscaler actions taken, by direction", ["action"])
_M_BLOCKED = _metrics.counter(
    "cluster_autoscale_blocked_total",
    "Autoscaler decisions suppressed, by reason (cooldown/bounds/failed)",
    ["reason"])


def _env_float(name, default):
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return float(default)


class ScaleAction(object):
    """One decision the autoscaler acted on (or tried to)."""

    __slots__ = ("action", "rule", "at", "ok", "epoch", "detail")

    def __init__(self, action, rule, at):
        self.action = action      # "scale_up" | "scale_down"
        self.rule = rule          # triggering rule name, or "idle"
        self.at = at              # monotonic decision time
        self.ok = False
        self.epoch = None         # the fence epoch the actuator reported
        self.detail = None

    def as_dict(self):
        return {"action": self.action, "rule": self.rule, "at": self.at,
                "ok": self.ok, "epoch": self.epoch, "detail": self.detail}


class Autoscaler(object):
    """Poll a :class:`~.watchdog.Watchdog`, turn sustained alerts into
    scale actions.

    ``scale_up(action)`` / ``scale_down(action)`` are the actuators;
    either may be None (that direction is then disabled).  ``size`` is a
    zero-argument callable reporting current capacity (replica count,
    shard count, rank count) for the min/max bounds; without it the
    bounds are not enforced.  All windows are injectable for tests and
    default to the ``MXNET_TPU_AUTOSCALE_*`` env rows."""

    def __init__(self, watchdog, scale_up=None, scale_down=None, *,
                 size=None, rules=WATCHED_RULES, sustain_s=None,
                 cooldown_s=None, idle_s=None, min_size=None,
                 max_size=None, clock=None):
        self.watchdog = watchdog
        self._up = scale_up
        self._down = scale_down
        self._size = size
        self.rules = frozenset(rules)
        self.sustain_s = (_env_float("MXNET_TPU_AUTOSCALE_SUSTAIN_S", 10.0)
                          if sustain_s is None else float(sustain_s))
        self.cooldown_s = (_env_float("MXNET_TPU_AUTOSCALE_COOLDOWN_S", 60.0)
                           if cooldown_s is None else float(cooldown_s))
        self.idle_s = (_env_float("MXNET_TPU_AUTOSCALE_IDLE_S", 300.0)
                       if idle_s is None else float(idle_s))
        self.min_size = (int(_env_float("MXNET_TPU_AUTOSCALE_MIN", 1))
                         if min_size is None else int(min_size))
        max_default = int(_env_float("MXNET_TPU_AUTOSCALE_MAX", 0))
        self.max_size = (max_default if max_size is None
                         else int(max_size)) or None  # 0/None = unbounded
        self._clock = clock or _time.monotonic
        self._lock = threading.Lock()
        self._first_seen = {}     # watched rule name -> first active time
        self._last_action_t = None
        self._busy_until = None   # last time a watched alert was active
        self.actions = []         # every acted ScaleAction, oldest first
        self._stop = threading.Event()
        self._thread = None

    # -- decision core ---------------------------------------------------

    def evaluate(self, now=None):
        """One policy pass: evaluate the watchdog, maybe act.  Returns
        the :class:`ScaleAction` taken, else None."""
        if now is None:
            now = self._clock()
        alerts = self.watchdog.evaluate(now=now)
        watched = [a for a in alerts if a.name in self.rules]
        with self._lock:
            active_names = {a.name for a in watched}
            for name in list(self._first_seen):
                if name not in active_names:
                    del self._first_seen[name]
            for name in active_names:
                self._first_seen.setdefault(name, now)
            if watched:
                self._busy_until = now
            elif self._busy_until is None:
                # idle window starts at the first evaluation, not at
                # process birth — a fresh autoscaler never insta-shrinks
                self._busy_until = now
            sustained = [n for n, t0 in self._first_seen.items()
                         if now - t0 >= self.sustain_s]
            if sustained and self._up is not None:
                # longest-burning rule is THE trigger named in the bundle
                rule = min(sustained, key=self._first_seen.get)
                return self._act("scale_up", rule, now)
            if (self._down is not None and not watched
                    and now - self._busy_until >= self.idle_s):
                return self._act("scale_down", "idle", now)
        return None

    def _act(self, direction, rule, now):
        # caller holds self._lock
        if self._last_action_t is not None \
                and now - self._last_action_t < self.cooldown_s:
            _M_BLOCKED.labels("cooldown").inc()
            return None
        size = self._size() if self._size is not None else None
        if size is not None:
            if direction == "scale_up" and self.max_size is not None \
                    and size >= self.max_size:
                _M_BLOCKED.labels("bounds").inc()
                return None
            if direction == "scale_down" and size <= self.min_size:
                _M_BLOCKED.labels("bounds").inc()
                return None
        action = ScaleAction(direction, rule, now)
        actuator = self._up if direction == "scale_up" else self._down
        try:
            result = actuator(action)
        except Exception as exc:  # noqa: BLE001 — policy must survive
            action.detail = repr(exc)
            _M_BLOCKED.labels("failed").inc()
            _flight.record_failure(
                "autoscale_failed", exc, rule=rule, action=direction,
                size=size)
            # a failed actuator still burns the cooldown: retrying a
            # broken resize every interval would thrash the cluster
            # (caller holds self._lock)
            self._last_action_t = now  # graftcheck: disable=lock-discipline
            self.actions.append(action)
            return action
        action.ok = True
        if isinstance(result, dict):
            action.epoch = result.get("epoch")
            action.detail = result
        self._last_action_t = now  # graftcheck: disable=lock-discipline
        # acting on a sustained alert resets its burn clock: the next
        # scale-up needs the breach to persist PAST the new capacity
        # (caller holds self._lock)
        if rule in self._first_seen:
            del self._first_seen[rule]
        self._busy_until = now  # graftcheck: disable=lock-discipline
        self.actions.append(action)
        _M_ACTIONS.labels(direction).inc()
        _emit_event("autoscale", action=direction, rule=rule,
                     epoch=action.epoch, size=size)
        _flight.record_failure(
            "autoscale_action", None, rule=rule, action=direction,
            epoch=action.epoch, size=size,
            alert=next((a.as_dict() for a in self.watchdog.firing()
                        if a.name == rule), None))
        return action

    # -- background loop -------------------------------------------------

    def start(self, interval_s=None):
        """Run :meth:`evaluate` every ``interval_s`` (default
        ``MXNET_TPU_AUTOSCALE_INTERVAL``) on a daemon thread."""
        interval = (_env_float("MXNET_TPU_AUTOSCALE_INTERVAL", 5.0)
                    if interval_s is None else float(interval_s))

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.evaluate()
                except Exception:
                    # the autoscaler must never take down what it scales
                    pass

        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=loop, name="mxtpu-autoscaler", daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5)
