"""Optimizers (parity: reference ``python/mxnet/optimizer.py``: SGD, NAG,
SGLD, ccSGD, Adam, AdaGrad, RMSProp, AdaDelta, Ftrl, DCASGD, Test).

Update math runs through the fused update ops in ``ops/tensor.py``
(reference ``src/operator/optimizer_op.cc``) or inline jnp expressions —
either way it jit-compiles and fuses with nothing else to schedule.  The
``Updater`` closure and ``get_updater`` keep KVStore's server-side-optimizer
contract (``kvstore.set_optimizer`` pickles an Optimizer, reference
``kvstore.py:226``).
"""

from __future__ import annotations

import math
import pickle

import numpy

from .ndarray import NDArray, zeros
from . import ndarray as nd


def _zeros_like(weight):
    """State tensor matching the weight's dtype AND device placement/sharding
    (mesh-replicated weights get mesh-replicated optimizer state)."""
    import jax.numpy as jnp

    return NDArray(jnp.zeros_like(weight._data), weight.context)

__all__ = [
    "Optimizer", "SGD", "NAG", "SGLD", "ccSGD", "Adam", "AdaGrad", "RMSProp",
    "AdaDelta", "Ftrl", "DCASGD", "Test", "Updater", "get_updater", "create",
    "register",
]


class Optimizer(object):
    """Base optimizer (parity: ``optimizer.py:Optimizer``)."""

    opt_registry = {}

    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError("Cannot find optimizer %s" % name)

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        import threading

        self._count_lock = threading.Lock()
        self.clip_gradient = clip_gradient
        if param_idx2name is None:
            param_idx2name = {}
        self.idx2name = param_idx2name.copy()
        self.sym = sym
        if sym is not None:
            attrs = sym.attr_dict()
            for name in sym.list_arguments():
                if name in attrs:
                    if "__lr_mult__" in attrs[name]:
                        self.lr_mult[name] = float(attrs[name]["__lr_mult__"])
                    if "__wd_mult__" in attrs[name]:
                        self.wd_mult[name] = float(attrs[name]["__wd_mult__"])

    def create_state(self, index, weight):
        raise NotImplementedError()

    def update(self, index, weight, grad, state):
        raise NotImplementedError()

    def set_lr_scale(self, args_lrscale):  # deprecated in reference too
        raise DeprecationWarning

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def __getstate__(self):
        # the count lock is not picklable; set_optimizer pickles optimizers
        # to the (possibly remote) updater side
        state = self.__dict__.copy()
        state.pop("_count_lock", None)
        return state

    def __setstate__(self, state):
        import threading

        self.__dict__.update(state)
        self._count_lock = threading.Lock()

    def _update_count(self, index):
        # engine-backed kvstores may run per-key updates on concurrent
        # worker threads; the read-modify-writes must be atomic or the
        # lr_scheduler sees a stale step count
        with self._count_lock:
            if index not in self._index_update_count:
                self._index_update_count[index] = self.begin_num_update
            self._index_update_count[index] += 1
            self.num_update = max(self._index_update_count[index],
                                  self.num_update)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def _fused_spec_for(self, op_name, **static):
        """Build a ``dist_tpu`` fused-step spec from a registered update
        op: ``(op, attrs, n_states, needs_t)``.  ``attrs`` is fully parsed
        with lr/wd (and t) as placeholders the fused program overwrites
        with traced values — so the update arithmetic is THE registered
        op's, the same one :meth:`update` calls (one registry, zero
        drift)."""
        from .ops.registry import get_op

        op = get_op(op_name)
        full = dict(static, lr=0.0, wd=0.0,
                    rescale_grad=self.rescale_grad,
                    clip_gradient=self.clip_gradient or -1.0)
        needs_t = "t" in op.params
        if needs_t:
            full["t"] = 1
        attrs = op.parse_attrs(full)
        return op, attrs, op.n_outputs(attrs) - 1, needs_t

    def fused_spec(self):
        """The fused reduce+update spec for the ``dist_tpu`` kvstore.
        Optimizers whose update math has no registered fused op cannot run
        on-device-fused; use ``dist_sync`` (host-side updater) for those."""
        from .base import MXNetError

        raise MXNetError(
            "%s has no fused update op: dist_tpu fuses the optimizer into "
            "the on-device sync step and needs one (sgd/adam/rmsprop). "
            "Use kvstore 'dist_sync' for host-side updaters."
            % type(self).__name__)


register = Optimizer.register


def _prep(grad_np, rescale, clip):
    g = grad_np * rescale
    if clip is not None and clip > 0:
        import jax.numpy as jnp

        g = jnp.clip(g, -clip, clip)
    return g


@register
class SGD(Optimizer):
    """SGD with momentum (parity: ``optimizer.py:SGD``), lowered to the fused
    ``sgd_update``/``sgd_mom_update`` ops."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _zeros_like(weight)

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        kwargs = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                      clip_gradient=self.clip_gradient or -1.0)
        if state is not None:
            nd.sgd_mom_update(weight, grad, state, out=[weight, state],
                              momentum=self.momentum, **kwargs)
        else:
            nd.sgd_update(weight, grad, out=weight, **kwargs)

    def fused_spec(self):
        if self.momentum:
            return self._fused_spec_for("sgd_mom_update",
                                        momentum=self.momentum)
        return self._fused_spec_for("sgd_update")


@register
class NAG(SGD):
    """Nesterov accelerated SGD (parity: ``optimizer.py:NAG``)."""

    def fused_spec(self):  # NAG's lookahead is not sgd_mom_update's math
        return Optimizer.fused_spec(self)

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        g = _prep(grad._data, self.rescale_grad, self.clip_gradient)
        if state is not None:
            mom = state._data * self.momentum
            gfull = g + wd * weight._data
            mom = mom + gfull
            g2 = gfull + self.momentum * mom
            state._set_data(mom)
            weight._set_data(weight._data - lr * g2)
        else:
            weight._set_data(weight._data - lr * (g + wd * weight._data))


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (parity: ``optimizer.py:SGLD``)."""

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        import jax

        from . import random as _random

        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        g = _prep(grad._data, self.rescale_grad, self.clip_gradient)
        noise = jax.random.normal(_random.next_key(), weight.shape,
                                  dtype=weight._data.dtype) * math.sqrt(lr)
        weight._set_data(weight._data - lr / 2 * (g + wd * weight._data) + noise)


@register
class ccSGD(SGD):
    """Same as SGD (the reference's ccSGD is a C++-side SGD clone)."""


@register
class Adam(Optimizer):
    """Adam (parity: ``optimizer.py:Adam``), fused ``adam_update`` op."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_zeros_like(weight), _zeros_like(weight))

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        t = self._index_update_count[index]
        mean, var = state
        nd.adam_update(weight, grad, mean, var, out=[weight, mean, var],
                       lr=lr, wd=wd, beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon, t=t,
                       rescale_grad=self.rescale_grad,
                       clip_gradient=self.clip_gradient or -1.0)

    def fused_spec(self):
        return self._fused_spec_for("adam_update", beta1=self.beta1,
                                    beta2=self.beta2, epsilon=self.epsilon)


@register
class AdaGrad(Optimizer):
    """AdaGrad (parity: ``optimizer.py:AdaGrad``)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return _zeros_like(weight)

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        g = _prep(grad._data, self.rescale_grad, self.clip_gradient)
        hist = state._data + jnp.square(g)
        state._set_data(hist)
        weight._set_data(
            weight._data
            - lr * (g / jnp.sqrt(hist + self.float_stable_eps) + wd * weight._data)
        )


@register
class RMSProp(Optimizer):
    """RMSProp (parity: ``optimizer.py:RMSProp``; centered=True matches the
    reference's Alex Graves variant via ``rmspropalex_update``)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (_zeros_like(weight), _zeros_like(weight),
                    _zeros_like(weight))
        return (_zeros_like(weight),)

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        kwargs = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                      clip_gradient=self.clip_gradient or -1.0,
                      gamma1=self.gamma1, epsilon=self.epsilon)
        if not self.centered:
            (n,) = state
            nd.rmsprop_update(weight, grad, n, out=[weight, n], **kwargs)
        else:
            n, g, delta = state
            nd.rmspropalex_update(weight, grad, n, g, delta,
                                  out=[weight, n, g, delta],
                                  gamma2=self.gamma2, **kwargs)

    def fused_spec(self):
        if self.centered:
            return self._fused_spec_for(
                "rmspropalex_update", gamma1=self.gamma1,
                gamma2=self.gamma2, epsilon=self.epsilon)
        return self._fused_spec_for("rmsprop_update", gamma1=self.gamma1,
                                    epsilon=self.epsilon)


@register
class AdaDelta(Optimizer):
    """AdaDelta (parity: ``optimizer.py:AdaDelta``)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_zeros_like(weight), _zeros_like(weight))

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        wd = self._get_wd(index)
        self._update_count(index)
        g = _prep(grad._data, self.rescale_grad, self.clip_gradient)
        acc_g, acc_delta = state
        new_acc_g = self.rho * acc_g._data + (1.0 - self.rho) * jnp.square(g)
        delta = (
            jnp.sqrt(acc_delta._data + self.epsilon)
            / jnp.sqrt(new_acc_g + self.epsilon)
            * g
        )
        new_acc_delta = self.rho * acc_delta._data + (1.0 - self.rho) * jnp.square(delta)
        acc_g._set_data(new_acc_g)
        acc_delta._set_data(new_acc_delta)
        weight._set_data(weight._data - delta - wd * weight._data)


@register
class Ftrl(Optimizer):
    """FTRL (parity: ``optimizer.py:Ftrl``)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (_zeros_like(weight), _zeros_like(weight))

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        g = _prep(grad._data, self.rescale_grad, self.clip_gradient)
        z, n = state
        sigma = (jnp.sqrt(n._data + jnp.square(g)) - jnp.sqrt(n._data)) / lr
        new_z = z._data + g - sigma * weight._data
        new_n = n._data + jnp.square(g)
        z._set_data(new_z)
        n._set_data(new_n)
        new_w = jnp.where(
            jnp.abs(new_z) <= self.lamda1,
            jnp.zeros_like(new_z),
            (jnp.sign(new_z) * self.lamda1 - new_z)
            / ((self.beta + jnp.sqrt(new_n)) / lr + wd),
        )
        weight._set_data(new_w)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (parity: ``optimizer.py:DCASGD``)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (_zeros_like(weight), weight.copy())

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        g = _prep(grad._data, self.rescale_grad, self.clip_gradient)
        mon, previous_weight = state
        delta = -lr * (
            g
            + wd * weight._data
            + self.lamda * g * g * (weight._data - previous_weight._data)
        )
        if mon is not None:
            m = self.momentum * mon._data + delta
            mon._set_data(m)
            delta = m
        previous_weight._set_data(weight._data)
        weight._set_data(weight._data + delta)


@register
class Test(Optimizer):
    """Test optimizer: ``w += rescale_grad * grad`` (parity:
    ``optimizer.py:706`` — used by the kvstore exact-arithmetic tests)."""

    def create_state(self, index, weight):
        return _zeros_like(weight)

    def update(self, index, weight, grad, state):
        weight._set_data(weight._data + grad._data * self.rescale_grad)
        state._set_data(weight._data)


def create(name, rescale_grad=1.0, **kwargs):
    """Create optimizer by name (parity: ``optimizer.py:create``)."""
    if isinstance(name, Optimizer):
        return name
    return Optimizer.create_optimizer(name, rescale_grad=rescale_grad, **kwargs)


class Updater(object):
    """Weight updater closure for kvstore (parity: ``optimizer.py:get_updater``)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
        self.optimizer.update(index, weight, grad, self.states[index])

    def set_states(self, states):
        self.states = pickle.loads(states)

    def get_states(self):
        return pickle.dumps(self.states)


def get_updater(optimizer):
    return Updater(optimizer)
