"""Bidirectional-LSTM sorting (parity: reference ``example/bi-lstm-sort/``
— feed a sequence of number tokens; the model emits the SORTED sequence,
one classification per output position.  Sorting needs global context,
which is exactly what the forward+backward passes of a bi-LSTM supply).

The bidirectional stack is composed from two unrolled LSTMCells (one on
the reversed sequence) with per-position concat — the cell algebra the
reference builds its ``bi_lstm_unroll`` from.

    python examples/bi_lstm_sort.py [--epochs 20]
"""

import argparse
import logging
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

import mxnet_tpu as mx

VOCAB = 16
SEQ = 5


def make_data(rng, n):
    data = rng.randint(0, VOCAB, (n, SEQ))
    labels = np.sort(data, axis=1)
    return data.astype(np.float32), labels.astype(np.float32)


def get_symbol(num_embed=24, num_hidden=64):
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    embed = mx.sym.Embedding(data, input_dim=VOCAB, output_dim=num_embed,
                             name="embed")
    steps = mx.sym.SliceChannel(embed, num_outputs=SEQ, axis=1,
                                squeeze_axis=True)
    fwd_cell = mx.rnn.LSTMCell(num_hidden=num_hidden, prefix="fwd_")
    bwd_cell = mx.rnn.LSTMCell(num_hidden=num_hidden, prefix="bwd_")
    fwd_out, _ = fwd_cell.unroll(SEQ, inputs=[steps[t] for t in range(SEQ)])
    bwd_out, _ = bwd_cell.unroll(SEQ, inputs=[steps[SEQ - 1 - t]
                                              for t in range(SEQ)])
    # each output position sees its local bidirectional state AND a
    # whole-sequence summary (final states of both directions): emitting
    # the t-th ORDER STATISTIC needs global context, not a window
    glob = mx.sym.Concat(fwd_out[-1], bwd_out[-1], dim=1)
    outs = []
    for t in range(SEQ):
        h = mx.sym.Concat(fwd_out[t], bwd_out[SEQ - 1 - t], glob, dim=1)
        h = mx.sym.Activation(mx.sym.FullyConnected(
            h, num_hidden=num_hidden, name="mix%d" % t), act_type="relu")
        outs.append(mx.sym.FullyConnected(h, num_hidden=VOCAB,
                                          name="cls%d" % t))
    stacked = mx.sym.Reshape(mx.sym.Concat(*outs, dim=1),
                             shape=(-1, SEQ, VOCAB))
    # one softmax per output position over the vocab axis
    swapped = mx.sym.SwapAxis(stacked, dim1=1, dim2=2)  # (B, VOCAB, SEQ)
    return mx.sym.SoftmaxOutput(swapped, label, multi_output=True,
                                name="softmax")


def run(epochs=20, batch=50, seed=0, log=True):
    rng = np.random.RandomState(seed)
    np.random.seed(seed + 1)
    xs, ys = make_data(rng, 1000)
    xv, yv = make_data(rng, 200)

    mod = mx.mod.Module(get_symbol(), context=mx.cpu())
    it = mx.io.NDArrayIter(xs, ys, batch_size=batch, shuffle=True, seed=2)
    mod.fit(it, num_epoch=epochs, optimizer="adam",
            optimizer_params={"learning_rate": 5e-3},
            initializer=mx.initializer.Xavier())

    mod_p = mx.mod.Module(get_symbol(), context=mx.cpu())
    mod_p.bind(data_shapes=[("data", (len(xv), SEQ))], for_training=False)
    mod_p.set_params(*mod.get_params())
    from mxnet_tpu.io import DataBatch

    mod_p.forward(DataBatch([mx.nd.array(xv)], None))
    pred = mod_p.get_outputs()[0].asnumpy().argmax(axis=1)  # (n, SEQ)
    elem_acc = float((pred == yv).mean())
    exact = float((pred == yv).all(axis=1).mean())
    if log:
        logging.info("element acc=%.3f exact-sort=%.3f", elem_acc, exact)
    return {"elem_acc": elem_acc, "exact": exact}


def main():
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=20)
    args = ap.parse_args()
    stats = run(epochs=args.epochs)
    print("bi_lstm_sort: elem_acc=%.3f exact=%.3f"
          % (stats["elem_acc"], stats["exact"]))


if __name__ == "__main__":
    main()
