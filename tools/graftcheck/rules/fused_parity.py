"""fused-parity: every fused-tier variant has a parity twin.

The fused-kernel tier's contract (ISSUE 19): a variant registered via
``ops.registry.register_variant`` ships only with a matching
``ops.fused.parity.register_parity`` registration — a kernel nobody can
falsify is a kernel nobody can trust.  The parity harness enforces the
same pairing at runtime, but only when it *runs*; this rule flags the
orphan at the registration site so review sees it on the diff.

Checked forms: ``register_variant("<op>", "<variant>", ...)`` against
``register_parity("<op>", "<variant>", ...)`` (any attribute path whose
last segment matches, so ``registry.register_variant(...)`` and
decorator usage both count).  Both names must be string literals — a
computed name defeats static pairing and is flagged as such.  Scope is
runtime files: test fixtures may register deliberately broken variants
for the harness to catch.
"""

from __future__ import annotations

import ast

from ..core import Finding, dotted_name

RULE = "fused-parity"


def _literal_pair(node):
    """(op, variant) from the call's first two args, or None."""
    if len(node.args) < 2:
        return None
    a, b = node.args[0], node.args[1]
    if isinstance(a, ast.Constant) and isinstance(a.value, str) \
            and isinstance(b, ast.Constant) and isinstance(b.value, str):
        return (a.value, b.value)
    return None


def check_fused_parity(project):
    variants = []       # (path, line, (op, variant))
    parity = set()      # (op, variant)
    non_literal = []    # (path, line, func name)
    for sf in project.runtime_files():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            if not dn:
                continue
            leaf = dn.rsplit(".", 1)[-1]
            if leaf not in ("register_variant", "register_parity"):
                continue
            pair = _literal_pair(node)
            if pair is None:
                non_literal.append((sf.path, node.lineno, leaf))
            elif leaf == "register_variant":
                variants.append((sf.path, node.lineno, pair))
            else:
                parity.add(pair)
    for path, line, leaf in non_literal:
        yield Finding(
            path, line, RULE,
            "%s() without literal op/variant names — the fused tier "
            "requires statically pairable registrations" % leaf)
    for path, line, (op, variant) in variants:
        if (op, variant) not in parity:
            yield Finding(
                path, line, RULE,
                "fused variant %s:%s has no register_parity "
                "registration (ops/fused/parity.py) — unfalsifiable "
                "kernel" % (op, variant))
