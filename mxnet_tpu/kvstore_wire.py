"""Binary zero-copy wire codec for the async parameter server.

PR 15 booked every JSON-framed byte against socket ground truth and
printed a PROJECTED savings line for the binary wire that would replace
it; this module is that wire.  One frame is::

    [8B outer length prefix — written by _send_msg, not here]
    fixed header   "<4sBBHiqqiIIIHII"  (54 bytes)
        4s magic            b"MXTB"
        B  version          1 (unknown versions -> CorruptMessageError)
        B  opcode           op-string table below; 0 = no/uncommon op
        H  flags            field-presence bits (_F_* below)
        i  rank             worker rank            (flags & _F_RANK)
        q  seq              per-worker RPC seqno   (flags & _F_SEQ)
        q  rseq             replication log seqno  (flags & _F_RSEQ)
        i  epoch            membership epoch       (flags & _F_EPOCH)
        I  n_pairs          (key, tensor) pairs
        I  n_keys           extra keys beyond the pairs (e.g. pull)
        I  n_vals           extra tensors beyond the pairs (e.g. vals)
        H  trace_len        PR-5 trace-token bytes
        I  meta_len         JSON escape-hatch bytes
        I  hdr_len          offset where raw tensor payload begins
    trace token    trace_len bytes, utf-8
    key table      (n_pairs + n_keys) x [u16 klen][klen JSON bytes]
    descriptors    (n_pairs + n_vals [+1 optimizer]) x tensor descriptor
    meta JSON      meta_len bytes — every field with no fixed slot
    payloads       raw tensor bytes, one slice per descriptor, decoded
                   ZERO-COPY (np.frombuffer on the exact slice)

Tensor descriptors carry a kind byte: 0 none, 1 raw, 2 int8-quantized
(symmetric max-abs grid from ``contrib/quantization.py`` + f32 scale),
3 top-k sparse (u32 indices + values), 4 opaque bytes (the HMAC-gated
optimizer pickle).  Kinds 2/3 are the opt-in gradient compression
(``MXNET_TPU_KV_COMPRESS``): the client quantizes/sparsifies eligible
push gradients with per-key error feedback (:class:`GradCompressor`),
the server decompresses at decode time — frames are self-describing,
so decompression needs no server-side negotiation state.

Everything malformed — truncated, bit-flipped, oversize counts, wrong
magic/version — raises typed :class:`CorruptMessageError` (never
``struct.error``): the ledger books the consumed prefix once under
op='corrupt' and the client retry ladder classifies it.
"""

from __future__ import annotations

import json as _json
import os
import struct
import threading

import numpy as _np

from .base import CorruptMessageError, MXNetError
from .observability import metrics as _metrics

__all__ = ["MAGIC", "VERSION", "encode_frame", "decode_frame",
           "is_binary_frame", "header_len", "wire_format",
           "CompressedTensor", "GradCompressor", "parse_compress_spec"]

MAGIC = b"MXTB"
VERSION = 1

_FIXED = struct.Struct("<4sBBHiqqiIIIHII")
_FIXED_LEN = _FIXED.size  # 54
_HDRLEN_OFF = _FIXED_LEN - 4  # the trailing u32 hdr_len slot

_F_RANK = 0x01
_F_SEQ = 0x02
_F_EPOCH = 0x04
_F_RSEQ = 0x08
_F_OPT = 0x10
_F_PAIRS = 0x20
_F_KEYS = 0x40
_F_VALS = 0x80
_F_TRACE = 0x100

# ops with a fixed code; anything else rides the meta JSON under "op"
_OPCODES = {"init": 1, "push": 2, "pull": 3, "push_pull": 4,
            "set_optimizer": 5, "command": 6, "heartbeat": 7, "stats": 8,
            "shutdown": 9, "replicate": 10, "promote": 11,
            "sync_follower": 12, "resize_install": 13, "resize_retire": 14,
            "resize_discard": 15, "resize_seal": 16, "resize_export": 17,
            "snapshot_export": 18}
_OPNAMES = {v: k for k, v in _OPCODES.items()}

_K_NONE, _K_RAW, _K_INT8, _K_TOPK, _K_OPAQUE = 0, 1, 2, 3, 4

_DTYPE_CODES = {"float32": 1, "float64": 2, "float16": 3, "int8": 4,
                "uint8": 5, "int16": 6, "uint16": 7, "int32": 8,
                "uint32": 9, "int64": 10, "uint64": 11, "bool": 12}
_DTYPE_NAMES = {v: k for k, v in _DTYPE_CODES.items()}

_NDIM_CAP = 32  # forged ndim bytes must not drive unbounded loops

# compression byte books: 'in' is the dense gradient bytes handed to the
# compressor, 'out' the bytes its wire form occupies — the bench's
# kv_compress_ratio is in/out
_M_COMPRESS_BYTES = _metrics.counter(
    "kv_compress_bytes_total",
    "Gradient-compression byte flow: dir='in' dense bytes entering the "
    "compressor, dir='out' compressed bytes leaving for the wire",
    ["dir"])
_H_COMP_IN = _M_COMPRESS_BYTES.labels("in")
_H_COMP_OUT = _M_COMPRESS_BYTES.labels("out")


def wire_format():
    """Frame format for OUTGOING messages (lazy env read, like every
    kvstore tunable): ``MXNET_TPU_KV_WIRE`` = ``binary`` (default) |
    ``json`` (the PR-15 frame, kept one release for interop — decode
    auto-detects by magic, so mixed fleets work either way)."""
    fmt = os.environ.get("MXNET_TPU_KV_WIRE", "binary").strip().lower()
    if fmt not in ("binary", "json"):
        raise MXNetError(
            "MXNET_TPU_KV_WIRE=%r — expected 'binary' or 'json'" % fmt)
    return fmt


def is_binary_frame(payload):
    """True when the frame body starts with the binary magic (old JSON
    frames start with a u32 header length whose bytes can never spell
    b'MXTB' followed by '{' — JSON headers are tiny and begin with
    '{')."""
    return len(payload) >= _FIXED_LEN and payload[:4] == MAGIC


def header_len(payload):
    """Framing-overhead bytes of a binary frame body (everything before
    the raw tensor payload section) — O(1) via the hdr_len slot, for
    the wire ledger's header/payload split."""
    (n,) = struct.unpack_from("<I", payload, _HDRLEN_OFF)
    return n


def _wire_key(k):
    """Keys on the wire are JSON values; tuple stripe keys ride as
    lists (shared with the JSON codec — kvstore_async imports these)."""
    return list(k) if isinstance(k, tuple) else k


def _unwire_key(k):
    return tuple(k) if isinstance(k, list) else k


# -- encode ---------------------------------------------------------------
#
# The same dtypes, shapes and keys cross the wire every step, so their
# encodings are memoized — the per-tensor Python overhead is what a
# zero-copy codec has left to pay, and caching it is how the binary
# frame beats C-optimized pickle on small tensors too.  Caches are
# size-capped so a peer feeding garbage cannot grow them unboundedly.
_CACHE_CAP = 4096
_DT_ENC_CACHE = {}
_DIMS_CACHE = {}
_KEY_ENC_CACHE = {}
_KEY_DEC_CACHE = {}


def _encode_dtype(dt):
    enc = _DT_ENC_CACHE.get(dt)
    if enc is None:
        code = _DTYPE_CODES.get(dt.name)
        if code is not None:
            enc = struct.pack("<B", code)
        else:
            name = dt.name.encode("ascii")
            enc = struct.pack("<BB", 0, len(name)) + name
        if len(_DT_ENC_CACHE) < _CACHE_CAP:
            _DT_ENC_CACHE[dt] = enc
    return enc


def _encode_dims(shape):
    enc = _DIMS_CACHE.get(shape)
    if enc is None:
        if len(shape) > _NDIM_CAP:
            raise MXNetError("tensor rank %d exceeds the wire cap of %d"
                             % (len(shape), _NDIM_CAP))
        enc = struct.pack("<B%dI" % len(shape), len(shape),
                          *(int(d) for d in shape))
        if len(_DIMS_CACHE) < _CACHE_CAP:
            _DIMS_CACHE[shape] = enc
    return enc


def _encode_key(k):
    try:
        enc = _KEY_ENC_CACHE.get(k)
        cacheable = True
    except TypeError:            # unhashable (e.g. a list-form key)
        enc, cacheable = None, False
    if enc is None:
        kb = _json.dumps(_wire_key(k),
                         separators=(",", ":")).encode("utf-8")
        if len(kb) > 0xFFFF:
            raise MXNetError(
                "kvstore key too long for the wire (%d bytes)" % len(kb))
        enc = struct.pack("<H", len(kb)) + kb
        if cacheable and len(_KEY_ENC_CACHE) < _CACHE_CAP:
            _KEY_ENC_CACHE[k] = enc
    return enc


def _decode_key(kb):
    k = _KEY_DEC_CACHE.get(kb)
    if k is None:
        k = _unwire_key(_json.loads(kb.decode("utf-8")))
        if len(_KEY_DEC_CACHE) < _CACHE_CAP:
            _KEY_DEC_CACHE[kb] = k
    return k


def _encode_tensor(v, descs, payloads):
    if v is None:
        descs.append(b"\x00")
        return
    if isinstance(v, CompressedTensor):
        if v.kind == "int8":
            descs.append(struct.pack("<B", _K_INT8)
                         + _encode_dtype(v.dtype) + _encode_dims(v.shape)
                         + struct.pack("<f", float(v.scale)))
            payloads.append(v.q.data)
        else:  # topk
            descs.append(struct.pack("<B", _K_TOPK)
                         + _encode_dtype(v.dtype) + _encode_dims(v.shape)
                         + struct.pack("<I", int(v.indices.size)))
            payloads.append(v.indices.data)
            payloads.append(v.values.data)
        return
    arr = _np.ascontiguousarray(v)
    descs.append(struct.pack("<B", _K_RAW) + _encode_dtype(arr.dtype)
                 + _encode_dims(arr.shape))
    payloads.append(arr.data)


def encode_frame(msg):
    """Serialize a message dict into one binary frame body (the caller
    adds the 8-byte outer length prefix).  Tensors under ``pairs`` /
    ``vals`` (dense ndarrays or :class:`CompressedTensor`) and the
    opaque ``optimizer`` bytes ride as raw payload slices; every other
    field must be JSON-safe, same contract as the JSON codec."""
    flags = 0
    opcode = rank = seq = rseq = epoch = 0
    pairs, keys, vals, opt = (), (), (), None
    trace = b""
    meta = {}
    for field, value in msg.items():
        if field == "op":
            opcode = _OPCODES.get(value, 0)
            if opcode == 0:
                meta[field] = value
        elif field == "rank" and value is not None:
            flags |= _F_RANK
            rank = int(value)
        elif field == "seq" and value is not None:
            flags |= _F_SEQ
            seq = int(value)
        elif field == "rseq" and value is not None:
            flags |= _F_RSEQ
            rseq = int(value)
        elif field == "epoch" and value is not None:
            flags |= _F_EPOCH
            epoch = int(value)
        elif field == "trace" and value is not None:
            flags |= _F_TRACE
            trace = str(value).encode("utf-8")
        elif field == "pairs":
            flags |= _F_PAIRS
            pairs = value
        elif field == "keys":
            flags |= _F_KEYS
            keys = value
        elif field == "vals":
            flags |= _F_VALS
            vals = value
        elif field == "optimizer":
            flags |= _F_OPT
            opt = bytes(value)
        else:
            meta[field] = value
    key_parts = [_encode_key(k)
                 for k in [k for k, _ in pairs] + list(keys)]
    descs, payloads = [], []
    for _, v in pairs:
        _encode_tensor(v, descs, payloads)
    for v in vals:
        _encode_tensor(v, descs, payloads)
    if opt is not None:
        descs.append(struct.pack("<BQ", _K_OPAQUE, len(opt)))
        payloads.append(opt)
    meta_b = (_json.dumps(meta, separators=(",", ":")).encode("utf-8")
              if meta else b"")
    hdr_len = (_FIXED_LEN + len(trace) + sum(len(p) for p in key_parts)
               + sum(len(d) for d in descs) + len(meta_b))
    fixed = _FIXED.pack(MAGIC, VERSION, opcode, flags, rank, seq, rseq,
                        epoch, len(pairs), len(keys), len(vals),
                        len(trace), len(meta_b), hdr_len)
    return b"".join([fixed, trace] + key_parts + descs + [meta_b]
                    + payloads)


# -- decode ---------------------------------------------------------------

def _decode_dtype(buf, cur):
    code = buf[cur]
    cur += 1
    if code == 0:
        n = buf[cur]
        cur += 1
        name = bytes(buf[cur:cur + n]).decode("ascii")
        cur += n
        return _np.dtype(name), cur
    name = _DTYPE_NAMES.get(code)
    if name is None:
        raise CorruptMessageError("unknown wire dtype code %d" % code)
    return _np.dtype(name), cur


def _decode_dims(buf, cur, limit):
    ndim = buf[cur]
    cur += 1
    if ndim > _NDIM_CAP or cur + 4 * ndim > limit:
        raise CorruptMessageError("corrupt tensor rank %d" % ndim)
    dims = struct.unpack_from("<%dI" % ndim, buf, cur)
    count = 1
    for d in dims:
        count *= d
    return tuple(int(d) for d in dims), count, cur + 4 * ndim


def _decode_frame_impl(payload, decompress):
    total = len(payload)
    if total < _FIXED_LEN:
        raise CorruptMessageError(
            "binary frame shorter than its fixed header")
    (magic, version, opcode, flags, rank, seq, rseq, epoch, n_pairs,
     n_keys, n_vals, trace_len, meta_len, hdr_len) = \
        _FIXED.unpack_from(payload, 0)
    if magic != MAGIC:
        raise CorruptMessageError("bad binary frame magic %r" % magic)
    if version != VERSION:
        raise CorruptMessageError(
            "unsupported binary wire version %d (this release speaks "
            "version %d)" % (version, VERSION))
    if hdr_len < _FIXED_LEN or hdr_len > total:
        raise CorruptMessageError("corrupt hdr_len %d in a %d-byte frame"
                                  % (hdr_len, total))
    # a forged count must die before it drives a loop: every key costs
    # >= 2 header bytes, every descriptor >= 1
    if 2 * (n_pairs + n_keys) + (n_pairs + n_vals) > hdr_len:
        raise CorruptMessageError("corrupt section counts (%d/%d/%d)"
                                  % (n_pairs, n_keys, n_vals))
    cur = _FIXED_LEN

    def need(n, what):
        if cur + n > hdr_len:
            raise CorruptMessageError("truncated %s section" % what)

    need(trace_len, "trace")
    trace = (bytes(payload[cur:cur + trace_len]).decode("utf-8")
             if trace_len else None)
    cur += trace_len
    all_keys = []
    for _ in range(n_pairs + n_keys):
        need(2, "key table")
        (klen,) = struct.unpack_from("<H", payload, cur)
        cur += 2
        need(klen, "key table")
        all_keys.append(_decode_key(bytes(payload[cur:cur + klen])))
        cur += klen
    # descriptor walk: payload slices are consumed in order starting at
    # hdr_len; every length is validated against the frame end BEFORE
    # the slice (np.frombuffer never over-reads)
    poff = hdr_len
    tensors = []
    n_opt = 1 if flags & _F_OPT else 0
    opt_raw = None
    for ti in range(n_pairs + n_vals + n_opt):
        need(1, "descriptor")
        kind = payload[cur]
        cur += 1
        if kind == _K_NONE:
            tensors.append(None)
            continue
        if kind == _K_OPAQUE:
            need(8, "descriptor")
            (blen,) = struct.unpack_from("<Q", payload, cur)
            cur += 8
            if poff + blen > total:
                raise CorruptMessageError("opaque payload overruns frame")
            blob = bytes(payload[poff:poff + blen])
            poff += blen
            tensors.append(blob)
            continue
        if kind not in (_K_RAW, _K_INT8, _K_TOPK):
            raise CorruptMessageError("unknown tensor kind %d" % kind)
        dt, cur = _decode_dtype(payload, cur)
        shape, count, cur = _decode_dims(payload, cur, hdr_len)
        if kind == _K_RAW:
            nbytes = count * dt.itemsize
            if poff + nbytes > total:
                raise CorruptMessageError("tensor payload overruns frame")
            arr = _np.frombuffer(payload, dtype=dt, count=count,
                                 offset=poff).reshape(shape)
            poff += nbytes
            tensors.append(arr)
        elif kind == _K_INT8:
            need(4, "descriptor")
            (scale,) = struct.unpack_from("<f", payload, cur)
            cur += 4
            if poff + count > total:
                raise CorruptMessageError("int8 payload overruns frame")
            q = _np.frombuffer(payload, dtype=_np.int8, count=count,
                               offset=poff)
            poff += count
            ct = CompressedTensor.int8(q.reshape(shape), scale, dt, shape)
            tensors.append(ct.decompress() if decompress else ct)
        else:  # _K_TOPK
            need(4, "descriptor")
            (k,) = struct.unpack_from("<I", payload, cur)
            cur += 4
            if k > count:
                raise CorruptMessageError("top-k k=%d exceeds size %d"
                                          % (k, count))
            nbytes = k * (4 + dt.itemsize)
            if poff + nbytes > total:
                raise CorruptMessageError("top-k payload overruns frame")
            idx = _np.frombuffer(payload, dtype=_np.uint32, count=k,
                                 offset=poff)
            values = _np.frombuffer(payload, dtype=dt, count=k,
                                    offset=poff + 4 * k)
            poff += nbytes
            if k and int(idx.max()) >= count:
                raise CorruptMessageError("top-k index out of range")
            ct = CompressedTensor.topk(idx, values, dt, shape)
            tensors.append(ct.decompress() if decompress else ct)
    if meta_len:
        need(meta_len, "meta")
        meta = _json.loads(bytes(payload[cur:cur + meta_len])
                           .decode("utf-8"))
        if not isinstance(meta, dict):
            raise CorruptMessageError("binary frame meta is not an object")
        cur += meta_len
    else:
        meta = {}
    if cur != hdr_len or poff != total:
        raise CorruptMessageError(
            "frame length mismatch (header %d/%d, payload %d/%d)"
            % (cur, hdr_len, poff, total))
    msg = dict(meta)
    if opcode:
        name = _OPNAMES.get(opcode)
        if name is None:
            raise CorruptMessageError("unknown opcode %d" % opcode)
        msg["op"] = name
    if flags & _F_RANK:
        msg["rank"] = rank
    if flags & _F_SEQ:
        msg["seq"] = seq
    if flags & _F_RSEQ:
        msg["rseq"] = rseq
    if flags & _F_EPOCH:
        msg["epoch"] = epoch
    if trace is not None:
        msg["trace"] = trace
    if flags & _F_OPT:
        opt_raw = tensors.pop()
        if not isinstance(opt_raw, (bytes, bytearray)):
            raise CorruptMessageError(
                "optimizer slot holds a non-opaque descriptor")
        msg["optimizer"] = bytes(opt_raw)
    if flags & _F_PAIRS:
        msg["pairs"] = list(zip(all_keys[:n_pairs], tensors[:n_pairs]))
    if flags & _F_KEYS:
        msg["keys"] = all_keys[n_pairs:]
    if flags & _F_VALS:
        msg["vals"] = tensors[n_pairs:n_pairs + n_vals]
    return msg


def decode_frame(payload, decompress=True):
    """Inverse of :func:`encode_frame`.  Dense tensors come back as
    ZERO-COPY read-only views over ``payload`` (``np.frombuffer`` on
    the exact slice); compressed tensors are decompressed to dense
    unless ``decompress=False`` (tests inspect the wire form).  Any
    malformed input raises :class:`CorruptMessageError` — never
    ``struct.error`` — at the consumed-prefix point, so the wire
    ledger's corrupt booking stays exact."""
    try:
        return _decode_frame_impl(payload, decompress)
    except CorruptMessageError:
        raise
    except (struct.error, ValueError, KeyError, IndexError, TypeError,
            UnicodeDecodeError, OverflowError) as exc:
        raise CorruptMessageError(
            "malformed binary frame: %r" % (exc,)) from exc


# -- gradient compression -------------------------------------------------

class CompressedTensor:
    """Wire form of one compressed gradient: ``int8`` (symmetric
    max-abs grid, payload = int8 codes + f32 scale) or ``topk``
    (payload = u32 flat indices + values).  Self-describing: carries
    the original dtype+shape so the decoder rebuilds a dense array."""

    __slots__ = ("kind", "dtype", "shape", "scale", "q", "indices",
                 "values")

    def __init__(self, kind, dtype, shape):
        self.kind = kind
        self.dtype = _np.dtype(dtype)
        self.shape = tuple(int(d) for d in shape)
        self.scale = 0.0
        self.q = self.indices = self.values = None

    @classmethod
    def int8(cls, q, scale, dtype, shape):
        ct = cls("int8", dtype, shape)
        ct.q = _np.ascontiguousarray(q, dtype=_np.int8)
        ct.scale = float(scale)
        return ct

    @classmethod
    def topk(cls, indices, values, dtype, shape):
        ct = cls("topk", dtype, shape)
        ct.indices = _np.ascontiguousarray(indices, dtype=_np.uint32)
        ct.values = _np.ascontiguousarray(values, dtype=dtype)
        return ct

    @property
    def wire_nbytes(self):
        """Payload bytes this tensor occupies on the wire."""
        if self.kind == "int8":
            return self.q.size  # int8: one byte per element
        return self.indices.nbytes + self.values.nbytes

    def decompress(self):
        if self.kind == "int8":
            return (self.q.astype(self.dtype) * self.dtype.type(self.scale)
                    ).reshape(self.shape)
        count = 1
        for d in self.shape:
            count *= d
        dense = _np.zeros(count, dtype=self.dtype)
        dense[self.indices] = self.values
        return dense.reshape(self.shape)


def parse_compress_spec(value=None):
    """``MXNET_TPU_KV_COMPRESS`` = ``int8`` | ``topk:<k>`` | ``0``
    (off, the default) -> ("int8", 0) | ("topk", k) | None."""
    spec = (value if value is not None
            else os.environ.get("MXNET_TPU_KV_COMPRESS", "0"))
    spec = spec.strip().lower()
    if spec in ("", "0", "off", "none"):
        return None
    if spec == "int8":
        return ("int8", 0)
    if spec.startswith("topk:"):
        try:
            k = int(spec.split(":", 1)[1])
        except ValueError:
            k = 0
        if k > 0:
            return ("topk", k)
    raise MXNetError(
        "MXNET_TPU_KV_COMPRESS=%r — expected 'int8', 'topk:<k>' or '0'"
        % spec)


def _compress_min_elems():
    """Below this element count a key is never compressed — header +
    scale overhead would eat the savings on tiny tensors."""
    return int(os.environ.get("MXNET_TPU_KV_COMPRESS_MIN", "16"))


class GradCompressor:
    """Client-side push-gradient compressor with per-key error
    feedback (the 1-bit-SGD recipe): the quantization/sparsification
    residual of step *t* is added back to the gradient of step *t+1*,
    so the compression error averages out instead of biasing the
    trajectory.

    Eligibility is negotiated per key at init time (the ISSUE's
    negotiation point): :meth:`negotiate` sees every wire key with its
    initial value and admits float32/float64 keys of at least
    ``MXNET_TPU_KV_COMPRESS_MIN`` elements; everything else (tiny
    biases, int tensors) is passed through dense.  Only pushes are ever
    compressed — init values and pulls stay exact."""

    def __init__(self, spec):
        self.kind, self.k = spec
        self._eligible = set()
        self._residual = {}
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls):
        spec = parse_compress_spec()
        return None if spec is None else cls(spec)

    def negotiate(self, wire_key, value):
        arr = _np.asarray(value)
        if arr.dtype in (_np.float32, _np.float64) \
                and arr.size >= _compress_min_elems():
            with self._lock:
                self._eligible.add(wire_key)

    def compress(self, wire_key, arr):
        """Dense gradient in, :class:`CompressedTensor` out (or the
        array unchanged when the key was not admitted at init)."""
        with self._lock:
            if wire_key not in self._eligible:
                return arr
            arr = _np.asarray(arr)
            res = self._residual.get(wire_key)
            g = arr + res.reshape(arr.shape) if res is not None else arr
            if self.kind == "int8":
                from .contrib.quantization import quantize_weight_int8

                q, scale = quantize_weight_int8(g)
                ct = CompressedTensor.int8(q, scale, arr.dtype, g.shape)
                self._residual[wire_key] = g - ct.decompress()
            else:
                flat = _np.ravel(g)
                k = min(self.k, flat.size)
                idx = _np.argpartition(_np.abs(flat),
                                       flat.size - k)[flat.size - k:]
                idx = _np.sort(idx).astype(_np.uint32)
                ct = CompressedTensor.topk(idx, flat[idx], arr.dtype,
                                           g.shape)
                residual = _np.array(flat, copy=True)
                residual[idx] = 0
                self._residual[wire_key] = residual
            if _metrics.metrics_enabled():
                _H_COMP_IN.inc(float(arr.nbytes))
                _H_COMP_OUT.inc(float(ct.wire_nbytes))
            return ct
