"""Dependency engine — Python surface over the native async scheduler.

Parity: reference ``Engine::Get()->PushAsync/NewVariable/WaitForVar/
WaitForAll`` (``include/mxnet/engine.h:75-250``); engine selection via env
(``src/engine/engine.cc:13-39``, ``MXNET_ENGINE_TYPE`` → ``MXTPU_ENGINE_TYPE``).

TPU framing: XLA/PJRT owns device async; this engine orders *host-side*
work on C++ worker pools keyed by ``FnProperty`` (normal/io/copy, the
per-device pool idea of ``threaded_engine_perdevice.cc:55-105`` at host
scope).  Production consumers: ``io.PrefetchingIter`` batch staging (IO
lane), ``model.save_checkpoint`` file writes (IO lane, with
read-after-write vars consumed by ``load_checkpoint``), and single-process
kvstore reduce/update ops (per-key write vars, ``pull`` waits).  Record
decode runs on the native RecordLoader's own C++ threads
(``native/src/recordio.cc``).  Functions pushed here are Python callables
executed on native threads (ctypes re-acquires the GIL per call, so
pure-numpy/file work overlaps fully only when it releases the GIL — same
caveat class as the reference's Python ``CustomOp`` callbacks).
``op_count()`` exposes the running op total so tests can assert the
engine is load-bearing.

Falls back to a synchronous in-process engine when the native library is
unavailable (semantics of the reference ``NaiveEngine``).
"""

from __future__ import annotations

import atexit
import ctypes
import itertools
import threading

from . import _native

__all__ = ["Var", "push", "new_variable", "wait_for_var", "wait_for_all",
           "engine_type", "FnProperty"]


class FnProperty(object):
    """Worker-pool classes (parity: ``engine.h FnProperty``)."""
    NORMAL = 0
    IO = 1
    COPY = 2


class Var(object):
    """Dependency variable (parity: ``Engine::NewVariable``)."""

    __slots__ = ("handle",)

    def __init__(self, handle):
        self.handle = handle


# --- native trampoline machinery -----------------------------------------

_cb_lock = threading.Lock()
_cb_registry = {}
_cb_seq = itertools.count(1)

_CBTYPE = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


_tls = threading.local()


def in_worker():
    """True when the calling thread is an engine worker executing an op —
    lets consumers avoid scheduling nested ops that would wait on the same
    bounded pool (pool-starvation deadlock)."""
    return getattr(_tls, "in_worker", False)


@_CBTYPE
def _run_cb(key):
    fn = _cb_registry.get(key)
    if fn is not None:
        _tls.in_worker = True
        try:
            fn()
        except Exception:  # noqa: BLE001 — exceptions can't cross the C ABI
            import traceback
            traceback.print_exc()
        finally:
            _tls.in_worker = False


@_CBTYPE
def _del_cb(key):
    with _cb_lock:
        _cb_registry.pop(key, None)


_NULL_CB = ctypes.cast(None, _CBTYPE)


class _NativeEngine(object):
    def __init__(self, lib):
        self._lib = lib

    def new_variable(self):
        return Var(self._lib.mxtpu_var_new())

    def delete_variable(self, var):
        self._lib.mxtpu_var_delete(var.handle)

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0,
             prop=FnProperty.NORMAL, name="opr"):
        key = next(_cb_seq)
        with _cb_lock:
            _cb_registry[key] = fn
        n_c, n_m = len(const_vars), len(mutable_vars)
        c_arr = (ctypes.c_void_p * max(n_c, 1))(
            *[v.handle for v in const_vars])
        m_arr = (ctypes.c_void_p * max(n_m, 1))(
            *[v.handle for v in mutable_vars])
        self._lib.mxtpu_push(_run_cb, ctypes.c_void_p(key), _del_cb,
                             c_arr, n_c, m_arr, n_m, priority, prop,
                             name.encode())

    def wait_for_var(self, var):
        self._lib.mxtpu_wait_for_var(var.handle)

    def wait_for_all(self):
        self._lib.mxtpu_wait_all()

    def engine_type(self):
        return ("NaiveEngine" if self._lib.mxtpu_engine_type() == 1
                else "ThreadedEnginePerDevice")


class _SerialEngine(object):
    """Pure-Python synchronous fallback (reference ``NaiveEngine``)."""

    def new_variable(self):
        return Var(None)

    def delete_variable(self, var):
        pass

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0,
             prop=FnProperty.NORMAL, name="opr"):
        fn()

    def wait_for_var(self, var):
        pass

    def wait_for_all(self):
        pass

    def engine_type(self):
        return "SerialEngine"


_engine = None
_engine_lock = threading.Lock()
_pushed = 0


def op_count():
    """Total ops pushed through the engine this process (both backends) —
    lets tests assert the engine is load-bearing, not ornamental."""
    return _pushed


def _get():
    global _engine
    if _engine is None:
        with _engine_lock:
            if _engine is None:
                lib = _native.lib()
                _engine = _NativeEngine(lib) if lib else _SerialEngine()
                # drain before interpreter teardown so worker threads never
                # call back into a finalized interpreter
                atexit.register(_engine.wait_for_all)
    return _engine


def new_variable():
    return _get().new_variable()


def delete_variable(var):
    _get().delete_variable(var)


def push(fn, const_vars=(), mutable_vars=(), priority=0,
         prop=FnProperty.NORMAL, name="opr"):
    """Push async host fn with read deps ``const_vars`` and write deps
    ``mutable_vars`` (parity: ``Engine::PushAsync``)."""
    global _pushed
    with _engine_lock:  # push may be called from worker threads too
        _pushed += 1
    _get().push(fn, const_vars, mutable_vars, priority, prop, name)


def wait_for_var(var):
    _get().wait_for_var(var)


def wait_for_all():
    _get().wait_for_all()


def engine_type():
    return _get().engine_type()
