"""contrib op tests (reference tier: ``tests/python/unittest/test_operator.py``
contrib sections — MultiBox*, Proposal, CTC, quantize, FFT — checked against
inline numpy references, same strategy as ``check_symbolic_forward``)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import contrib


def _nd(x):
    return mx.nd.array(np.asarray(x, dtype=np.float32))


def np_iou(a, b):
    ix1 = max(a[0], b[0]); iy1 = max(a[1], b[1])
    ix2 = min(a[2], b[2]); iy2 = min(a[3], b[3])
    inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
    ua = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) - inter
    return inter / ua if ua > 0 else 0.0


def test_multibox_prior_shapes_and_values():
    data = _nd(np.zeros((1, 3, 4, 6)))
    out = contrib.nd.MultiBoxPrior(data, sizes=(0.5, 0.25), ratios=(1, 2))
    a = out.asnumpy()
    # A = len(sizes)+len(ratios)-1 = 3 anchors per pixel
    assert a.shape == (1, 4 * 6 * 3, 4)
    # first anchor at pixel (0,0): center ((0+.5)/6, (0+.5)/4), size 0.5
    np.testing.assert_allclose(
        a[0, 0], [0.5 / 6 - 0.25, 0.5 / 4 - 0.25,
                  0.5 / 6 + 0.25, 0.5 / 4 + 0.25], rtol=1e-5)
    # second anchor: size 0.25
    np.testing.assert_allclose(
        a[0, 1], [0.5 / 6 - 0.125, 0.5 / 4 - 0.125,
                  0.5 / 6 + 0.125, 0.5 / 4 + 0.125], rtol=1e-5)
    # third anchor: size 0.5 ratio 2 → w=0.5*sqrt(2)/2, h=0.5/sqrt(2)/2
    w, h = 0.5 * np.sqrt(2) / 2, 0.5 / np.sqrt(2) / 2
    np.testing.assert_allclose(
        a[0, 2], [0.5 / 6 - w, 0.5 / 4 - h, 0.5 / 6 + w, 0.5 / 4 + h],
        rtol=1e-5)


def test_multibox_target_matching():
    anchors = np.array([[[0.0, 0.0, 0.5, 0.5],
                         [0.5, 0.5, 1.0, 1.0],
                         [0.0, 0.5, 0.5, 1.0],
                         [0.4, 0.4, 0.6, 0.6]]], dtype=np.float32)
    # one GT overlapping anchor 0 well, class 2
    label = np.array([[[2.0, 0.05, 0.05, 0.45, 0.45],
                       [-1, 0, 0, 0, 0]]], dtype=np.float32)
    cls_pred = np.zeros((1, 4, 4), dtype=np.float32)
    loc_t, loc_m, cls_t = contrib.nd.MultiBoxTarget(
        _nd(anchors), _nd(label), _nd(cls_pred), overlap_threshold=0.5)
    cls_t = cls_t.asnumpy()[0]
    assert cls_t[0] == 3.0          # matched → class+1
    assert all(cls_t[1:] == 0.0)    # others background
    m = loc_m.asnumpy()[0].reshape(4, 4)
    assert m[0].sum() == 4 and m[1:].sum() == 0
    # encoded loc target matches the manual formula
    t = loc_t.asnumpy()[0].reshape(4, 4)[0]
    aw = ah = 0.5
    gcx = gcy = 0.25; acx = acy = 0.25
    gw = gh = 0.4
    np.testing.assert_allclose(
        t, [(gcx - acx) / aw / 0.1, (gcy - acy) / ah / 0.1,
            np.log(gw / aw) / 0.2, np.log(gh / ah) / 0.2],
        rtol=1e-4, atol=1e-5)


def test_multibox_target_negative_mining():
    A = 10
    anchors = np.zeros((1, A, 4), dtype=np.float32)
    anchors[0, :, 2:] = 0.1  # tiny boxes at origin
    anchors[0, 0] = [0.0, 0.0, 0.5, 0.5]
    label = np.array([[[1.0, 0.0, 0.0, 0.5, 0.5]]], dtype=np.float32)
    cls_pred = np.random.RandomState(0).rand(1, 3, A).astype(np.float32)
    _, _, cls_t = contrib.nd.MultiBoxTarget(
        _nd(anchors), _nd(label), _nd(cls_pred),
        negative_mining_ratio=3.0, negative_mining_thresh=0.5)
    cls_t = cls_t.asnumpy()[0]
    assert cls_t[0] == 2.0
    # 1 positive → 3 negatives kept, rest ignored (-1)
    assert (cls_t == 0).sum() == 3
    assert (cls_t == -1).sum() == A - 4


def test_multibox_detection_decode_and_nms():
    anchors = np.array([[[0.1, 0.1, 0.3, 0.3],
                         [0.11, 0.11, 0.31, 0.31],
                         [0.6, 0.6, 0.9, 0.9]]], dtype=np.float32)
    # zero loc_pred → boxes = anchors
    loc_pred = np.zeros((1, 12), dtype=np.float32)
    cls_prob = np.array([[[0.1, 0.2, 0.05],     # background
                          [0.8, 0.7, 0.1],      # class 0
                          [0.1, 0.1, 0.85]]],   # class 1
                        dtype=np.float32)
    out = contrib.nd.MultiBoxDetection(
        _nd(cls_prob), _nd(loc_pred), _nd(anchors),
        nms_threshold=0.5).asnumpy()[0]
    # anchor1 suppressed by anchor0 (same class, IoU high); anchor2 kept
    r0, r1, r2 = out[0], out[1], out[2]
    assert r0[0] == 0.0 and abs(r0[1] - 0.8) < 1e-6
    np.testing.assert_allclose(r0[2:], anchors[0, 0], atol=1e-5)
    assert r1[0] == -1.0
    assert r2[0] == 1.0 and abs(r2[1] - 0.85) < 1e-6
    np.testing.assert_allclose(r2[2:], anchors[0, 2], atol=1e-5)


def test_multibox_detection_variance_decode():
    anchors = np.array([[[0.2, 0.2, 0.4, 0.4]]], dtype=np.float32)
    loc = np.array([[1.0, 0.5, 0.2, -0.2]], dtype=np.float32).reshape(1, 4)
    cls_prob = np.array([[[0.1], [0.9]]], dtype=np.float32)
    out = contrib.nd.MultiBoxDetection(
        _nd(cls_prob), _nd(loc), _nd(anchors), clip=False).asnumpy()[0][0]
    aw = ah = 0.2; acx = acy = 0.3
    cx = acx + 1.0 * 0.1 * aw
    cy = acy + 0.5 * 0.1 * ah
    w = np.exp(0.2 * 0.2) * aw / 2
    h = np.exp(-0.2 * 0.2) * ah / 2
    np.testing.assert_allclose(out[2:], [cx - w, cy - h, cx + w, cy + h],
                               rtol=1e-4)


def test_proposal_shapes_and_validity():
    rng = np.random.RandomState(0)
    B, K, H, W = 1, 3, 4, 4
    cls_prob = rng.rand(B, 2 * K, H, W).astype(np.float32)
    bbox_pred = (rng.rand(B, 4 * K, H, W).astype(np.float32) - 0.5) * 0.1
    im_info = np.array([[64.0, 64.0, 1.0]], dtype=np.float32)
    rois = contrib.nd.Proposal(
        _nd(cls_prob), _nd(bbox_pred), _nd(im_info),
        feature_stride=16, scales=(2.0,), ratios=(0.5, 1.0, 2.0),
        rpn_pre_nms_top_n=30, rpn_post_nms_top_n=8,
        rpn_min_size=4).asnumpy()
    assert rois.shape == (8, 5)
    assert (rois[:, 0] == 0).all()
    # boxes inside the image and non-degenerate
    assert (rois[:, 1] >= 0).all() and (rois[:, 3] <= 63).all()
    assert (rois[:, 3] >= rois[:, 1]).all() and (rois[:, 4] >= rois[:, 2]).all()


def _brute_force_ctc(probs, labels):
    """Sum of path probabilities over all valid alignments (tiny cases)."""
    import itertools
    T, C = probs.shape

    def collapse(path):
        out = []
        prev = None
        for p in path:
            if p != prev and p != 0:
                out.append(p)
            prev = p
        return tuple(out)

    total = 0.0
    for path in itertools.product(range(C), repeat=T):
        if collapse(path) == tuple(labels):
            p = 1.0
            for t, k in enumerate(path):
                p *= probs[t, k]
            total += p
    return -np.log(total)


def test_ctc_loss_vs_bruteforce():
    rng = np.random.RandomState(0)
    T, B, C = 4, 2, 3
    data = rng.randn(T, B, C).astype(np.float32)
    label = np.array([[1, 2], [1, 0]], dtype=np.float32)  # 0 = padding
    loss = contrib.nd.ctc_loss(_nd(data), _nd(label)).asnumpy()
    probs = np.exp(data) / np.exp(data).sum(-1, keepdims=True)
    want0 = _brute_force_ctc(probs[:, 0], [1, 2])
    want1 = _brute_force_ctc(probs[:, 1], [1])
    np.testing.assert_allclose(loss, [want0, want1], rtol=1e-4)


def test_ctc_loss_grad_finite():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.registry import get_op

    op = get_op("_contrib_ctc_loss")
    attrs = op.parse_attrs({})
    rng = np.random.RandomState(1)
    data = jnp.asarray(rng.randn(5, 1, 4).astype(np.float32))
    label = jnp.asarray(np.array([[2, 3, 0]], dtype=np.float32))

    def f(d):
        (out,), _ = op.apply(attrs, [d, label])
        return out.sum()

    g = jax.grad(f)(data)
    assert np.isfinite(np.asarray(g)).all() and np.abs(np.asarray(g)).sum() > 0


def test_quantize_dequantize_roundtrip():
    rng = np.random.RandomState(0)
    data = rng.uniform(-3, 5, (4, 7)).astype(np.float32)
    q, lo, hi = contrib.nd.quantize(
        _nd(data), _nd([-3.0]), _nd([5.0]), out_type="uint8")
    assert q.asnumpy().dtype == np.uint8
    back = contrib.nd.dequantize(q, lo, hi).asnumpy()
    assert np.abs(back - data).max() < (5 - (-3)) / 255.0 * 0.51 + 1e-6


def test_fft_ifft_roundtrip():
    rng = np.random.RandomState(0)
    data = rng.randn(3, 8).astype(np.float32)
    spec = contrib.nd.fft(_nd(data)).asnumpy()
    assert spec.shape == (3, 16)
    want = np.fft.fft(data, axis=-1)
    np.testing.assert_allclose(spec[:, 0::2], want.real, atol=1e-4)
    np.testing.assert_allclose(spec[:, 1::2], want.imag, atol=1e-4)
    # unnormalized inverse (reference cuFFT semantics): ifft(fft(x)) = d*x
    back = contrib.nd.ifft(mx.nd.array(spec)).asnumpy()
    np.testing.assert_allclose(back, data * 8, rtol=1e-4, atol=1e-4)


def test_count_sketch():
    rng = np.random.RandomState(0)
    N, d, out_dim = 2, 6, 4
    data = rng.randn(N, d).astype(np.float32)
    h = rng.randint(0, out_dim, (1, d)).astype(np.float32)
    s = (rng.randint(0, 2, (1, d)) * 2 - 1).astype(np.float32)
    out = contrib.nd.count_sketch(
        _nd(data), _nd(h), _nd(s), out_dim=out_dim).asnumpy()
    want = np.zeros((N, out_dim), dtype=np.float32)
    for i in range(d):
        want[:, int(h[0, i])] += s[0, i] * data[:, i]
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)


def test_contrib_ops_in_symbol_graph():
    # contrib ops compose into Symbol graphs like any op
    data = mx.sym.Variable("data")
    prior = contrib.sym.MultiBoxPrior(data, sizes=(0.3,), ratios=(1.0,))
    ex = prior.bind(mx.cpu(), {"data": _nd(np.zeros((1, 3, 2, 2)))})
    out = ex.forward()[0].asnumpy()
    assert out.shape == (1, 4, 4)


def test_quantized_fully_connected_matches_fake_quant():
    """_contrib_quantized_fully_connected (beyond-parity int8 MXU op):
    with symmetric ranges, int8 x int8 -> int32 rescaled must equal the
    fake-quant float path (dequantize both operands, float dot) up to
    fp32 rounding."""
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32) * 2.0
    w = rng.randn(12, 16).astype(np.float32)
    hx = float(np.abs(x).max())
    hw = float(np.abs(w).max())
    qx, xlo, xhi = contrib.nd.quantize(
        mx.nd.array(x), mx.nd.array([-hx]), mx.nd.array([hx]),
        out_type="int8")
    qw, wlo, whi = contrib.nd.quantize(
        mx.nd.array(w), mx.nd.array([-hw]), mx.nd.array([hw]),
        out_type="int8")
    assert qx.dtype == np.int8
    out = contrib.nd.quantized_fully_connected(
        qx, qw, xlo, xhi, wlo, whi, num_hidden=12).asnumpy()
    ref = (contrib.nd.dequantize(qx, xlo, xhi).asnumpy()
           @ contrib.nd.dequantize(qw, wlo, whi).asnumpy().T)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
    # asymmetric uint8 path: the zero-point cross terms must make the op
    # STILL equal the fake-quant float path
    qx8, xlo8, xhi8 = contrib.nd.quantize(
        mx.nd.array(x), mx.nd.array([float(x.min())]),
        mx.nd.array([float(x.max())]), out_type="uint8")
    out8 = contrib.nd.quantized_fully_connected(
        qx8, qw, xlo8, xhi8, wlo, whi, num_hidden=12).asnumpy()
    ref8 = (contrib.nd.dequantize(qx8, xlo8, xhi8).asnumpy()
            @ contrib.nd.dequantize(qw, wlo, whi).asnumpy().T)
    np.testing.assert_allclose(out8, ref8, rtol=1e-4, atol=1e-4)
    # and the quantization error vs the true product stays bounded by
    # the two tensors' quantization steps
    true = x @ w.T
    step = (hx / 127.0) * np.abs(w).sum(1).max() \
        + (hw / 127.0) * np.abs(x).sum(1).max()
    assert float(np.abs(out - true).max()) < step, (out, true)


def test_quantized_conv_matches_fake_quant():
    """_contrib_quantized_conv: int8 (and mixed uint8-data) convolution
    with int32 MXU accumulation must equal the fake-quant float path —
    including PADDING, where a padded slot is zero in q-space but
    b = lo - s*qmin in float space, so the zero-point corrections must
    count only valid window elements."""
    rng = np.random.RandomState(1)
    x = rng.randn(2, 3, 10, 10).astype(np.float32) * 1.7
    w = rng.randn(6, 3, 3, 3).astype(np.float32)
    hx, hw = float(np.abs(x).max()), float(np.abs(w).max())
    qw, wlo, whi = contrib.nd.quantize(
        mx.nd.array(w), mx.nd.array([-hw]), mx.nd.array([hw]),
        out_type="int8")
    conv_sym = mx.sym.Convolution(
        mx.sym.Variable("d"), mx.sym.Variable("w"), kernel=(3, 3),
        num_filter=6, pad=(1, 1), stride=(2, 2), no_bias=True)
    # asymmetric uint8 WEIGHTS too, so the s_d*b_w*win_d correction is
    # genuinely exercised (symmetric int8 weights have b_w == 0)
    qw8, wlo8, whi8 = contrib.nd.quantize(
        mx.nd.array(w), mx.nd.array([float(w.min())]),
        mx.nd.array([float(w.max())]), out_type="uint8")
    for out_type, lo_v, hi_v, (qww, wl, wh) in (
            ("int8", -hx, hx, (qw, wlo, whi)),
            ("uint8", float(x.min()), float(x.max()), (qw, wlo, whi)),
            ("uint8", float(x.min()), float(x.max()), (qw8, wlo8, whi8))):
        qx, xlo, xhi = contrib.nd.quantize(
            mx.nd.array(x), mx.nd.array([lo_v]), mx.nd.array([hi_v]),
            out_type=out_type)
        out = contrib.nd.quantized_conv(
            qx, qww, xlo, xhi, wl, wh, kernel=(3, 3), num_filter=6,
            pad=(1, 1), stride=(2, 2)).asnumpy()
        ex = conv_sym.bind(mx.cpu(), {
            "d": contrib.nd.dequantize(qx, xlo, xhi),
            "w": contrib.nd.dequantize(qww, wl, wh)})
        ref = ex.forward()[0].asnumpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4,
                                   err_msg="%s/%s" % (out_type, qww.dtype))
