"""Server-process entry point for ``tools/launch.py -s N`` (parity: the
reference's ``DMLC_ROLE=server`` processes running
``KVStoreDistServer::Run``, ``src/kvstore/kvstore_dist_server.h``).

The launcher hands this process its port/identity/secret via env
(``MXNET_TPU_SERVER_PORT``, ``MXNET_TPU_SERVER_ID``,
``MXNET_TPU_PS_SECRET``) — the dmlc tracker env contract.  The process
serves until a worker sends the ``shutdown`` op or the launcher reaps it
after the workers exit.
"""

import logging
import os

from .kvstore_async import AsyncServer


def main():
    logging.basicConfig(level=logging.INFO)
    port = int(os.environ.get("MXNET_TPU_SERVER_PORT", "0"))
    server_id = int(os.environ.get("MXNET_TPU_SERVER_ID", "0"))
    server = AsyncServer(port=port, server_id=server_id).start()
    addr_file = os.environ.get("MXNET_TPU_SERVER_ADDR_FILE")
    if addr_file:
        # port 0 = kernel-assigned (no probe-then-bind race); report the
        # actual address to the launcher atomically
        tmp = addr_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(server.address)
        os.replace(tmp, addr_file)
    logging.info("async PS shard %d serving on %s", server_id, server.address)
    server.wait_shutdown()
    server.stop()


if __name__ == "__main__":
    main()
