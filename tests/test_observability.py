"""Unified observability subsystem (mxnet_tpu.observability): the
acceptance surface for the metrics registry, cross-thread trace spans,
and the Prometheus/Perfetto exporters.

Pins the contract, not the implementation:
 - one pipelined ``ShardedTrainer.fit`` + one in-process kvstore
   round-trip populate series from >=3 subsystems in ONE Prometheus
   snapshot, and the chrome-trace JSON shows engine-lane spans parented
   under the trainer span that pushed them (across the thread hop);
 - with ``MXNET_TPU_METRICS=0`` the hot path is a constant-time guard —
   asserted by call-count on the ``_record`` seam, not wall-clock;
 - the kvstore failover/fencing counters move EXACTLY once per event;
 - the text exposition is golden-filed (name/label/type-line format).
"""

import json
import os
import re
import threading
import urllib.request

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

import mxnet_tpu as mx
from mxnet_tpu import chaos
from mxnet_tpu import engine
from mxnet_tpu import kvstore_async as ka
from mxnet_tpu import observability as obs
from mxnet_tpu.io import NDArrayIter
from mxnet_tpu.kvstore_async import (AsyncClient, AsyncServer,
                                     ReplicatedClient)
from mxnet_tpu.observability import metrics, tracing
from mxnet_tpu.parallel.trainer import ShardedTrainer

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "golden", "metrics_exposition.txt")
GOLDEN_EXEMPLARS = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "golden", "metrics_exposition_exemplars.txt")

# a valid exposition line: comment, or series (optional labels) + value
_SERIES_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?'
    r' (\+Inf|-?[0-9.e+-]+)$')


def _mlp():
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=16,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=8, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _fit_pipelined(steps=5, K=2):
    tr = ShardedTrainer(_mlp(), Mesh(np.array(jax.devices()[:2]), ("data",)),
                        data_shapes={"data": (8, 6)},
                        label_shapes={"softmax_label": (8,)},
                        momentum=0.9, rescale_grad=1.0 / 8,
                        pipeline_steps=K)
    rs = np.random.RandomState(3)
    it = NDArrayIter(rs.randn(steps * 8, 6).astype(np.float32),
                     rs.randint(0, 8, (steps * 8,)).astype(np.float32),
                     batch_size=8)
    tr.fit(it, num_epoch=1, seed=0)


def _kv_roundtrip():
    """One init + one pull over real sockets: the cheapest traffic that
    exercises the client RPC seam (kv_rpc_seconds)."""
    srv = AsyncServer(secret="obs").start()
    try:
        cli = AsyncClient(srv.address, rank=0, heartbeat=False,
                          secret="obs")
        cli.init([("w", np.zeros(4, np.float32))])
        (val,) = cli.pull(["w"])
        np.testing.assert_array_equal(val, np.zeros(4, np.float32))
        cli.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# acceptance: one fit -> one snapshot + one nested cross-thread trace
# ---------------------------------------------------------------------------

def test_fit_metrics_snapshot_and_nested_trace(tmp_path):
    obs.reset_metrics()
    obs.clear_spans()
    obs.enable_tracing()
    try:
        _fit_pipelined(steps=5, K=2)
        _kv_roundtrip()
    finally:
        obs.disable_tracing()

    # (a) a valid Prometheus snapshot ...
    text = obs.dump_metrics()
    for line in text.splitlines():
        if line.startswith("#"):
            assert re.match(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* ",
                            line), line
        else:
            assert _SERIES_RE.match(line), "malformed series line: %r" % line
    # ... with series from >=3 subsystems, all from THIS run
    assert metrics.REGISTRY.get("trainer_steps_total").value == 5
    assert metrics.REGISTRY.get("trainer_step_seconds").count == 5
    assert metrics.REGISTRY.get("prefetch_chunks_total").value >= 3
    assert metrics.REGISTRY.get("engine_push_total").labels("io").value > 0
    rpc = metrics.REGISTRY.get("kv_rpc_seconds")
    assert rpc.labels("init").count == 1 and rpc.labels("pull").count == 1
    for needle in ("trainer_step_seconds_bucket{le=", "prefetch_occupancy",
                   'kv_rpc_seconds_count{op="pull"}',
                   'engine_run_total{lane="io"}'):
        assert needle in text, needle

    # (b) chrome-trace JSON whose engine spans nest under the trainer
    # span that pushed them, across the thread hop
    out = tmp_path / "trace.json"
    obs.export_chrome_trace(str(out))
    with open(out, encoding="utf-8") as f:
        trace = json.load(f)
    spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"
             and "span_id" in e.get("args", {})]
    assert spans, "no span events in the exported trace"
    by_id = {e["args"]["span_id"]: e for e in spans}
    children = [e for e in spans if e.get("cat") == "engine"
                and e["args"].get("parent") in by_id]
    assert children, "no engine spans parented under a recorded span"
    if engine.engine_type() != "SerialEngine":
        # with the threaded engine the child really ran on a worker
        # thread: parenting survived the hop
        assert any(e["tid"] != by_id[e["args"]["parent"]]["tid"]
                   for e in children), \
            "engine children all share their parent's tid"
    names = {e["name"] for e in spans}
    assert "trainer.flush" in names and "prefetch.wait" in names


def test_metrics_http_endpoint():
    metrics.counter("obs_http_probe_total", "endpoint probe").inc()
    with obs.start_metrics_server(port=0) as srv:
        with urllib.request.urlopen(srv.url, timeout=5) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            body = resp.read().decode("utf-8")
    assert "obs_http_probe_total 1" in body
    assert "# TYPE obs_http_probe_total counter" in body


# ---------------------------------------------------------------------------
# disabled hot path: a constant-time guard, asserted by call-count
# ---------------------------------------------------------------------------

def test_disabled_metrics_skip_record_entirely(monkeypatch):
    calls = []
    monkeypatch.setattr(metrics.Counter, "_record",
                        lambda self, v: calls.append("counter"))
    monkeypatch.setattr(metrics.Gauge, "_record",
                        lambda self, v, op: calls.append("gauge"))
    monkeypatch.setattr(metrics.Histogram, "_record",
                        lambda self, v, exemplar=None:
                            calls.append("histogram"))
    c = metrics.counter("obs_gate_probe_total", "gate probe")
    g = metrics.gauge("obs_gate_probe", "gate probe")
    h = metrics.histogram("obs_gate_probe_seconds", "gate probe")

    monkeypatch.setenv("MXNET_TPU_METRICS", "0")
    for _ in range(100):
        c.inc()
        g.set(1.0)
        g.inc()
        h.observe(0.1)
    # the guard returned before _record every single time
    assert calls == []
    # spans are the same kind of no-op while tracing is off
    before = len(tracing.spans())
    with tracing.span("gated"):
        pass
    assert len(tracing.spans()) == before

    # flipping the env back on re-enables recording without re-import
    monkeypatch.setenv("MXNET_TPU_METRICS", "1")
    c.inc()
    g.set(2.0)
    h.observe(0.2)
    assert sorted(calls) == ["counter", "gauge", "histogram"]


# ---------------------------------------------------------------------------
# kvstore lifecycle counters: exactly once per event
# ---------------------------------------------------------------------------

@pytest.fixture
def _fast_kv(monkeypatch):
    monkeypatch.setattr(AsyncClient, "_BACKOFF_CAP_S", 0.1)
    monkeypatch.setenv("MXNET_TPU_PS_CALL_TIMEOUT", "2")
    monkeypatch.setenv("MXNET_TPU_PS_DEADLINE", "3")
    monkeypatch.setenv("MXNET_TPU_PS_DEAD_AFTER", "2")
    monkeypatch.setenv("MXNET_TPU_KV_REPL_SYNC", "1")
    ka.reset_membership()
    yield
    ka.reset_membership()


def _sgd_pickle():
    import pickle

    from mxnet_tpu import optimizer as opt

    return pickle.dumps(opt.SGD(learning_rate=0.1, wd=0.0))


@pytest.mark.chaos
def test_server_kill_failover_increments_counters_exactly_once(_fast_kv):
    obs.reset_metrics()
    p = AsyncServer(secret="r", server_id=0).start()
    f = AsyncServer(secret="r", server_id=0).start()
    f.rejoin(p.address)
    try:
        assert ka._M_REJOIN.value == 1
        cli = ReplicatedClient([p.address, f.address], rank=0,
                               heartbeat=False, secret="r")
        cli.set_optimizer(_sgd_pickle())
        cli.init([("w", np.zeros(4, np.float32))])
        with chaos.inject("kvstore.server_kill", "raise", seed=0,
                          match="s0:primary:push", limit=1) as inj:
            cli.push([("w", np.ones(4, np.float32))])
        assert inj.fires == 1 and f.role == "primary"
        # one kill -> ONE failover, and the chaos counter saw the rule
        assert ka._M_FAILOVER.value == 1
        assert chaos._M_FIRED.labels("kvstore.server_kill").value == 1
        # the heartbeat-age gauge is part of the registered surface even
        # with heartbeats off in this test
        assert metrics.REGISTRY.get("kv_heartbeat_age_seconds") is not None
        cli.close()
    finally:
        p.stop()
        f.stop()


def test_zombie_fencing_increments_fenced_counter_exactly_once(_fast_kv):
    obs.reset_metrics()
    p = AsyncServer(secret="r", server_id=0).start()
    f = AsyncServer(secret="r", server_id=0).start()
    f.rejoin(p.address)
    try:
        promoter = AsyncClient(f.address, rank=9, heartbeat=False,
                               secret="r")
        promoter._call({"op": "promote", "epoch": p.epoch + 1})
        promoter.close()
        # a stale write to the zombie makes its replication stream hit
        # the higher-epoch ex-follower, which fences it
        stale = AsyncClient(p.address, rank=0, heartbeat=False, secret="r")
        stale.set_optimizer(_sgd_pickle())
        deadline = 5.0
        import time
        t0 = time.monotonic()
        while p.role != "fenced":
            assert time.monotonic() - t0 < deadline, "zombie never fenced"
            time.sleep(0.02)
        assert ka._M_FENCED.value == 1
        # re-reporting the new epoch is idempotent: the role guard keeps
        # the counter at exactly one per demotion
        p._fence(f.epoch)
        p._fence(f.epoch + 1)
        assert ka._M_FENCED.value == 1
        stale.close()
    finally:
        p.stop()
        f.stop()


# ---------------------------------------------------------------------------
# exposition format: golden file
# ---------------------------------------------------------------------------

def test_prometheus_exposition_matches_golden(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_METRICS", "1")
    reg = metrics.Registry()
    req = reg.counter("demo_requests_total", "Requests served.",
                      ["method", "code"])
    req.labels("get", "200").inc(3)
    req.labels("post", "500").inc()
    reg.gauge("demo_queue_depth", "Items waiting.").set(7)
    lat = reg.histogram("demo_latency_seconds", "Request latency.",
                        buckets=(0.5, 2.0, 8.0))
    # two observations carry exemplar trace tokens: the default 0.0.4
    # exposition must stay byte-identical (exemplars are opt-in), and
    # render(exemplars=True) pins the OpenMetrics-style suffix format
    for v, tok in ((0.25, None), (0.5, "41:7"), (2.0, "41:9"),
                   (8.0, None)):
        lat.observe(v, exemplar=tok)
    with open(GOLDEN, encoding="utf-8") as fh:
        assert reg.render() == fh.read()
    with open(GOLDEN_EXEMPLARS, encoding="utf-8") as fh:
        assert reg.render(exemplars=True) == fh.read()


def test_registry_semantics():
    reg = metrics.Registry()
    fam = reg.counter("sem_total", "x", ["k"])
    # same (kind, labels) re-registration returns the SAME family ...
    assert reg.counter("sem_total", "x", ["k"]) is fam
    # ... and the same label combination the SAME handle
    h = fam.labels("a")
    assert fam.labels("a") is h
    with pytest.raises(ValueError):
        reg.gauge("sem_total", "x", ["k"])     # kind mismatch
    with pytest.raises(ValueError):
        reg.counter("sem_total", "x", [])      # label-schema mismatch
    with pytest.raises(ValueError):
        fam.labels("a", "b")                   # wrong label arity
    with pytest.raises(ValueError):
        h.inc(-1)                              # counters only go up
    h.inc(2)
    reg.reset()
    # reset zeroes values but keeps the pre-resolved handle wired
    assert fam.labels("a") is h and h.value == 0
    h.inc()
    assert h.value == 1


# ---------------------------------------------------------------------------
# profiler facade: the double-start race is gone; scope() is a span
# ---------------------------------------------------------------------------

def test_profiler_state_is_race_free(monkeypatch, tmp_path):
    starts, stops = [], []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: starts.append(d))
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: stops.append(1))
    mx.profiler.profiler_set_config(
        filename=str(tmp_path / "prof" / "p.json"))

    def hammer(state):
        barrier.wait()
        mx.profiler.profiler_set_state(state)

    barrier = threading.Barrier(8)
    threads = [threading.Thread(target=hammer, args=("run",))
               for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(starts) == 1          # the old dict-state let N through

    with mx.profiler.scope("obs_phase"):
        pass                         # scope routes through the span API
    assert any(s.name == "obs_phase" for s in tracing.spans())

    barrier = threading.Barrier(8)
    threads = [threading.Thread(target=hammer, args=("stop",))
               for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(stops) == 1
    assert not tracing.tracing_enabled()
