"""``dist_tpu`` — the TPU-native kvstore mode (SURVEY §5's named comm
surface; reference mode dispatch: ``src/kvstore/kvstore.cc:17-44``).

``dist_sync`` reproduces the reference's worker/server split: gradients
gather to the host, the updater runs as host-side imperative ops, results
scatter back.  On TPU that split costs a host round-trip per key per step.
``dist_tpu`` keeps ``dist_sync``'s synchronous exact-arithmetic semantics
but expresses push as what the hardware actually wants: ONE jitted XLA
program per key that (a) sums the per-worker gradients across the global
process mesh (ICI/DCN collective — the summation is an axis-0 sum over the
worker-stacked gradient, the same order ``dist_sync``'s host reduce uses,
so integer-valued flows agree bitwise) and (b) applies the optimizer via
the registered fused ``*_update`` op in the same program — weights and
optimizer state never leave the device between steps.  This is the
kvstore-API spelling of ``ShardedTrainer``'s fused step: same update ops,
same one-registry contract (``Optimizer.fused_spec`` mirrors exactly the
kwargs each ``Optimizer.update`` passes, and a parity test pins the two
paths bitwise).

Mode semantics vs the other dist stores:

* requires ``set_optimizer`` with a fused-op-backed optimizer for
  update-on-push; a plain ``push`` without one accumulates (the
  ``dist_sync`` default-updater behavior) — still fused, still on-device.
* ``set_updater`` is rejected: an arbitrary host callback would reintroduce
  the host round-trip this mode exists to remove (use ``dist_sync``).
"""

from __future__ import annotations

import numpy as _np

from ..base import MXNetError

__all__ = ["FusedTPUStore"]


class FusedTPUStore:
    """Per-key fused reduce+update programs over the global process mesh."""

    def __init__(self):
        import jax

        self._nproc = jax.process_count()
        self._mesh = None
        self._weights = {}   # key -> jnp array (global replicated when dist)
        self._states = {}    # key -> tuple of jnp arrays
        self._spec = None    # (update_op, static_attrs, n_states, needs_t)
        self._jits = {}      # (kind, shape, dtype) -> compiled step

    # -- plumbing ------------------------------------------------------

    def _ensure_mesh(self):
        """1-D mesh with exactly ONE device per process (hosts with
        several local chips still contribute one mesh slot — the stacked
        gradient's axis is process-sized, and the fused program runs on
        the representative device; dist_sync's reduce is likewise
        per-process)."""
        import jax
        from jax.sharding import Mesh

        if self._mesh is None:
            per_proc = {}
            for d in jax.devices():
                per_proc.setdefault(d.process_index, d)
            devs = [per_proc[p] for p in sorted(per_proc)]
            self._mesh = Mesh(_np.array(devs), ("host",))
            self._local_dev = per_proc[jax.process_index()]
        return self._mesh

    def _to_global(self, arr, stacked=False):
        """Local value -> global array on the process mesh.  The per-push
        gradient (``stacked=True``) stays on-device: its row is this
        process's addressable shard of the worker-stacked global array —
        no host round trip.  Weights/state replicate (init/restore-time
        only, so the host hop there is fine)."""
        import jax
        import jax.numpy as jnp
        from jax.experimental import multihost_utils
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self._nproc == 1:
            a = jnp.asarray(arr)
            return a[None] if stacked else a
        mesh = self._ensure_mesh()
        if stacked:
            row = jax.device_put(jnp.asarray(arr)[None], self._local_dev)
            return jax.make_array_from_single_device_arrays(
                (self._nproc,) + tuple(row.shape[1:]),
                NamedSharding(mesh, P("host")), [row])
        return multihost_utils.host_local_array_to_global_array(
            _np.asarray(arr), mesh, P())

    def _local(self, garr):
        """Local (full, replicated) view of a stored array."""
        import jax.numpy as jnp

        if self._nproc == 1:
            return garr
        return jnp.asarray(garr.addressable_shards[0].data)

    def _replicated_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self._ensure_mesh(), P())

    def _step(self, kind, shape, dtype):
        """Build/cache the fused program for one key signature.  ``kind``
        is 'accum' or the update op; the program takes
        (weight, stacked_grads, lr, wd, t, *state) and returns
        (new_weight, *new_state) — reduce and update in one compile."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        key = (kind, tuple(shape), str(dtype))
        if key in self._jits:
            return self._jits[key]
        spec = self._spec
        nproc = self._nproc

        def fn(w, gstack, lr, wd, t, *state):
            # worker-stacked sum: the same axis-0 summation order the
            # dist_sync host reduce uses (exact for integer-valued flows)
            g = jnp.sum(gstack, axis=0)
            if kind == "accum":
                return (w + g,)
            update_op, static_attrs, _, needs_t = spec
            attrs = dict(static_attrs, lr=lr, wd=wd)
            if needs_t:
                attrs["t"] = t
            outs, _ = update_op.apply(attrs, [w, g, *state])
            return tuple(outs)

        if nproc == 1:
            comp = jax.jit(fn)
        else:
            mesh = self._ensure_mesh()
            from jax.sharding import NamedSharding

            rep = NamedSharding(mesh, P())
            n_state = 0 if kind == "accum" else spec[2]
            in_sh = (rep, NamedSharding(mesh, P("host")), rep, rep, rep) \
                + (rep,) * n_state
            out_sh = (rep,) * (1 + n_state)
            comp = jax.jit(fn, in_shardings=in_sh, out_shardings=out_sh)
        self._jits[key] = comp
        return comp

    # -- store API -----------------------------------------------------

    def set_optimizer(self, optimizer):
        self._spec = optimizer.fused_spec()  # raises if not fused-capable
        self._jits = {k: v for k, v in self._jits.items()
                      if k[0] == "accum"}
        self._states = {}

    def init(self, key, value_jnp):
        self._weights[key] = self._to_global(value_jnp)
        self._states.pop(key, None)

    def __contains__(self, key):
        return key in self._weights

    def push(self, key, grad_jnp, lr=0.0, wd=0.0, t=0):
        if key not in self._weights:
            raise MXNetError("key %s has not been initialized" % key)
        w = self._weights[key]
        gstack = self._to_global(grad_jnp, stacked=True)
        if self._spec is None:
            kind, state = "accum", ()
        else:
            kind = self._spec[0].name
            state = self._states.get(key)
            if state is None:
                z = _np.zeros(w.shape, w.dtype)
                state = tuple(self._to_global(z)
                              for _ in range(self._spec[2]))
        step = self._step(kind, w.shape, w.dtype)
        outs = step(w, gstack,
                    _np.float32(lr), _np.float32(wd), _np.int32(t), *state)
        self._weights[key] = outs[0]
        if self._spec is not None:
            self._states[key] = tuple(outs[1:])

    def pull(self, key):
        if key not in self._weights:
            raise MXNetError("key %s has not been initialized" % key)
        return self._local(self._weights[key])

    # -- optimizer-state persistence ----------------------------------

    def get_states(self):
        import pickle

        return pickle.dumps({
            k: tuple(_np.asarray(self._local(s)) for s in st)
            for k, st in self._states.items()})

    def set_states(self, blob):
        import pickle

        self._states = {
            k: tuple(self._to_global(s) for s in st)
            for k, st in pickle.loads(blob).items()}
