"""Device meshes and sharding helpers — the TPU-native replacement for the
reference's multi-device machinery (``DataParallelExecutorGroup``, kvstore
``device`` mode, ``PlaceDevice`` model parallelism).

Axis conventions follow the scaling-book recipe: ``data`` (DP), ``model``
(TP), ``seq`` (SP/context parallel), ``expert`` (EP), ``pipe`` (PP).  Pick a
mesh, annotate shardings, let XLA insert the collectives over ICI.
"""

from __future__ import annotations

import numpy as _np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["data_parallel_mesh", "make_mesh", "shard_batch", "replicate",
           "local_mesh", "P", "Mesh", "NamedSharding"]


def local_mesh(axes=("data",), shape=None):
    """Mesh over all local devices with the given logical axes."""
    devs = jax.devices()
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axes) - 1)
    arr = _np.array(devs).reshape(shape)
    return Mesh(arr, axes)


def make_mesh(axis_shapes):
    """Build a mesh from {axis_name: size} over all devices.

    ``make_mesh({'data': 2, 'model': 4})`` on 8 devices gives a 2x4 mesh whose
    inner (``model``) axis maps to adjacent devices — the ICI-friendly layout.
    """
    names = tuple(axis_shapes)
    sizes = tuple(axis_shapes[n] for n in names)
    devs = jax.devices()
    n = 1
    for s in sizes:
        n *= s
    if n > len(devs):
        raise ValueError("mesh needs %d devices; only %d available" % (n, len(devs)))
    arr = _np.array(devs[:n]).reshape(sizes)
    return Mesh(arr, names)


def data_parallel_mesh(devices):
    """1-D ``data`` mesh over an explicit device list (Module multi-context)."""
    return Mesh(_np.array(devices), ("data",))


def shard_batch(mesh, array, axis=0):
    """Put an array onto the mesh sharded along the batch axis."""
    spec = [None] * array.ndim
    spec[axis] = "data"
    return jax.device_put(array, NamedSharding(mesh, P(*spec)))


def replicate(mesh, array):
    """Put an array onto the mesh fully replicated."""
    return jax.device_put(array, NamedSharding(mesh, P()))
