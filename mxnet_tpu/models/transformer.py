"""Decoder-only transformer language model — the long-context flagship of the
capability layer (the 2017 reference has no attention models; SURVEY.md §2.4
lists sequence/context parallelism as a required capability gap).

Pre-norm GPT-style blocks over ``MultiHeadAttention`` (Pallas flash attention
on-chip; ring attention across a mesh ``seq`` axis when
``context_parallel_axis='seq'``).  Same Module/fit contract as the rest of the
model zoo: inputs ``data`` (batch, seq_len) int tokens and ``softmax_label``
(batch, seq_len); single ``SoftmaxOutput`` head named ``softmax``.
"""

import contextlib

from .. import symbol as sym
from ..attribute import AttrScope


def get_symbol(num_classes=32000, seq_len=1024, num_embed=512, num_heads=8,
               num_layers=6, dropout=0.0, causal=True,
               context_parallel_axis="", dtype="float32", head="softmax",
               ce_chunk=2048, remat="none", ffn="dense", num_experts=8,
               moe_top_k=1, moe_aux_scale=0.01, **kwargs):
    """``ffn='moe'`` swaps every block's dense FFN for a ``MoELayer``
    (``num_experts`` experts of the same 4x hidden, top-``moe_top_k``
    routing); the per-layer load-balancing losses sum into one
    ``MakeLoss`` output scaled by ``moe_aux_scale``, grouped after the
    LM head (ShardedTrainer sums all loss-op outputs).  On a mesh with
    an ``expert`` axis the experts shard over it; on one chip the same
    graph runs dense (routing + capacity + dispatch still execute —
    the single-chip MoE bench row in BENCH_TABLE.md)."""
    if ffn not in ("dense", "moe"):
        raise ValueError("ffn must be 'dense' or 'moe', got %r" % (ffn,))
    aux_losses = []
    data = sym.Variable("data")
    x = sym.Embedding(data=data, input_dim=num_classes, output_dim=num_embed,
                      name="embed")
    pos = sym.Variable("pos_embed_weight", shape=(1, seq_len, num_embed))
    x = sym.broadcast_add(x, pos)
    if dtype != "float32":
        x = sym.Cast(x, dtype=dtype)

    if remat not in ("none", "block"):
        raise ValueError("remat must be 'none' or 'block', got %r" % (remat,))
    for i in range(num_layers):
        # remat='block': each layer becomes one __remat__ checkpoint
        # region (executor._remat_plan) — activations inside the block are
        # recomputed in backward, so live memory is one residual stream
        # per layer instead of every intermediate (the graph-executor
        # mirror option, reference graph_executor.cc:225-233)
        scope = (AttrScope(__remat__="l%d" % i) if remat == "block"
                 else contextlib.nullcontext())
        with scope:
            h = sym.LayerNorm(x, name="l%d_ln1" % i)
            h = sym.MultiHeadAttention(
                h, num_heads=num_heads, causal=causal,
                context_parallel_axis=context_parallel_axis,
                name="l%d_attn" % i)
            if dropout > 0:
                h = sym.Dropout(h, p=dropout, name="l%d_attndrop" % i)
            x = x + h
            h = sym.LayerNorm(x, name="l%d_ln2" % i)
            if ffn == "moe":
                m = sym.MoELayer(h, num_experts=num_experts,
                                 hidden_size=4 * num_embed,
                                 top_k=moe_top_k, name="l%d_moe" % i)
                h = m[0]
                aux_losses.append(m[1])
            else:
                h = sym.FullyConnected(h, num_hidden=4 * num_embed,
                                       flatten=False, name="l%d_ffn1" % i)
                h = sym.Activation(h, act_type="gelu", name="l%d_gelu" % i)
                h = sym.FullyConnected(h, num_hidden=num_embed,
                                       flatten=False, name="l%d_ffn2" % i)
            if dropout > 0:
                h = sym.Dropout(h, p=dropout, name="l%d_ffndrop" % i)
            x = x + h

    x = sym.LayerNorm(x, name="final_ln")
    pred = sym.Reshape(x, shape=(-1, num_embed))
    label = sym.Reshape(sym.Variable("softmax_label"), shape=(-1,))
    if head not in ("softmax", "fused_ce"):
        raise ValueError("head must be 'softmax' or 'fused_ce', got %r"
                         % (head,))
    def with_aux(head_sym):
        if not aux_losses:
            return head_sym
        total = aux_losses[0]
        for a in aux_losses[1:]:
            total = total + a
        return sym.Group([head_sym,
                          sym.MakeLoss(total * moe_aux_scale,
                                       name="moe_aux")])

    if head == "fused_ce":
        # long-context head: chunked fused linear + softmax CE — never
        # materializes the [T, vocab] logits (O(chunk*V) live instead of
        # O(T*V)); output is per-token fp32 loss, which ShardedTrainer's
        # sum-of-outputs loss consumes directly.  Reuses the FC weight
        # layout (pred_weight [V, d]) so checkpoints swap between heads
        # (the softmax head's pred_bias has no fused counterpart).
        pred_w = sym.Variable("pred_weight",
                              shape=(num_classes, num_embed))
        return with_aux(sym._contrib_fused_lm_head(
            pred, pred_w, label, name="softmax", chunk=ce_chunk))
    # vocab projection in the model dtype (the largest matmul in the
    # model — in bf16 it runs at full MXU rate with fp32 accumulation);
    # logits cast up AFTER, so softmax/loss run in fp32
    pred = sym.FullyConnected(pred, num_hidden=num_classes, name="pred")
    if dtype != "float32":
        pred = sym.Cast(pred, dtype="float32")
    return with_aux(sym.SoftmaxOutput(data=pred, label=label, name="softmax"))
