"""im2rec — pack an image directory/list into RecordIO (parity: reference
``tools/im2rec.py`` + ``tools/im2rec.cc``; same .lst format
``index\\tlabel[s]\\tpath`` and .rec/.idx output, readable by
``mx.io.ImageRecordIter``).

The bulk write path goes through the native C++ recordio writer
(``native/src/recordio.cc``) when built.  Image encode uses the framework's
``image.imencode`` (PNG/npy — no OpenCV dependency in this build).

Usage:
    python tools/im2rec.py prefix image_root --list          # make .lst
    python tools/im2rec.py prefix image_root                  # pack .rec/.idx
"""

import argparse
import os
import random
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu import recordio  # noqa: E402

_EXTS = (".jpg", ".jpeg", ".png", ".npy")


def list_images(root, recursive):
    i = 0
    cat = {}
    if recursive:
        for path, dirs, files in sorted(os.walk(root, followlinks=True)):
            dirs.sort()
            for fname in sorted(files):
                if os.path.splitext(fname)[1].lower() not in _EXTS:
                    continue
                label_dir = os.path.relpath(path, root).split(os.sep)[0]
                if label_dir not in cat:
                    cat[label_dir] = len(cat)
                yield i, os.path.relpath(os.path.join(path, fname), root), \
                    cat[label_dir]
                i += 1
    else:
        for fname in sorted(os.listdir(root)):
            if os.path.splitext(fname)[1].lower() in _EXTS:
                yield i, fname, 0
                i += 1


def write_list(args):
    entries = list(list_images(args.root, args.recursive))
    if args.shuffle:
        random.seed(100)
        random.shuffle(entries)
    n_train = int(len(entries) * args.train_ratio)
    chunks = {"train": entries[:n_train], "val": entries[n_train:]} \
        if args.train_ratio < 1.0 else {"": entries}
    for suffix, chunk in chunks.items():
        if not chunk:
            continue
        name = args.prefix + ("_" + suffix if suffix else "") + ".lst"
        with open(name, "w") as f:
            for i, (idx, path, label) in enumerate(chunk):
                f.write("%d\t%f\t%s\n" % (i, float(label), path))
        print("wrote %s (%d entries)" % (name, len(chunk)))


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            idx = int(parts[0])
            labels = [float(x) for x in parts[1:-1]]
            yield idx, parts[-1], labels


def _load_image(path):
    from mxnet_tpu.image import imread

    return imread(path)


def write_record(args, lst_path):
    out_prefix = os.path.splitext(lst_path)[0]
    rec = recordio.MXIndexedRecordIO(out_prefix + ".idx",
                                     out_prefix + ".rec", "w")
    count = 0
    for idx, rel_path, labels in read_list(lst_path):
        img = _load_image(os.path.join(args.root, rel_path))
        if args.resize:
            from mxnet_tpu.image import resize_short

            img = resize_short(img, args.resize)
        label = labels[0] if len(labels) == 1 else np.array(labels)
        packed = recordio.pack_img((0, label, idx, 0), img,
                                   quality=args.quality,
                                   img_fmt=args.encoding)
        rec.write_idx(idx, packed)
        count += 1
        if count % 1000 == 0:
            print("packed %d images" % count)
    rec.close()
    print("wrote %s.rec / .idx (%d records)" % (out_prefix, count))


def main():
    parser = argparse.ArgumentParser(
        description="make a RecordIO dataset from images",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("prefix", help="output prefix (or .lst path)")
    parser.add_argument("root", help="image root dir")
    parser.add_argument("--list", action="store_true",
                        help="generate .lst only")
    parser.add_argument("--recursive", action="store_true",
                        help="label = top-level subdir index")
    parser.add_argument("--shuffle", action="store_true", default=True)
    parser.add_argument("--no-shuffle", dest="shuffle", action="store_false")
    parser.add_argument("--train-ratio", type=float, default=1.0)
    parser.add_argument("--resize", type=int, default=0,
                        help="resize shorter edge")
    parser.add_argument("--quality", type=int, default=95)
    parser.add_argument("--encoding", type=str, default=".png",
                        choices=[".png", ".npy"])
    args = parser.parse_args()

    if args.list:
        write_list(args)
        return
    if os.path.isfile(args.prefix) and args.prefix.endswith(".lst"):
        lsts = [args.prefix]
    else:
        d = os.path.dirname(args.prefix) or "."
        base = os.path.basename(args.prefix)
        lsts = [os.path.join(d, f) for f in sorted(os.listdir(d))
                if f.startswith(base) and f.endswith(".lst")]
    if not lsts:
        sys.exit("no .lst found for prefix %s (run with --list first)"
                 % args.prefix)
    for lst in lsts:
        write_record(args, lst)


if __name__ == "__main__":
    main()
