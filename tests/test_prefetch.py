"""PrefetchFeeder (parallel.prefetch): chunk ordering, push-time size
planning, exhaustion, error propagation through engine var poison, and
chaos-drop handling.  All pure host machinery — no jit compiles — so the
whole file runs in well under a second."""

import pytest

from mxnet_tpu import chaos, engine
from mxnet_tpu.parallel.prefetch import PrefetchFeeder


class BoomError(Exception):
    pass


def _feeder(items, sizes=4, depth=2, extract=None, name="pf"):
    return PrefetchFeeder(iter(items),
                          extract=extract or (lambda b: b),
                          place=lambda host: list(host),
                          sizes=sizes, depth=depth, name=name)


def _drain(f):
    got = []
    while True:
        c = f.next_chunk()
        if c is None:
            return got
        got.append(c)


def test_chunks_arrive_in_order_with_short_tail():
    f = _feeder(list(range(10)), sizes=4)
    chunks = _drain(f)
    assert [c.host for c in chunks] == [[0, 1, 2, 3], [4, 5, 6, 7], [8, 9]]
    assert [c.count for c in chunks] == [4, 4, 2]
    assert [c.placed for c in chunks] == [c.host for c in chunks]
    assert f.next_chunk() is None  # END is sticky
    f.close()


def test_empty_iterator_yields_none_immediately():
    f = _feeder([], sizes=3)
    assert f.next_chunk() is None
    f.close()


def test_callable_sizes_planned_at_push_time_in_push_order():
    plan = iter([3, 1, 2, 4, 4, 4])
    f = _feeder(list(range(6)), sizes=lambda: next(plan))
    chunks = _drain(f)
    # fetches run in push order, each consuming the size planned when it
    # was PUSHED: 3, then 1, then 2 — the checkpoint-alignment contract
    assert [c.host for c in chunks] == [[0, 1, 2], [3], [4, 5]]
    f.close()


def test_fetch_error_reraises_original_then_reset_recovers():
    def extract(b):
        if b == 5:
            raise BoomError("bad record")
        return b

    f = _feeder(list(range(10)), sizes=4, extract=extract)
    assert f.next_chunk().host == [0, 1, 2, 3]
    # slot 1's fetch consumed 4 then blew up on 5: the ORIGINAL exception
    # surfaces at the consumer's sync point, and stays until recovery
    with pytest.raises(BoomError, match="bad record"):
        f.next_chunk()
    with pytest.raises(BoomError):
        f.next_chunk()
    # recovery: poison cleared, prefetch restarts at the iterator's
    # current position (past the poison pill)
    f.reset()
    assert [c.host for c in _drain(f)] == [[6, 7, 8, 9]]
    f.close()


def test_error_fails_later_fetches_fast():
    """A failed fetch poisons the shared order var, so refill fetches
    never touch the iterator — no data is silently consumed past an
    error."""
    pulled = []

    def extract(b):
        pulled.append(b)
        if b == 2:
            raise BoomError("x")
        return b

    f = _feeder(list(range(20)), sizes=2, extract=extract)
    assert f.next_chunk().host == [0, 1]  # also pushes slot 0's refill
    with pytest.raises(BoomError):
        f.next_chunk()
    # slot 1's fetch pulled 2 and died; the refill failed fast on the
    # poisoned order var without consuming anything
    assert pulled == [0, 1, 2]
    f.close()


@pytest.mark.chaos
def test_chaos_dropped_fetch_breaks_feeder_and_reset_recovers():
    with chaos.inject("engine.op", "drop", seed=0, limit=1,
                      match="pf.fetch0"):
        f = _feeder(list(range(12)), sizes=4)
        # slot 0's fetch was silently dropped (its 4 batches were never
        # pulled); serving slot 1 would skip data — fail loudly instead
        with pytest.raises(RuntimeError, match="lost"):
            f.next_chunk()
        with pytest.raises(RuntimeError, match="reset"):
            f.next_chunk()  # sticky until recovery
    f.reset()
    # slot 1's fetch DID run (pulled 0-3) before the loss was noticed;
    # reset resumes from the iterator's current position
    assert [c.host for c in _drain(f)] == [[4, 5, 6, 7], [8, 9, 10, 11]]
    f.close()


def test_feeder_inside_engine_op_degrades_to_sync_fetch():
    """A feeder built INSIDE an engine op (nested prefetch) must not
    push-and-wait on the bounded pool — it fetches synchronously."""
    out = []

    def run():
        f = _feeder(list(range(4)), sizes=2)
        out.append(f.next_chunk().host)
        out.append(f.next_chunk().host)
        out.append(f.next_chunk())
        f.close()

    v = engine.new_variable()
    engine.push(run, mutable_vars=[v], prop=engine.FnProperty.IO,
                name="nested_feeder")
    engine.wait_for_var(v)
    engine.delete_variable(v)
    assert out == [[0, 1], [2, 3], None]


def test_close_is_idempotent_and_next_chunk_after_close_raises():
    f = _feeder(list(range(4)), sizes=2)
    f.close()
    f.close()
    with pytest.raises(RuntimeError, match="closed"):
        f.next_chunk()


def test_depth_one_still_correct():
    f = _feeder(list(range(5)), sizes=2, depth=1)
    assert [c.host for c in _drain(f)] == [[0, 1], [2, 3], [4]]
    f.close()


def test_bad_args_rejected():
    with pytest.raises(ValueError, match="depth"):
        _feeder([1], sizes=1, depth=0)
    with pytest.raises(ValueError, match="size"):
        _feeder([1], sizes=0)
