"""Elastic scale: live PS re-striping and elastic worker rosters.

ROADMAP item 4 — generalize the epoch-fenced membership machinery from
"replica replaces dead primary" to "capacity follows load".  This module
owns the two training-side halves (``observability.autoscaler`` closes
the alert loop; ``serving.replication`` grows/shrinks serving groups):

**Live PS re-striping** (:class:`ResizePlan`) — add or remove parameter-
server shards mid-fit with a two-phase cutover:

1. *prepare* — the plan computes the epoch-bumped key→shard assignment,
   then **warm-copies** every moving key to its new owner
   (``resize_export`` → ``resize_install``) while the trainer keeps
   stepping against the old assignment.  Each copy carries the source
   per-key seqno as a *staged mark*.
2. *commit* — a short critical section (the group's routing lock, so
   same-process ops never observe the middle): ``resize_retire``
   atomically freezes each moving key on its old owner, deletes it,
   leaves a tombstone, and returns — in the same response — the
   (value, seqno) of every key whose seqno advanced past its staged
   mark, i.e. exactly the pushes that landed after the warm copy.  The
   plan installs those dirty deltas, **seals** the tombstones with the
   new shard list (``resize_seal`` — a straggler's rejection becomes a
   self-describing forwarding pointer), publishes the topology at the
   new epoch, and atomically cuts ``ServerGroup`` routing over.

Any failure rolls back (*abort*): staged copies are discarded and
retired keys are restored at their old seqnos — no key is orphaned, the
old epoch stays authoritative, and the caller sees a typed
:class:`~mxnet_tpu.base.ResizeAbortedError`.

Straggler writes to a key's old home are fenced by the tombstones with
``StaleEpochError(moved=True)`` — a *topology* staleness, handled by
``ServerGroup._routed`` (adopt the forwarded shard list / the published
topology and retry), never by replica failover.

**Worker elasticity** (:class:`WorkerRoster`) — data-parallel ranks join
or drain mid-fit; batch ownership is a pure function of the live member
list (``index % len(members) == my position``) so assignment re-balances
the moment the roster version bumps, and joiners fast-forward to the
roster's recorded (epoch, batch) progress so ``resume="auto"`` semantics
hold mid-epoch.

Chunk geometry note: a resize that changes the shard count re-chunks
big striped tensors, so per-chunk optimizer slots (momentum etc.) cannot
be remapped exactly and are reset for those keys; plain-key moves and
same-count re-shardings carry their slots bit-exactly.  Run stateless
optimizers (plain SGD) or budget a parity tolerance when resizing across
stripe counts.
"""

from __future__ import annotations

import os
import pickle
import threading
import time
import zlib

import numpy as _np

from . import chaos as _chaos
from . import kvstore_async as _ka
from .base import MXNetError, ResizeAbortedError
from .observability.events import emit as _emit_event
from .observability import flight_recorder as _flight
from .observability import metrics as _metrics

__all__ = ["ResizePlan", "WorkerRoster", "publish_topology",
           "lookup_topology", "reset_topology"]

_M_RESIZE = _metrics.counter(
    "kv_resize_total", "Elastic PS re-striping plans, by outcome",
    ["outcome"])
_M_CUTOVER = _metrics.histogram(
    "kv_resize_cutover_seconds",
    "Commit critical section of a PS resize (routing frozen)")
_M_ROSTER = _metrics.gauge(
    "elastic_worker_ranks", "Live data-parallel ranks in the roster")


# -- topology directory --------------------------------------------------
#
# Maps a ServerGroup's IDENTITY (its original spec tuple — stable across
# resizes) to the current shard list + epoch.  Process-local like the
# replica-membership directory; cross-process stragglers don't need it —
# sealed tombstones forward the new shard list from the old owner.

_TOPO_LOCK = threading.Lock()
_TOPOLOGY = {}  # group_id tuple -> {"epoch": int, "addresses": [spec...]}


def reset_topology():
    """Forget every published topology (test isolation)."""
    with _TOPO_LOCK:
        _TOPOLOGY.clear()


def publish_topology(group_id, addresses, epoch):
    """Record an epoch-bumped shard list for a group.  Monotonic: an
    older epoch never overwrites a newer one."""
    group_id = tuple(group_id)
    with _TOPO_LOCK:
        rec = _TOPOLOGY.get(group_id)
        if rec is not None and int(epoch) <= rec["epoch"]:
            return
        _TOPOLOGY[group_id] = {"epoch": int(epoch),
                               "addresses": [str(a) for a in addresses]}


def lookup_topology(group_id):
    with _TOPO_LOCK:
        rec = _TOPOLOGY.get(tuple(group_id))
        if rec is None:
            return None
        return {"epoch": rec["epoch"],
                "addresses": list(rec["addresses"])}


# -- placement math ------------------------------------------------------

def _prod(shape):
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _chunk_slices(size, n):
    """Flat (start, end) per chunk, matching ``np.array_split``."""
    base, extra = divmod(size, n)
    out, off = [], 0
    for i in range(n):
        ln = base + (1 if i < extra else 0)
        out.append((off, off + ln))
        off += ln
    return out


def _placement(specs, key, shape, bound):
    """[(shard_idx, wire_key, flat_slice | None)] under one topology —
    the same pure function of (element count, bound, shard count) that
    ``ServerGroup._split`` / ``server_of`` route by."""
    n = len(specs)
    size = _prod(shape)
    if n > 1 and size >= bound:
        return [(i, ("stripe", key, i), sl)
                for i, sl in enumerate(_chunk_slices(size, n))]
    return [(zlib.crc32(repr(key).encode("utf-8")) % n, key, None)]


def _state_key(wire_key):
    return repr(wire_key) if isinstance(wire_key, tuple) else wire_key


def _batch_keys():
    return max(1, int(os.environ.get("MXNET_TPU_RESIZE_BATCH_KEYS", "64")))


def _batched(items, n):
    for i in range(0, len(items), n):
        yield items[i:i + n]


class _KeyPlan:
    """Transfer plan for ONE base key across the resize."""

    __slots__ = ("key", "shape", "size", "old_parts", "new_parts",
                 "persist", "warm", "colliding", "src_seq", "s0", "dirty")

    def __init__(self, key, shape, old_specs, new_specs, bound):
        self.key = key
        self.shape = tuple(int(d) for d in shape)
        self.size = _prod(self.shape)
        self.old_parts = [(old_specs[i], wk, sl) for i, wk, sl
                          in _placement(old_specs, key, self.shape, bound)]
        self.new_parts = [(new_specs[i], wk, sl) for i, wk, sl
                          in _placement(new_specs, key, self.shape, bound)]
        old_ident = set(self.old_parts)
        # parts identical under both topologies stay put: not exported,
        # not retired, not re-installed
        self.persist = {p for p in self.new_parts if p in old_ident}
        occupied = {(spec, wk) for spec, wk, _ in self.old_parts}
        # a new part whose (shard, wire key) is live under the OLD
        # placement with different geometry cannot be warm-staged — the
        # old key is still serving reads — so it transfers inside the
        # commit critical section instead
        self.colliding = [p for p in self.new_parts
                          if p not in self.persist
                          and (p[0], p[1]) in occupied]
        self.warm = [p for p in self.new_parts
                     if p not in self.persist and p not in self.colliding]
        self.src_seq = {}   # old wire key -> seqno at export
        self.s0 = 0
        self.dirty = False

    @property
    def moving(self):
        return len(self.persist) != len(self.new_parts) \
            or len(self.old_parts) != len(self.new_parts)

    def retired_parts(self):
        return [p for p in self.old_parts if p not in self.persist]


class ResizePlan:
    """Two-phase live re-striping of a :class:`~mxnet_tpu.kvstore_async.
    ServerGroup` onto a new shard list.

    ``keys`` is the full ``[(key, shape), ...]`` inventory of the store
    (``KVStore.resize`` derives it from its local mirror).  Typical use::

        plan = ResizePlan(group, new_addresses, keys)
        plan.run()        # prepare + commit, abort-on-failure
        plan.cutover_ms   # routing-frozen window, for the bench

    ``prepare``/``commit``/``abort`` are also public for tests and for
    callers that want to overlap the warm copy with training exactly.
    """

    def __init__(self, group, new_addresses, keys, secret=None):
        self._group = group
        self._old_specs = list(group._specs)
        self._new_specs = [group._normalize_spec(a) for a in new_addresses]
        if not self._new_specs:
            raise ValueError("ResizePlan: empty new shard list")
        self.new_epoch = group.topology_epoch + 1
        self._secret = secret or group._secret \
            or os.environ.get("MXNET_TPU_PS_SECRET")
        self._plans = [_KeyPlan(k, s, self._old_specs, self._new_specs,
                                group._bound) for k, s in keys]
        self._moving = [p for p in self._plans if p.moving]
        self._base = {}       # key -> flat np array (moving segments)
        self._states = {}     # state_key -> optimizer slot (by NEW home)
        self._opt_raw = None  # set_optimizer pickle forwarded by exports
        self._installed = []  # (spec, [wire keys]) — staged/commit installs
        self._retired = []    # (spec, [wire keys]) — for abort restore
        self._clients = {}
        self.state = "new"
        self.cutover_ms = None

    # -- shard RPC plumbing ---------------------------------------------

    def _client(self, spec):
        cli = self._clients.get(spec)
        if cli is None:
            reps = spec.split("|")
            rank = -next(_ka._rejoin_ranks)
            if len(reps) > 1:
                cli = _ka.ReplicatedClient(reps, rank, heartbeat=False,
                                           secret=self._secret)
            else:
                cli = _ka.AsyncClient(reps[0], rank, heartbeat=False,
                                      secret=self._secret)
            self._clients[spec] = cli
        return cli

    def close(self):
        for cli in self._clients.values():
            cli.close()
        self._clients = {}

    def _states_payload(self, wire_keys):
        """(raw, mac) optimizer payload for these wire keys, or None."""
        states = {sk: self._states[sk]
                  for sk in (_state_key(wk) for wk in wire_keys)
                  if sk in self._states}
        if not states:
            return None
        raw = pickle.dumps({"states": states})
        return raw, _ka._optimizer_mac(self._secret or "", raw)

    def _take_states(self, resp):
        """Verify + absorb an export/retire response's optimizer slots."""
        raw = resp.get("optimizer")
        if raw is None:
            return
        mac = _ka._optimizer_mac(self._secret or "", raw)
        import hmac as _hmaclib

        if not _hmaclib.compare_digest(resp.get("mac", ""), mac):
            raise MXNetError(
                "resize transfer rejected: bad or missing HMAC on the "
                "optimizer-state payload (shards must share the per-job "
                "secret)")
        payload = pickle.loads(raw)
        self._states.update(payload.get("states", {}))
        if payload.get("opt_raw") is not None:
            self._opt_raw = payload["opt_raw"]

    def _install(self, spec, triples, extra_states=True):
        """``resize_install`` a batch of (wire_key, flat value, seqno)."""
        for batch in _batched(triples, _batch_keys()):
            msg = {"op": "resize_install",
                   "pairs": [(wk, v) for wk, v, _ in batch],
                   "seqlist": [[_ka._wire_key(wk), int(sq)]
                               for wk, _, sq in batch]}
            if extra_states:
                payload = self._states_payload([wk for wk, _, _ in batch])
                if payload is not None:
                    msg["optimizer"], msg["mac"] = payload
            self._client(spec)._call(dict(msg))
            self._installed.append((spec, [wk for wk, _, _ in batch]))

    def _fill(self, plan, sl, val):
        """Absorb one exported/dirty part into the key's base array."""
        flat = _np.asarray(val).ravel()
        if self._base.get(plan.key) is None:
            self._base[plan.key] = _np.zeros(plan.size, dtype=flat.dtype)
        if sl is None:
            self._base[plan.key][:] = flat
        else:
            self._base[plan.key][sl[0]:sl[1]] = flat

    def _part_value(self, plan, sl):
        """One part's install payload: a flat chunk (striped) or the
        full tensor in its original shape (plain key)."""
        flat = self._base[plan.key]
        if sl is None:
            return flat.reshape(plan.shape)
        return flat[sl[0]:sl[1]]

    # -- phase 1: warm copy ----------------------------------------------

    def prepare(self):
        """Export every moving key from its old owner and stage it on
        its new owner, recording staged seqno marks.  The trainer keeps
        pushing through the old assignment the whole time."""
        if self.state != "new":
            raise MXNetError("ResizePlan.prepare: plan is %s" % self.state)
        try:
            per_old = {}  # old spec -> [(plan, wire_key, slice)]
            for plan in self._moving:
                _chaos.visit("kvstore.resize_drop",
                             name="prepare:%r" % (plan.key,))
                self._base[plan.key] = None
                for spec, wk, sl in plan.retired_parts():
                    per_old.setdefault(spec, []).append((plan, wk, sl))
            for spec, parts in sorted(per_old.items()):
                for batch in _batched(parts, _batch_keys()):
                    resp = self._client(spec)._call(
                        {"op": "resize_export",
                         "keys": [wk for _, wk, _ in batch]})
                    seqs = {_ka._unwire_key(k): int(n)
                            for k, n in resp.get("seqlist", [])}
                    for (plan, wk, sl), val in zip(batch, resp["vals"]):
                        self._fill(plan, sl, val)
                        plan.src_seq[wk] = seqs.get(wk, 0)
                    self._take_states(resp)
            for plan in self._moving:
                plan.s0 = max(plan.src_seq.values(), default=0)
            # a shard that joined AFTER set_optimizer has no updater and
            # would reject every post-cutover push: configure it from
            # the optimizer pickle the exports forwarded
            if self._opt_raw is not None:
                for spec in self._new_specs:
                    if spec not in self._old_specs:
                        self._client(spec).set_optimizer(self._opt_raw)
            per_new = {}  # new spec -> [(wk, value, seq)]
            for plan in self._moving:
                for spec, wk, sl in plan.warm:
                    per_new.setdefault(spec, []).append(
                        (wk, self._part_value(plan, sl), plan.s0 + 1))
            for spec, triples in sorted(per_new.items()):
                self._install(spec, triples)
        except Exception:
            self.state = "failed"
            raise
        self.state = "prepared"
        _emit_event("resize", phase="prepared",
                     group=",".join(self._group.group_id),
                     moving=len(self._moving), epoch=self.new_epoch)
        return self

    # -- phase 2: cutover ------------------------------------------------

    def commit(self):
        """Freeze, delta-copy, seal, publish, adopt — all inside the
        group's routing lock, so same-process ops go straight from the
        old assignment to the new one with no observable middle."""
        if self.state != "prepared":
            raise MXNetError("ResizePlan.commit: plan is %s" % self.state)
        per_old = {}  # old spec -> [(plan, wire_key, slice)]
        for plan in self._moving:
            for spec, wk, sl in plan.retired_parts():
                per_old.setdefault(spec, []).append((plan, wk, sl))
        t0 = time.monotonic()
        try:
            with self._group.routing_frozen():
                for spec, parts in sorted(per_old.items()):
                    _chaos.visit("kvstore.resize_drop",
                                 name="commit:%s" % spec)
                    wks = [wk for _, wk, _ in parts]
                    staged = [[_ka._wire_key(wk), int(plan.src_seq[wk])]
                              for plan, wk, _ in parts]
                    resp = self._client(spec)._call(
                        {"op": "resize_retire", "keys": wks,
                         "new_epoch": self.new_epoch, "staged": staged})
                    self._retired.append((spec, wks))
                    dseqs = {_ka._unwire_key(k): int(n)
                             for k, n in resp.get("seqlist", [])}
                    by_wk = {wk: (plan, sl) for plan, wk, sl in parts}
                    for wk, val in resp.get("pairs", []):
                        plan, sl = by_wk[wk]
                        self._fill(plan, sl, val)
                        plan.src_seq[wk] = dseqs.get(
                            wk, plan.src_seq.get(wk, 0))
                        plan.dirty = True
                    self._take_states(resp)
                # install the commit-phase content: every colliding part,
                # plus ALL non-persisting parts of any dirty key
                per_new = {}
                for plan in self._moving:
                    parts = list(plan.colliding)
                    if plan.dirty:
                        parts = plan.colliding + plan.warm
                    for spec, wk, sl in parts:
                        per_new.setdefault(spec, []).append(
                            (wk, self._part_value(plan, sl), plan.s0 + 2))
                for spec, triples in sorted(per_new.items()):
                    self._install(spec, triples)
                # seal: moved rejections now forward the new shard list
                for spec, wks in self._retired:
                    self._client(spec)._call(
                        {"op": "resize_seal", "keys": wks,
                         "addresses": list(self._new_specs),
                         "new_epoch": self.new_epoch})
                publish_topology(self._group.group_id, self._new_specs,
                                 self.new_epoch)
                self._group.adopt_topology(self._new_specs, self.new_epoch)
        except Exception:
            self.state = "failed"
            raise
        dt = time.monotonic() - t0
        self.cutover_ms = dt * 1000.0
        _M_CUTOVER.observe(dt)
        _M_RESIZE.labels("committed").inc()
        self.state = "committed"
        _emit_event("resize", phase="committed",
                     group=",".join(self._group.group_id),
                     cutover_ms=round(self.cutover_ms, 3),
                     epoch=self.new_epoch)
        return self

    # -- rollback ---------------------------------------------------------

    def abort(self):
        """Roll back to the old assignment at the old epoch: discard
        every staged/committed install, restore every retired key at its
        last seqno, clear tombstones.  Idempotent and safe after a
        partial prepare or a partial commit."""
        if self.state in ("committed", "aborted"):
            raise MXNetError("ResizePlan.abort: plan is %s" % self.state)
        failures = []
        for spec, wks in self._installed:
            try:
                self._client(spec)._call(
                    {"op": "resize_discard", "keys": list(wks)})
            except Exception as exc:  # noqa: BLE001 — best-effort rollback
                failures.append((spec, exc))
        for spec, wks in self._retired:
            triples = []
            for plan in self._moving:
                for pspec, wk, sl in plan.retired_parts():
                    if pspec == spec and wk in wks:
                        triples.append((wk, self._part_value(plan, sl),
                                        plan.src_seq.get(wk, 1)))
            try:
                self._install(spec, triples)
            except Exception as exc:  # noqa: BLE001 — best-effort rollback
                failures.append((spec, exc))
        self._installed = []
        self._retired = []
        self.state = "aborted"
        _M_RESIZE.labels("aborted").inc()
        _emit_event("resize", phase="aborted",
                     group=",".join(self._group.group_id),
                     restore_failures=len(failures))
        _flight.record_failure(
            "resize_aborted",
            group=",".join(self._group.group_id),
            old=",".join(self._old_specs), new=",".join(self._new_specs),
            epoch=self._group.topology_epoch,
            restore_failures=len(failures))
        if failures:
            raise MXNetError(
                "ResizePlan.abort: rollback left %d shard(s) unrestored: "
                "%s" % (len(failures),
                        "; ".join("%s: %r" % f for f in failures)))
        return self

    def run(self):
        """prepare + commit; any failure aborts (rollback to the old
        epoch) and re-raises as :class:`ResizeAbortedError`."""
        try:
            self.prepare()
            self.commit()
        except Exception as exc:  # noqa: BLE001 — abort on ANY failure
            try:
                self.abort()
            finally:
                self.close()
            raise ResizeAbortedError(
                "resize %s -> %s aborted at the old epoch (%d): %r"
                % (",".join(self._old_specs), ",".join(self._new_specs),
                   self._group.topology_epoch, exc)) from exc
        self.close()
        return self


# -- worker elasticity ---------------------------------------------------

class WorkerRoster:
    """Elastic membership for data-parallel workers.

    Batch ownership is a pure function of the member list: worker at
    sorted position ``p`` of ``n`` members owns global batch ``i`` iff
    ``i % n == p`` — so a ``join``/``drain`` re-balances the assignment
    for everyone at the next batch boundary with no coordinator.

    Mid-epoch handoff: the fit loop records (epoch, next batch index)
    through :meth:`mark_progress`; a joining worker reads
    :meth:`resume_point` and fast-forwards its iterator so the epoch's
    already-consumed batches are not re-trained (``resume="auto"``
    semantics across a roster change)."""

    def __init__(self, ranks=(0,)):
        self._lock = threading.Lock()
        self._members = sorted(set(ranks))
        self.version = 0
        self._progress = (0, 0)  # (epoch, next batch index)
        _M_ROSTER.set(len(self._members))

    def members(self):
        with self._lock:
            return list(self._members)

    @property
    def size(self):
        with self._lock:
            return len(self._members)

    def join(self, rank):
        """Add a rank; returns the roster version after the change."""
        with self._lock:
            if rank not in self._members:
                self._members = sorted(self._members + [rank])
                self.version += 1
            _M_ROSTER.set(len(self._members))
            return self.version

    def drain(self, rank):
        """Remove a rank (it finishes its in-flight batch and stops
        claiming new ones).  The last member can not drain — training
        must keep a worker."""
        with self._lock:
            if rank in self._members and len(self._members) == 1:
                raise MXNetError(
                    "WorkerRoster.drain: cannot drain the last worker "
                    "(rank %d)" % rank)
            if rank in self._members:
                self._members = [m for m in self._members if m != rank]
                self.version += 1
            _M_ROSTER.set(len(self._members))
            return self.version

    def owns(self, rank, batch_index):
        """Does ``rank`` own global batch ``batch_index`` under the
        CURRENT membership?  A drained rank owns nothing."""
        with self._lock:
            if rank not in self._members:
                return False
            pos = self._members.index(rank)
            return batch_index % len(self._members) == pos

    def mark_progress(self, epoch, next_batch):
        """Advance the group's high-water mark (monotonic: interleaved
        ranks can never move the handoff point backward)."""
        with self._lock:
            point = (int(epoch), int(next_batch))
            if point > self._progress:
                self._progress = point

    def resume_point(self):
        """(epoch, next batch index) a joining worker fast-forwards to."""
        with self._lock:
            return self._progress
