"""Profiler (parity: reference ``python/mxnet/profiler.py`` +
``src/engine/profiler.cc``) — now a façade over
:mod:`mxnet_tpu.observability`.

Three lanes, merged under one API:
 - **device**: the jax/XLA profiler (xplane) — ``profiler_set_state('run')``
   starts a trace viewable in TensorBoard/Perfetto.  This is the TPU
   equivalent of the reference's GPU op timing.
 - **host engine**: the native engine profiler (``native/src/profiler.cc``)
   records per-op start/end/thread for host-side engine work — the direct
   equivalent of the reference's ``OprExecStat`` → ``DumpProfile`` path
   (``src/engine/profiler.h:20-141``, hook ``threaded_engine.h:294-308``).
 - **frontend spans**: ``scope()`` and every instrumented runtime seam
   record through :func:`observability.span` into the cross-thread ring
   buffer; ``dump_profile`` merges them with the native dump into ONE
   chrome://tracing JSON (shared CLOCK_MONOTONIC µs timeline).
"""

from __future__ import annotations

import logging
import os
import threading

from . import _native, observability as _obs

__all__ = ["profiler_set_config", "profiler_set_state", "dump_profile",
           "scope"]


class _ProfilerState(object):
    """Lock-guarded profiler session state.  The old module-global dict
    let two threads racing ``profiler_set_state('run')`` both observe
    ``running=False`` and double-start the xplane trace; here the
    check-and-flip happens under one lock."""

    def __init__(self):
        self.lock = threading.Lock()
        self.mode = "symbolic"
        self.dir = "profile_output"
        self.running = False


_STATE = _ProfilerState()


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """(parity: ``profiler.py:profiler_set_config``)"""
    with _STATE.lock:
        _STATE.mode = mode
        _STATE.dir = os.path.splitext(filename)[0]


def profiler_set_state(state="stop"):
    """'run' starts the xplane trace, the native engine recording, and
    frontend span recording; 'stop' ends all three (parity:
    ``profiler.py:profiler_set_state``).  Idempotent and thread-safe:
    concurrent or repeated 'run' calls start ONE session."""
    import jax

    lib = _native.lib()
    with _STATE.lock:
        if state == "run" and not _STATE.running:
            os.makedirs(_STATE.dir, exist_ok=True)
            jax.profiler.start_trace(_STATE.dir)
            if lib is not None:
                lib.mxtpu_profiler_clear()  # fresh session, no stale events
                lib.mxtpu_profiler_set_state(1)
            _obs.clear_spans()
            _obs.enable_tracing()
            _STATE.running = True
        elif state == "stop" and _STATE.running:
            jax.profiler.stop_trace()
            if lib is not None:
                lib.mxtpu_profiler_set_state(0)
            _obs.disable_tracing()
            _STATE.running = False
        else:
            logging.debug("profiler state change to %r ignored", state)


def dump_profile():
    """Stop + flush all traces.  The host-engine chrome trace lands at
    ``<dir>/engine_trace.json`` (parity: ``profiler.py:dump_profile`` /
    ``Profiler::DumpProfile``); the MERGED view — frontend/engine/
    prefetch/kvstore spans plus the native engine ops on one timeline —
    lands at ``<dir>/trace.json``.  Returns the merged path."""
    profiler_set_state("stop")
    with _STATE.lock:
        out_dir = _STATE.dir
    os.makedirs(out_dir, exist_ok=True)
    lib = _native.lib()
    if lib is not None:
        path = os.path.join(out_dir, "engine_trace.json")
        n = lib.mxtpu_profiler_dump(path.encode())
        logging.info("dumped %d engine events to %s", n, path)
    merged = os.path.join(out_dir, "trace.json")
    trace = _obs.export_chrome_trace(merged)
    logging.info("dumped merged trace (%d events) to %s",
                 len(trace["traceEvents"]), merged)
    return merged


class scope(object):
    """Context manager recording a named frontend span (the
    ``mx.profiler``-visible analog of engine op events).  Routed through
    the observability span API — nested scopes parent correctly, engine
    ops pushed inside inherit the scope across threads — and mirrored
    into the native event table for the legacy ``engine_trace.json``."""

    def __init__(self, name, cat="frontend"):
        self.name = name
        self.cat = cat
        self._span = _obs.span(name, cat=cat)

    def __enter__(self):
        import time

        self._t0 = int(time.monotonic() * 1e6)
        self._span.__enter__()
        return self

    def __exit__(self, *exc):
        import time

        self._span.__exit__(*exc)
        if _obs.tracing_enabled():
            return False  # the span IS the record; don't double-emit
        lib = _native.lib()
        if lib is not None and lib.mxtpu_profiler_state():
            # legacy path: native profiler driven directly, span
            # recording off — mirror into the native event table
            lib.mxtpu_profiler_add_event(
                self.name.encode(), self.cat.encode(), self._t0,
                int(time.monotonic() * 1e6),
                threading.get_ident() % 100000)
        return False
