/*!
 * C++ training frontend example: LeNet on MNIST-format idx files
 * (parity: reference ``cpp-package/example/lenet.cpp`` — the full
 * Symbol/Executor/Optimizer/KVStore training surface from C++, not just
 * predict).  Built by ``make -C native cpp_train``; driven by
 * ``tests/test_native.py::test_cpp_frontend_trains_lenet``.
 *
 * Usage: train_lenet <images.idx> <labels.idx> <epochs> <batch> [prefix]
 * With [prefix]: saves a Python-compatible checkpoint
 * (prefix-symbol.json + prefix-0001.params) after training.
 * Prints "CPP_TRAIN acc=<accuracy>"; exit 0 iff acc >= 0.9.
 */
#include <cstdio>
#include <cstdlib>
#include <string>

#include "mxtpu/training.hpp"

using namespace mxtpu::train;

static Symbol LeNet() {
  Symbol data = Symbol::Variable("data");
  Symbol x = Convolution("c1", data, {5, 5}, 8);
  x = Activation("a1", x, "tanh");
  x = Pooling("p1", x, {2, 2}, "max", {2, 2});
  x = Convolution("c2", x, {5, 5}, 16);
  x = Activation("a2", x, "tanh");
  x = Pooling("p2", x, {2, 2}, "max", {2, 2});
  x = Flatten("fl", x);
  x = FullyConnected("f1", x, 64);
  x = Activation("a3", x, "tanh");
  x = FullyConnected("f2", x, 10);
  return SoftmaxOutput("softmax", x);
}

int main(int argc, char **argv) {
  if (argc != 5 && argc != 6) {
    std::fprintf(stderr,
                 "usage: %s images.idx labels.idx epochs batch [prefix]\n",
                 argv[0]);
    return 2;
  }
  const std::string images = argv[1], labels = argv[2];
  const int epochs = std::atoi(argv[3]);
  const int64_t batch = std::atoi(argv[4]);

  try {
    Symbol net = LeNet();
    /* symbol JSON round-trip (save/load parity) */
    Symbol reloaded = Symbol::FromJSON(net.ToJSON());
    if (reloaded.ListArguments() != net.ListArguments())
      throw std::runtime_error("JSON round-trip changed the arguments");

    FeedForward model(net, {{"data", {batch, 1, 28, 28}},
                            {"softmax_label", {batch}}});

    KVStore kv("local");
    char opt[128];
    std::snprintf(opt, sizeof opt,
                  "{\"learning_rate\": 0.1, \"momentum\": 0.9, "
                  "\"rescale_grad\": %.8f}", 1.0 / static_cast<double>(batch));
    kv.SetOptimizer("sgd", opt);

    char iter_kwargs[512];
    std::snprintf(iter_kwargs, sizeof iter_kwargs,
                  "{\"image\": \"%s\", \"label\": \"%s\", "
                  "\"batch_size\": %d, \"shuffle\": true, \"seed\": 11}",
                  images.c_str(), labels.c_str(),
                  static_cast<int>(batch));
    DataIter train("MNISTIter", iter_kwargs);

    model.InitParams(kv, /*seed=*/3);
    double acc = 0.0;
    for (int e = 0; e < epochs; ++e) {
      model.FitEpoch(train, kv);
      acc = model.Score(train);
      std::printf("epoch %d: train-acc=%.4f\n", e, acc);
      std::fflush(stdout);
    }
    if (argc == 6) {
      // Python-compatible checkpoint: the test reloads it with
      // mx.model.load_checkpoint and checks prediction parity
      model.SaveCheckpoint(argv[5], 1);
      std::printf("saved checkpoint %s\n", argv[5]);
    }
    std::printf("CPP_TRAIN acc=%.4f\n", acc);
    return acc >= 0.9 ? 0 : 1;
  } catch (const std::exception &e) {
    std::fprintf(stderr, "FATAL: %s\n", e.what());
    return 1;
  }
}
