"""Operator registry — the NNVM ``Op`` registry rebuilt for XLA.

In the reference, ops live in two C++ registries (``OperatorProperty`` and NNVM
``FCompute``; reference ``include/mxnet/op_attr_types.h:57-62``,
``src/nnvm/legacy_op_util.cc``) and kernels are mshadow/CUDA.  Here there is a
single registry and every op's compute function is a *traceable JAX function*:
the imperative path jits it per (attrs, shapes) and the symbolic executor traces
whole graphs of them into one XLA computation.  That one design change replaces
the dependency engine + mshadow + cuDNN stack: XLA does the scheduling, fusion
and memory planning that the reference does by hand.

An op declares:

* ``arg_names``   — positional tensor inputs (e.g. ``['data','weight','bias']``);
  missing inputs auto-materialize as variables at Symbol compose time, exactly
  like the reference's parameter inputs.
* ``aux_names``   — auxiliary states mutated by training forward (BatchNorm
  moving stats).  The compute fn returns their new values after the outputs.
* ``params``      — attribute spec (name -> ParamSpec), the ``dmlc::Parameter``
  equivalent: typed, defaulted, string-parseable (for JSON graph loading).
* ``fn(attrs, *tensors, is_train=..., rng=...)`` — the compute rule on jax
  arrays.  ``rng`` is a jax PRNG key for stochastic ops (Dropout, samplers).
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, List, Optional, Sequence

import numpy as _np

from ..base import MXNetError

__all__ = ["Op", "ParamSpec", "register", "get_op", "list_ops", "OP_REGISTRY"]

OP_REGISTRY: Dict[str, "Op"] = {}
_ALIAS: Dict[str, str] = {}


def _parse_bool(s):
    if isinstance(s, bool):
        return s
    if isinstance(s, (int, float)):
        return bool(s)
    s = s.strip().lower()
    if s in ("true", "1"):
        return True
    if s in ("false", "0"):
        return False
    raise ValueError("cannot parse bool from %r" % s)


def _parse_shape(s):
    if s is None:
        return None
    if isinstance(s, (tuple, list)):
        return tuple(int(x) for x in s)
    if isinstance(s, (int, _np.integer)):
        return (int(s),)
    s = s.strip()
    if s in ("None", ""):
        return None
    val = ast.literal_eval(s)
    if isinstance(val, (int, float)):
        return (int(val),)
    return tuple(int(x) for x in val)


class ParamSpec:
    """One attribute of an op (the ``DMLC_DECLARE_FIELD`` equivalent)."""

    __slots__ = ("name", "type", "default", "required", "enum")

    def __init__(self, type="str", default=None, required=False, enum=None):
        self.type = type
        self.default = default
        self.required = required
        self.enum = enum

    def parse(self, value):
        if value is None:
            return None
        t = self.type
        if t == "int":
            return int(value)
        if t == "float":
            return float(value)
        if t == "bool":
            return _parse_bool(value)
        if t == "shape":
            return _parse_shape(value)
        if t == "str":
            v = str(value)
            if self.enum is not None and v not in self.enum:
                raise MXNetError("invalid value %r; expected one of %s" % (v, self.enum))
            return v
        if t == "any":
            return value
        raise MXNetError("unknown param type %r" % (t,))


class Op:
    """A registered operator."""

    def __init__(
        self,
        name: str,
        fn: Callable,
        arg_names: Sequence[str] = ("data",),
        aux_names: Sequence[str] = (),
        num_outputs=1,
        params: Optional[Dict[str, ParamSpec]] = None,
        needs_mode: bool = False,
        needs_rng: bool = False,
        variable_args: bool = False,
        output_names: Optional[Sequence[str]] = None,
        input_names_fn: Optional[Callable] = None,
        collect_extra: bool = False,
        mesh_aware: bool = False,
    ):
        self.name = name
        self.fn = fn
        self.arg_names = list(arg_names)
        self.aux_names = list(aux_names)
        self.num_outputs = num_outputs  # int or callable(attrs) -> int
        self.params = params or {}
        self.needs_mode = needs_mode
        self.needs_rng = needs_rng
        # variable_args: op takes N homogeneous inputs (Concat, add_n, ...)
        # controlled by attr 'num_args'
        self.variable_args = variable_args
        self.output_names = list(output_names) if output_names else None
        self.input_names_fn = input_names_fn
        self.collect_extra = collect_extra
        # mesh_aware: the compute rule consults the ambient default mesh at
        # trace time, so jit caches must key on the mesh identity too
        self.mesh_aware = mesh_aware

    # -- attrs ---------------------------------------------------------
    def parse_attrs(self, kwargs: Dict) -> Dict:
        """Validate/parse keyword attributes into a canonical attrs dict."""
        attrs = {}
        for k, v in kwargs.items():
            if k in self.params:
                attrs[k] = self.params[k].parse(v)
            elif k == "num_args" and self.variable_args:
                attrs["num_args"] = int(v)
            elif self.collect_extra:
                attrs.setdefault("_kwargs", {})[k] = v
            else:
                raise MXNetError(
                    "%s got unknown attribute %r (known: %s)"
                    % (self.name, k, sorted(self.params))
                )
        for k, spec in self.params.items():
            if k not in attrs:
                if spec.required:
                    raise MXNetError("%s missing required attribute %r" % (self.name, k))
                attrs[k] = spec.default
        return attrs

    def attrs_key(self, attrs: Dict):
        """Hashable canonical form of attrs (jit-cache key component)."""
        return tuple(sorted((k, _hashable(v)) for k, v in attrs.items()))

    def n_outputs(self, attrs) -> int:
        if callable(self.num_outputs):
            return self.num_outputs(attrs)
        return self.num_outputs

    def input_names(self, attrs) -> List[str]:
        if self.variable_args:
            n = int(attrs.get("num_args") or 0)
            return ["arg%d" % i for i in range(n)]
        if self.input_names_fn is not None:
            return list(self.input_names_fn(attrs))
        return self.arg_names

    # -- compute -------------------------------------------------------
    def apply(self, attrs, args, auxs=(), is_train=False, rng=None):
        """Run the compute rule.  Returns (outputs_list, new_aux_list)."""
        kw = {}
        if self.needs_mode:
            kw["is_train"] = is_train
        if self.needs_rng:
            kw["rng"] = rng
        out = self.fn(attrs, *list(args) + list(auxs), **kw)
        n_out = self.n_outputs(attrs)
        if not isinstance(out, tuple):
            out = (out,)
        outputs = list(out[:n_out])
        new_aux = list(out[n_out:])
        if len(outputs) != n_out or len(new_aux) != len(self.aux_names):
            raise MXNetError(
                "%s returned %d arrays; expected %d outputs + %d aux"
                % (self.name, len(out), n_out, len(self.aux_names))
            )
        return outputs, new_aux

    def __repr__(self):
        return "Op(%s)" % self.name


def _hashable(v):
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


def register(name, aliases=(), **kwargs):
    """Decorator: register ``fn`` as op ``name`` (+ aliases)."""

    def deco(fn):
        op = Op(name, fn, **kwargs)
        OP_REGISTRY[name] = op
        for a in aliases:
            _ALIAS[a] = name
        return fn

    return deco


def register_op(op: Op, aliases=()):
    OP_REGISTRY[op.name] = op
    for a in aliases:
        _ALIAS[a] = op.name
    return op


def get_op(name: str) -> Op:
    if name in OP_REGISTRY:
        return OP_REGISTRY[name]
    if name in _ALIAS:
        return OP_REGISTRY[_ALIAS[name]]
    raise MXNetError("operator %r is not registered" % name)


def list_ops() -> List[str]:
    return sorted(set(OP_REGISTRY) | set(_ALIAS))
