"""Deployment daemon: gated checkpoint hot-swap with automatic rollback.

The continuous-training loop's consumer half.  ``fit_stream`` (the
producer) drops sharded checkpoints into a directory every N steps;
:class:`DeployDaemon` watches that directory and walks each new step
through a promotion pipeline:

1. **Restore-validate** — the caller's ``loader(checkpoint_dir, step)``
   builds a serving backend from the checkpoint; any exception
   (corrupt shard, layout mismatch) rejects the candidate, it never
   touches traffic.
2. **Eval floor** — ``eval_fn(backend)`` must return a finite score,
   and at least ``eval_floor`` (default
   ``MXNET_TPU_DEPLOYD_EVAL_FLOOR``) when a floor is set.
3. **Golden-metrics diff** — the candidate runs a pinned golden batch;
   non-finite outputs always reject, and when ``golden_max_drift`` is
   set its outputs must stay within that max-abs-diff of the currently
   serving model's on the same batch (a guard against a checkpoint
   that loads fine but answers garbage).

A candidate that clears the gate is promoted with
:meth:`~mxnet_tpu.serving.registry.ModelRegistry.swap` on every live
replica — each swap lands between dispatch windows under the entry's
``dispatch_lock``, and the replica group's router keeps answering from
peers mid-swap, so accepted requests are never dropped (brownout, not
blackout).  The displaced backends are **pinned**.

Promotion opens a **probation window** (``probation_s``, default
``MXNET_TPU_DEPLOYD_PROBATION_S``): a fresh :class:`~mxnet_tpu.
observability.watchdog.Watchdog` over the error-budget burn-rate rules
(:func:`~mxnet_tpu.observability.slo.burn_rules`) — or the caller's
``rules`` factory — is evaluated on every poll.  A **terminal** alert
inside the window triggers exactly ONE rollback: every replica swaps
back to its pinned previous backend, the decision is emitted as a
``deploy.rollback`` ops event naming the rule, and a flight-recorder
bundle (``deployd.rollback``) captures the postmortem.  No new
candidate is considered while probation is open — one change in
flight at a time.

Every decision (``deploy.promote`` / ``deploy.reject`` /
``deploy.rollback``) is an ops event and a metrics increment, so "the
daemon rolled back exactly once, for this rule" is a testable
statement.  ``poll_once(now=)`` takes an injectable clock for
deterministic tests; :meth:`start` runs the same poll on a daemon
thread every ``MXNET_TPU_DEPLOYD_POLL_S`` seconds for real deploys.
"""

from __future__ import annotations

import logging
import math
import os
import threading
import time as _time

import numpy as _np

from .base import CheckpointCorruptError, MXNetError
from .observability import flight_recorder as _flight
from .observability import metrics as _metrics
from .observability import watchdog as _watchdog
from .observability.events import emit as _emit_event
from .parallel import checkpoint as _ckpt

__all__ = ["DeployDaemon"]

_M_PROMOTE = _metrics.counter(
    "deployd_promotions_total",
    "Checkpoint candidates that cleared the validation gate and were "
    "hot-swapped onto the serving replicas")
_M_REJECT = _metrics.counter(
    "deployd_rejections_total",
    "Checkpoint candidates rejected by the validation gate, by stage",
    ["reason"])
_M_ROLLBACK = _metrics.counter(
    "deployd_rollbacks_total",
    "Automatic rollbacks: a terminal watchdog alert fired inside the "
    "post-promotion probation window")
_M_LIVE = _metrics.gauge(
    "deployd_live_step",
    "Checkpoint step currently serving traffic (0 = the pre-daemon "
    "baseline backend)")


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _default_rules():
    from .observability import slo as _slo

    return _slo.burn_rules()


def _finite(arrays):
    for a in arrays:
        if not _np.all(_np.isfinite(_np.asarray(a, dtype=_np.float64))):
            return False
    return True


class DeployDaemon(object):
    """Watch ``checkpoint_dir`` and gate-promote new steps onto ``group``.

    Parameters
    ----------
    checkpoint_dir : str
        Directory ``fit_stream``/``fit`` saves sharded checkpoints into.
    group : ReplicaGroup | Scheduler | ModelRegistry
        Where promotions land.  A :class:`~mxnet_tpu.serving.replication.
        ReplicaGroup` swaps every live replica; a single scheduler or
        bare registry swaps just itself.
    model : str
        The registered model name being continuously redeployed.
    loader : callable(checkpoint_dir, step) -> Backend
        Restore-validate: build a serving backend from the checkpoint.
        Called once per replica on promotion (replicas never share
        executors); any exception rejects the candidate.
    eval_fn : callable(backend) -> float, optional
        Offline eval score for the gate; non-finite always rejects.
    eval_floor : float, optional
        Minimum accepted ``eval_fn`` score (default
        ``MXNET_TPU_DEPLOYD_EVAL_FLOOR``; unset = finite-only check).
    golden_batch : dict name -> array, optional
        A pinned batch for the golden-metrics diff (already padded to a
        served bucket shape).
    golden_max_drift : float, optional
        Max abs output drift vs the CURRENT model on the golden batch.
    probation_s : float
        Post-promotion watch window (default
        ``MXNET_TPU_DEPLOYD_PROBATION_S``).
    rules : callable() -> [Rule], optional
        Factory for the probation watchdog's rules — called fresh per
        promotion, because rules are stateful.  Default:
        :func:`~mxnet_tpu.observability.slo.burn_rules` (the fast-burn
        rules are terminal and trigger rollback).
    watchdog_source : optional
        Metrics source for the probation watchdog (default: the
        process-global registry).
    """

    def __init__(self, checkpoint_dir, group, model, loader,
                 eval_fn=None, eval_floor=None, golden_batch=None,
                 golden_max_drift=None, probation_s=None, rules=None,
                 watchdog_source=None, logger=None):
        self.checkpoint_dir = checkpoint_dir
        self.model = model
        self._group = group
        self._loader = loader
        self._eval_fn = eval_fn
        if eval_floor is None:
            raw = os.environ.get("MXNET_TPU_DEPLOYD_EVAL_FLOOR", "")
            eval_floor = float(raw) if raw else None
        self._eval_floor = eval_floor
        self._golden = golden_batch
        self._golden_max_drift = golden_max_drift
        self._probation_s = (
            _env_float("MXNET_TPU_DEPLOYD_PROBATION_S", 60.0)
            if probation_s is None else float(probation_s))
        self._rules = rules if rules is not None else _default_rules
        self._watch_source = watchdog_source
        self._log = logger or logging.getLogger(__name__)
        self._lock = threading.Lock()
        self._last_scanned = -1   # newest step already decided on
        self._live_step = None    # step serving traffic (None = baseline)
        self._pinned = None       # {"step", "prev_step", "olds": [(t, b)]}
        self._probation_until = None
        self._dog = None
        self.history = []         # decision dicts, oldest first
        self._stop = threading.Event()
        self._thread = None

    # -- targets --------------------------------------------------------

    def _targets(self):
        """The swap targets: every live replica of a group, or the bare
        scheduler/registry itself."""
        if hasattr(self._group, "live"):
            return [s for _, s in self._group.live()]
        return [self._group]

    def _current_backend(self):
        targets = self._targets()
        if not targets:
            return None
        t = targets[0]
        registry = getattr(t, "registry", t)
        return registry.get(self.model).backend

    # -- the gate -------------------------------------------------------

    def _reject(self, step, reason, detail):
        _M_REJECT.labels(reason).inc()
        _emit_event("deploy.reject", model=self.model, step=int(step),
                    reason=reason, detail=str(detail)[:500])
        decision = {"action": "reject", "step": step, "reason": reason,
                    "detail": str(detail)}
        self.history.append(decision)
        self._log.warning("deployd: rejected step %d at gate %r: %s",
                          step, reason, detail)
        return decision

    def _gate(self, step):
        """Run the candidate through the gate; returns the validated
        backend or None (rejection already recorded)."""
        try:
            # integrity first: a checkpoint whose manifest checksums no
            # longer match its bytes must never reach a build attempt —
            # a corrupt weight file can load "successfully" into wrong
            # numbers that only the golden gate might catch (and serving
            # configs without one would promote silently)
            _ckpt.verify_checkpoint(self.checkpoint_dir, step)
        except CheckpointCorruptError as exc:
            self._reject(step, "checksum", exc)
            return None
        except OSError:
            pass  # absence is the loader's failure to classify, not ours
        try:
            backend = self._loader(self.checkpoint_dir, step)
        except Exception as exc:  # noqa: BLE001 — any load failure rejects
            self._reject(step, "restore", exc)
            return None
        if self._eval_fn is not None:
            try:
                score = float(self._eval_fn(backend))
            except Exception as exc:  # noqa: BLE001
                self._reject(step, "eval", exc)
                return None
            if not math.isfinite(score):
                self._reject(step, "eval", "non-finite score %r" % score)
                return None
            if self._eval_floor is not None and score < self._eval_floor:
                self._reject(step, "eval_floor",
                             "score %.6g < floor %.6g"
                             % (score, self._eval_floor))
                return None
        if self._golden is not None:
            try:
                outs, _cold = backend.infer(dict(self._golden))
            except Exception as exc:  # noqa: BLE001
                self._reject(step, "golden", exc)
                return None
            if not _finite(outs):
                self._reject(step, "golden",
                             "non-finite outputs on the golden batch")
                return None
            if self._golden_max_drift is not None:
                current = self._current_backend()
                if current is not None:
                    ref, _ = current.infer(dict(self._golden))
                    drift = max(
                        float(_np.max(_np.abs(_np.asarray(a, _np.float64)
                                              - _np.asarray(b, _np.float64))))
                        for a, b in zip(outs, ref))
                    if drift > self._golden_max_drift:
                        self._reject(
                            step, "golden_drift",
                            "max output drift %.6g > bound %.6g"
                            % (drift, self._golden_max_drift))
                        return None
        return backend

    # -- promote / rollback --------------------------------------------

    def _promote_locked(self, step, backend, now):
        targets = self._targets()
        if not targets:
            raise MXNetError("deployd: no live replicas to promote onto")
        backends = [backend]
        for _ in targets[1:]:
            # each replica gets its own backend (executors not shared);
            # a load that succeeded once and fails now still rejects
            try:
                backends.append(self._loader(self.checkpoint_dir, step))
            except Exception as exc:  # noqa: BLE001
                self._reject(step, "restore", exc)
                return None
        olds = []
        for t, b in zip(targets, backends):
            olds.append((t, t.swap(self.model, b)))
        prev = self._live_step
        self._pinned = {"step": step, "prev_step": prev, "olds": olds}
        self._live_step = step
        self._probation_until = now + self._probation_s
        # fresh rules per probation: rule state (burn windows, sustain
        # timers) must start at the promotion edge, not carry history
        self._dog = _watchdog.Watchdog(rules=self._rules(),
                                       source=self._watch_source)
        self._dog.evaluate(now=now)  # baseline sample for the windows
        _M_PROMOTE.inc()
        _M_LIVE.set(step)
        _emit_event("deploy.promote", model=self.model, step=int(step),
                    replicas=len(olds), prev_step=prev,
                    probation_s=self._probation_s)
        decision = {"action": "promote", "step": step, "prev_step": prev,
                    "replicas": len(olds)}
        self.history.append(decision)
        self._log.info("deployd: promoted step %d onto %d replica(s); "
                       "probation %.1fs", step, len(olds),
                       self._probation_s)
        return decision

    def _rollback_locked(self, rule_name, alert, now):
        pinned, self._pinned = self._pinned, None
        self._probation_until = None
        self._dog = None
        for t, old in pinned["olds"]:
            try:
                t.swap(self.model, old)
            except Exception:  # noqa: BLE001 — a fenced replica mid-swap
                self._log.exception(
                    "deployd: rollback swap failed on one replica "
                    "(fenced mid-probation?) — continuing")
        self._live_step = pinned["prev_step"]
        _M_ROLLBACK.inc()
        _M_LIVE.set(pinned["prev_step"] or 0)
        _emit_event("deploy.rollback", model=self.model,
                    step=int(pinned["step"]),
                    restored_step=pinned["prev_step"], rule=rule_name)
        _flight.record_failure(
            "deployd.rollback", None, rule=rule_name,
            step=int(pinned["step"]),
            restored_step=pinned["prev_step"],
            alert=alert.as_dict() if alert is not None else None)
        decision = {"action": "rollback", "step": pinned["step"],
                    "restored_step": pinned["prev_step"],
                    "rule": rule_name}
        self.history.append(decision)
        self._log.error(
            "deployd: rolled back step %r -> %r (watchdog rule %r fired "
            "in probation)", pinned["step"], pinned["prev_step"],
            rule_name)
        return decision

    # -- the poll -------------------------------------------------------

    def poll_once(self, now=None):
        """One state-machine turn; returns the decision made (a dict
        with ``action`` of ``promote``/``reject``/``rollback``/
        ``probation_pass``) or None when nothing changed.  ``now``
        (monotonic seconds) is injectable so tests drive the probation
        and burn-rate windows deterministically."""
        if now is None:
            now = _time.monotonic()
        with self._lock:
            if self._probation_until is not None:
                alerts = self._dog.evaluate(now=now)
                terminal = [a for a in alerts if a.severity == "terminal"]
                if terminal:
                    return self._rollback_locked(terminal[0].name,
                                                 terminal[0], now)
                if now >= self._probation_until:
                    step = self._pinned["step"]
                    self._probation_until = None
                    self._dog = None
                    decision = {"action": "probation_pass", "step": step}
                    self.history.append(decision)
                    self._log.info(
                        "deployd: step %d survived probation", step)
                    return decision
                return None
            steps = [s for s in _ckpt.all_steps(self.checkpoint_dir)
                     if s > self._last_scanned]
            if not steps:
                return None
            # newest candidate wins; the ones it lapped are superseded,
            # not gated — a backlog never triggers N swaps
            step = steps[-1]
            self._last_scanned = step
            for lapped in steps[:-1]:
                self.history.append({"action": "superseded",
                                     "step": lapped, "by": step})
            backend = self._gate(step)
            if backend is None:
                return self.history[-1]
            return self._promote_locked(step, backend, now)

    # -- background loop ------------------------------------------------

    def start(self, poll_s=None):
        """Poll every ``poll_s`` (default ``MXNET_TPU_DEPLOYD_POLL_S``)
        on a daemon thread."""
        interval = (_env_float("MXNET_TPU_DEPLOYD_POLL_S", 5.0)
                    if poll_s is None else float(poll_s))

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.poll_once()
                except Exception:  # noqa: BLE001
                    # the daemon must outlive a bad poll; the decision
                    # trail and flight bundles carry the evidence
                    self._log.exception("deployd: poll failed")

        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=loop, name="mxtpu-deployd", daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5)

    def describe(self):
        """Current state for ops endpoints/logs."""
        with self._lock:
            return {"model": self.model, "live_step": self._live_step,
                    "probation_open": self._probation_until is not None,
                    "last_scanned": self._last_scanned,
                    "decisions": len(self.history)}
