"""Wire-level bandwidth observability (PR 15): per-RPC byte accounting
with the header/payload split, the encode/decode cost ledger, and the
binary-wire savings report.

The plane is FALSIFIABLE by construction: `_sendall`/`_recv_exact`
count the actual socket bytes into ``kv_socket_bytes_total``, and every
test that drives traffic closes with ``wire_reconciles()`` — the per-op
books must sum to the socket truth.  The acceptance drill is the
2-shard replicated fit: books vs socket within 1%, replicate frames on
the ledger, codec wall covered by the attribution ``kv`` phase, and the
``wire_bytes_regression`` watchdog firing exactly once on a synthetic
2x byte inflation with the rule named in the flight bundle.
"""

import io
import json
import os
import socket
import struct
import time
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import kvstore_async as ka
from mxnet_tpu import observability as obs
from mxnet_tpu.base import CorruptMessageError
from mxnet_tpu.kvstore_async import AsyncClient, AsyncServer
from mxnet_tpu.observability import metrics as omet
from mxnet_tpu.observability import tracing
from mxnet_tpu.observability import wire as owire


@pytest.fixture(autouse=True)
def _fast_and_isolated(monkeypatch):
    """Sub-second retry envelope + clean membership per test (mirrors
    test_kvstore_replication.py)."""
    monkeypatch.setattr(AsyncClient, "_BACKOFF_CAP_S", 0.1)
    monkeypatch.setenv("MXNET_TPU_PS_CALL_TIMEOUT", "2")
    monkeypatch.setenv("MXNET_TPU_PS_DEADLINE", "3")
    monkeypatch.setenv("MXNET_TPU_PS_DEAD_AFTER", "2")
    monkeypatch.setenv("MXNET_TPU_KV_REPL_SYNC", "1")
    ka.reset_membership()
    yield
    ka.reset_membership()


def _wire_children():
    fam = obs.REGISTRY.get("kv_wire_bytes_total")
    with fam._lock:
        return {k: c.value for k, c in fam._children.items()}


def _sgd_pickle(lr=0.1):
    import pickle

    from mxnet_tpu import optimizer as opt

    return pickle.dumps(opt.SGD(learning_rate=lr, wd=0.0))


# ---------------------------------------------------------------------------
# backward compatibility: instrumentation never changes the frame
# ---------------------------------------------------------------------------

def test_encoded_frame_identical_with_books_on_and_off(monkeypatch):
    """The byte accounting observes frames, it does not shape them: the
    encoded payload is byte-identical whether the metrics plane is on or
    off, so old and new peers interoperate unchanged."""
    msg = {"op": "push", "rank": 1, "seq": 9,
           "pairs": [("w", np.arange(6, dtype=np.float32))]}
    with_books = ka._encode_msg(dict(msg))
    monkeypatch.setenv("MXNET_TPU_METRICS", "0")
    without = ka._encode_msg(dict(msg))
    assert with_books == without
    out = ka._decode_msg(with_books)
    assert out["op"] == "push" and out["seq"] == 9
    np.testing.assert_array_equal(out["pairs"][0][1], msg["pairs"][0][1])


# ---------------------------------------------------------------------------
# corrupt paths: the consumed prefix is booked exactly once
# ---------------------------------------------------------------------------

def test_corrupt_frame_books_consumed_prefix_exactly_once():
    """A frame that fails to decode WAS consumed off the socket; it is
    booked once under op='corrupt' at the raise site, and the retry
    (the next frame on the wire) opens its own books — no double
    count, and the totals still reconcile with the socket truth."""
    a, b = socket.socketpair()
    try:
        bad = b"\xff" * 32                 # hdr_len garbage: decode raises
        b.sendall(struct.pack("<Q", len(bad)) + bad)
        with pytest.raises(Exception):
            ka._recv_msg(a)
        books = _wire_children()
        assert books[("corrupt", "recv", "header")] == 8.0
        assert books[("corrupt", "recv", "payload")] == 32.0

        # retry: a good frame on the SAME socket books under its own op
        good = ka._encode_msg({"op": "stats"})
        b.sendall(struct.pack("<Q", len(good)) + good)
        assert ka._recv_msg(a)["op"] == "stats"
        books = _wire_children()
        assert books[("corrupt", "recv", "header")] == 8.0   # unchanged
        assert books[("corrupt", "recv", "payload")] == 32.0
        ok, wire_b, sock_b = owire.wire_reconciles()
        assert ok, "books %d vs socket %d" % (wire_b, sock_b)
        assert wire_b == sock_b == (8 + 32) + (8 + len(good))
    finally:
        a.close()
        b.close()


def test_oversize_frame_books_only_the_eight_byte_prefix(monkeypatch):
    """An oversize length prefix tears the connection down before the
    body is read: exactly the 8 consumed bytes land under 'corrupt',
    with no payload part."""
    a, b = socket.socketpair()
    try:
        b.sendall(struct.pack("<Q", 1 << 40))
        with pytest.raises(CorruptMessageError):
            ka._recv_msg(a)
        books = _wire_children()
        assert books[("corrupt", "recv", "header")] == 8.0
        assert books.get(("corrupt", "recv", "payload"), 0.0) == 0.0
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# the books vs the socket truth
# ---------------------------------------------------------------------------

def test_roundtrip_books_reconcile_exactly_with_socket_truth():
    """Client and server share this process's registry, so the per-op
    byte books must equal the socket-level ground truth EXACTLY — both
    directions of every frame (request, response, heartbeat-free)."""
    s = AsyncServer(secret="t").start()
    try:
        cli = AsyncClient(s.address, rank=0, heartbeat=False, secret="t")
        cli.init([("w", np.zeros(8, np.float32))])
        cli._call({"op": "pull", "keys": ["w"]})
        cli._call({"op": "stats"})
        cli.close()
    finally:
        s.stop()
    ok, wire_b, sock_b = owire.wire_reconciles()
    assert ok and wire_b == sock_b > 0
    books = _wire_children()
    # request frames booked under their op on BOTH sides of the wire
    assert books[("init", "send", "header")] > 0
    assert books[("init", "recv", "header")] > 0
    assert books[("pull", "send", "payload")] >= 0
    # per-frame size histogram rides the same seams
    ffam = obs.REGISTRY.get("kv_wire_frame_bytes")
    with ffam._lock:
        frames = sum(c.count for c in ffam._children.values())
    assert frames > 0


def test_fit_2shard_replicated_books_reconcile(monkeypatch):
    """ACCEPTANCE: on a 2-shard replicated fit, summed
    ``kv_wire_bytes_total`` matches the socket-level bytes within 1%,
    replication frames ride the ledger under dir='replicate', the
    codec wall reconciles against the attribution ``kv`` phase, and
    the report carries nonzero bytes/step, header overhead and RPC
    fan-out."""
    import jax
    from jax.sharding import Mesh

    from mxnet_tpu.io import NDArrayIter
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    secret = "wire-t"
    monkeypatch.setenv("MXNET_TPU_PS_SECRET", secret)
    servers, addrs = [], []
    for shard in range(2):
        pri = ka.AsyncServer(server_id=shard * 2, secret=secret).start()
        fol = ka.AsyncServer(server_id=shard * 2 + 1,
                             secret=secret).start()
        fol.rejoin(pri.address)
        servers += [pri, fol]
        addrs.append("%s|%s" % (pri.address, fol.address))
    monkeypatch.setenv("MXNET_TPU_ASYNC_PS_ADDRS", ",".join(addrs))
    ka.reset_membership()
    try:
        B, D = 8, 6
        net = mx.sym.FullyConnected(mx.sym.Variable("data"),
                                    num_hidden=16, name="fc1")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(net, num_hidden=8, name="fc2"),
            name="softmax")
        kv = mx.kv.create("dist_async")
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1,
                                          rescale_grad=1.0 / B, wd=0.0))
        rs = np.random.RandomState(3)
        it = NDArrayIter({"data": rs.randn(32, D).astype(np.float32)},
                         {"softmax_label":
                          rs.randint(0, 8, (32,)).astype(np.float32)},
                         batch_size=B)
        mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
        tr = ShardedTrainer(net, mesh, data_shapes={"data": (B, D)},
                            label_shapes={"softmax_label": (B,)},
                            rescale_grad=1.0 / B)
        tr.fit(it, num_epoch=2, seed=5, log_every=0, kvstore=kv)
    finally:
        for s in servers:
            s.stop()

    ok, wire_b, sock_b = owire.wire_reconciles(tol=0.01)
    assert ok, "books %d vs socket %d diverge past 1%%" % (wire_b, sock_b)
    # sync replication: every push re-sent to the follower, on the books
    books = _wire_children()
    repl = [k for k in books if k[1] == "replicate"]
    assert repl, "no replicate frames on the ledger: %s" % sorted(books)
    assert sum(books[k] for k in repl) > 0
    cok, codec_kv, kv_phase = owire.codec_reconciles()
    assert cok, ("foreground codec %.4fs exceeds the attribution kv "
                 "phase %.4fs" % (codec_kv, kv_phase))
    rep = owire.wire_report()
    assert rep["steps"] > 0 and rep["bytes_per_step"] > 0
    assert 0.0 < rep["header_overhead_pct"] < 100.0
    assert rep["codec_seconds"] > 0
    assert rep["rpcs_per_flush_p50"] >= 1.0
    text = owire.format_wire_report()
    assert "PROJECTED binary-wire savings" in text


def test_wire_reconciles_rejects_an_empty_ledger():
    """No traffic must not pass the gate: an empty ledger reconciling
    '0 == 0' would make the falsifiability check vacuous."""
    ok, wire_b, sock_b = owire.wire_reconciles()
    assert not ok and wire_b == sock_b == 0


# ---------------------------------------------------------------------------
# spans, fan-out, serving
# ---------------------------------------------------------------------------

def test_rpc_span_carries_byte_and_codec_attrs():
    """With tracing on, every kv.rpc span reports the frame bytes that
    crossed the wire for that RPC plus the encode/decode wall — a slow
    span shows whether the wire or the codec ate it."""
    s = AsyncServer(secret="t").start()
    try:
        cli = AsyncClient(s.address, rank=0, heartbeat=False, secret="t")
        obs.enable_tracing()
        cli.init([("w", np.arange(16, dtype=np.float32))])
        cli._call({"op": "pull", "keys": ["w"]})
        cli.close()
    finally:
        s.stop()
        obs.disable_tracing()
    rpcs = [sp for sp in tracing.spans() if sp.name == "kv.rpc"]
    assert rpcs
    for sp in rpcs:
        # request + response frames, each 8-byte prefixed
        assert sp.attrs["bytes"] > 16
        assert sp.attrs["encode_us"] >= 0.0
        assert sp.attrs["decode_us"] >= 0.0
    pull = [sp for sp in rpcs if sp.attrs["op"] == "pull"][-1]
    # the pulled tensor dominates the frame: 16 f32 = 64B of payload
    assert pull.attrs["bytes"] >= 64


def test_rpcs_per_flush_histogram_observes_fanout():
    """A striped push/pull through a 2-shard ServerGroup fans out to
    both shards; kv_wire_rpcs_per_flush records exactly that width."""
    servers = [AsyncServer(server_id=i, secret="t").start()
               for i in range(2)]
    try:
        group = ka.ServerGroup([s.address for s in servers], rank=0,
                               heartbeat=False, secret="t")
        group._bound = 1 << 10        # stripe the big key across shards
        big = np.ones(1 << 11, np.float32)
        group.init([("big", big)])
        group.set_optimizer(_sgd_pickle())
        group.push([("big", big)])
        group.pull(["big"])
        group.shutdown()
    finally:
        for s in servers:
            s.stop()
    rfam = obs.REGISTRY.get("kv_wire_rpcs_per_flush")
    assert rfam.count >= 2            # at least the push and the pull
    assert rfam.percentile(0.5) == pytest.approx(2.0, abs=1.0)
    ok, wire_b, sock_b = owire.wire_reconciles()
    assert ok and wire_b == sock_b


class _StubTarget(object):
    """Minimal Scheduler stand-in for the frontend: request() echoes the
    row doubled (the raw path only needs the shared signature)."""

    def request(self, model, inputs, deadline_ms=None, timeout=None,
                tenant=None):
        ((_, row),) = inputs.items()
        return [np.asarray(row) * 2.0]


def test_serving_raw_path_books_wire_bytes():
    """The raw-npy serving path is the frontend's analogue of the kv
    wire: request bodies land under dir='recv', response bodies under
    dir='send', byte-exact."""
    from mxnet_tpu import serving

    row = np.arange(5, dtype=np.float32)
    buf = io.BytesIO()
    np.save(buf, row)
    body = buf.getvalue()
    with serving.start_frontend(_StubTarget()) as fe:
        req = urllib.request.Request(
            fe.url + "/v1/predict?model=m&input=data", data=body,
            headers={"Content-Type": "application/octet-stream"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            out_bytes = resp.read()
        np.testing.assert_allclose(
            np.load(io.BytesIO(out_bytes), allow_pickle=False), row * 2.0)
    fam = obs.REGISTRY.get("serving_wire_bytes_total")
    assert fam.labels("recv").value == float(len(body))
    assert fam.labels("send").value == float(len(out_bytes))


# ---------------------------------------------------------------------------
# watchdog: the regression + codec-share rules
# ---------------------------------------------------------------------------

def _wire_rule(name):
    rules = [r for r in obs.default_rules() if r.name == name]
    assert rules, "default_rules() lost the %s rule" % name
    return rules


def test_wire_bytes_regression_fires_exactly_once(monkeypatch, tmp_path):
    """ACCEPTANCE: a synthetic >=2x bytes/step inflation trips
    wire_bytes_regression exactly once (one rising edge, one terminal
    flight bundle naming the rule), evaluated over exposition text like
    any other source."""
    monkeypatch.setenv("MXNET_TPU_FLIGHT_DIR", str(tmp_path))
    state = {"bytes": 1000.0}         # 10 steps -> 100 B/step baseline

    def exposition():
        return (
            "# HELP kv_wire_bytes_total b\n"
            "# TYPE kv_wire_bytes_total counter\n"
            'kv_wire_bytes_total{op="push",dir="send",part="payload"} %r\n'
            "# HELP trainer_step_seconds s\n"
            "# TYPE trainer_step_seconds histogram\n"
            "trainer_step_seconds_sum 0.5\n"
            "trainer_step_seconds_count 10\n" % state["bytes"])

    wd = obs.Watchdog(_wire_rule("wire_bytes_regression"),
                      source=exposition)
    for now in (0.0, 10.0, 20.0, 30.0):   # steady 100 B/step: quiet
        assert wd.evaluate(now=now) == []
    state["bytes"] = 2500.0               # 250 B/step: 2.5x the baseline
    (alert,) = wd.evaluate(now=40.0)
    assert alert.name == "wire_bytes_regression"
    assert alert.severity == "terminal"
    assert alert.value == pytest.approx(250.0)
    state["bytes"] = 4000.0               # stays inflated: no second edge
    assert len(wd.evaluate(now=50.0)) == 1
    assert obs.REGISTRY.get("cluster_alerts_fired_total").labels(
        "wire_bytes_regression").value == 1
    bundles = [d for d in os.listdir(str(tmp_path))
               if d.startswith("flight_watchdog.wire_bytes_regression")]
    assert len(bundles) == 1, "expected exactly one postmortem bundle"
    with open(os.path.join(str(tmp_path), bundles[0],
                           "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["kind"] == "watchdog.wire_bytes_regression"
    assert "wire_bytes_regression" in manifest["extra"]["alert"]


def test_wire_codec_share_rule_fires_and_resolves():
    """wire_codec_share: codec wall above the allowed share of step
    wall fires a warning; a healthy share resolves it."""
    state = {"wall": 1.0}

    def exposition():
        return (
            "# HELP kv_wire_codec_seconds s\n"
            "# TYPE kv_wire_codec_seconds histogram\n"
            "kv_wire_codec_seconds_sum 0.5\n"
            "kv_wire_codec_seconds_count 100\n"
            "# HELP trainer_step_seconds s\n"
            "# TYPE trainer_step_seconds histogram\n"
            "trainer_step_seconds_sum %r\n"
            "trainer_step_seconds_count 10\n" % state["wall"])

    wd = obs.Watchdog(_wire_rule("wire_codec_share"), source=exposition)
    (alert,) = wd.evaluate(now=0.0)       # 0.5/1.0 = 50% > 25%
    assert alert.name == "wire_codec_share"
    assert alert.severity == "warning"
    assert alert.value == pytest.approx(0.5)
    state["wall"] = 100.0                 # 0.5% of step wall: healthy
    assert wd.evaluate(now=1.0) == []


def test_wire_rules_stay_quiet_on_server_only_books(monkeypatch):
    """A server process has byte books but no trainer steps: both wire
    rules must see None (neither firing nor seeding the baseline)."""
    text = ("# TYPE kv_wire_bytes_total counter\n"
            'kv_wire_bytes_total{op="push",dir="recv",part="payload"} 4096\n')
    wd = obs.Watchdog(_wire_rule("wire_bytes_regression")
                      + _wire_rule("wire_codec_share"), source=text)
    for now in (0.0, 1.0, 2.0, 3.0, 4.0):
        assert wd.evaluate(now=now) == []


# ---------------------------------------------------------------------------
# federation
# ---------------------------------------------------------------------------

def test_federation_exports_cluster_wire_series():
    """The federated view re-exports every member's byte books as
    cluster_kv_wire_bytes{member,dir} and derives the cluster-wide
    wire rate from consecutive passes (0 on the first)."""
    fam = obs.REGISTRY.get("kv_wire_bytes_total")
    fam.labels("push", "send", "header").inc(120.0)
    fam.labels("push", "send", "payload").inc(4096.0)
    fam.labels("push", "replicate", "payload").inc(4096.0)
    fed = obs.FederatedCollector([
        {"shard": 0, "role": "primary", "epoch": 0,
         "registry": obs.REGISTRY},
    ])
    text = fed.render()
    assert ('cluster_kv_wire_bytes{member="0:primary:0",dir="send"} '
            "4216") in text
    assert ('cluster_kv_wire_bytes{member="0:primary:0",'
            'dir="replicate"} 4096') in text
    assert "cluster_wire_mb_per_sec 0\n" in text     # first pass: no rate
    fam.labels("push", "send", "payload").inc(1 << 20)
    time.sleep(0.01)
    text2 = fed.render()
    rate = [l for l in text2.splitlines()
            if l.startswith("cluster_wire_mb_per_sec")]
    assert rate and float(rate[0].split()[-1]) > 0


# ---------------------------------------------------------------------------
# MXNET_TPU_METRICS=0: every new seam is a constant-time guard
# ---------------------------------------------------------------------------

def test_metrics_disabled_records_nothing_on_wire_seams(monkeypatch):
    """With the plane off, driving EVERY new seam — client RPCs, server
    handling, the replication stream, the ServerGroup flush fan-out,
    the serving raw path, federation render and the report itself —
    lands zero _record calls."""
    calls = []
    monkeypatch.setattr(omet.Counter, "_record",
                        lambda self, v: calls.append(("c", v)))
    monkeypatch.setattr(omet.Gauge, "_record",
                        lambda self, v, op: calls.append(("g", v)))
    monkeypatch.setattr(omet.Histogram, "_record",
                        lambda self, v: calls.append(("h", v)))
    monkeypatch.setenv("MXNET_TPU_METRICS", "0")

    p = AsyncServer(secret="t").start()
    f = AsyncServer(secret="t").start()
    try:
        f.rejoin(p.address)               # replication + snapshot seams
        cli = AsyncClient(p.address, rank=0, heartbeat=False, secret="t")
        cli.init([("w", np.zeros(4, np.float32))])
        cli._call({"op": "pull", "keys": ["w"]})
        cli.close()
    finally:
        p.stop()
        f.stop()

    servers = [AsyncServer(server_id=i, secret="g").start()
               for i in range(2)]
    try:
        group = ka.ServerGroup([s.address for s in servers], rank=0,
                               heartbeat=False, secret="g")
        group.init([("k", np.ones(4, np.float32))])
        group.set_optimizer(_sgd_pickle())
        group.push([("k", np.ones(4, np.float32))])   # flush fan-out seam
        group.pull(["k"])
        group.shutdown()
    finally:
        for s in servers:
            s.stop()

    from mxnet_tpu import serving

    row = np.arange(3, dtype=np.float32)
    buf = io.BytesIO()
    np.save(buf, row)
    with serving.start_frontend(_StubTarget()) as fe:
        req = urllib.request.Request(
            fe.url + "/v1/predict?model=m&input=data", data=buf.getvalue(),
            headers={"Content-Type": "application/octet-stream"})
        urllib.request.urlopen(req, timeout=10).read()

    fed = obs.FederatedCollector([
        {"shard": 0, "role": "primary", "epoch": 0,
         "registry": obs.REGISTRY}])
    fed.render()                          # federation parse seam
    rep = owire.wire_report()             # report degrades to zeros
    assert rep["bytes_total"] == 0.0 and rep["socket_bytes"] == 0.0
    assert calls == []
