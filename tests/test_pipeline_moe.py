"""Pipeline + expert parallelism tests on the 8-virtual-device CPU mesh
(the reference's multi-device-on-one-box test strategy, SURVEY.md §4 —
``test_multi_device_exec.py`` / ``test_model_parallel.py`` tier, extended to
the parallelism modes the reference lacks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mxnet_tpu.parallel import moe, pipeline


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _make_stages(rng, n_stages, d):
    out = []
    for i in range(n_stages):
        k1, k2 = jax.random.split(jax.random.fold_in(rng, i))
        out.append({"w": jax.random.normal(k1, (d, d)) * 0.5,
                    "b": jax.random.normal(k2, (d,)) * 0.1})
    return out


def _pipe_mesh(n=4):
    devs = jax.devices()[:n]
    if len(devs) < n:
        pytest.skip("need %d devices" % n)
    return Mesh(np.array(devs), ("pipe",))


def test_pipeline_matches_sequential():
    mesh = _pipe_mesh(4)
    rng = jax.random.PRNGKey(0)
    d, B = 6, 8
    stages = _make_stages(rng, 4, d)
    x = jax.random.normal(jax.random.fold_in(rng, 99), (B, d))

    want = x
    for p in stages:
        want = _stage_fn(p, want)

    stacked = pipeline.stack_stage_params(stages)
    got = pipeline.pipeline_apply(_stage_fn, stacked, x, mesh=mesh,
                                  n_microbatch=4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_microbatch_counts():
    mesh = _pipe_mesh(4)
    rng = jax.random.PRNGKey(1)
    d, B = 4, 12
    stages = _make_stages(rng, 4, d)
    x = jax.random.normal(rng, (B, d))
    want = x
    for p in stages:
        want = _stage_fn(p, want)
    stacked = pipeline.stack_stage_params(stages)
    for n_mb in (2, 3, 6, 12):
        got = pipeline.pipeline_apply(_stage_fn, stacked, x, mesh=mesh,
                                      n_microbatch=n_mb)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match_sequential():
    mesh = _pipe_mesh(4)
    rng = jax.random.PRNGKey(2)
    d, B = 4, 8
    stages = _make_stages(rng, 4, d)
    x = jax.random.normal(rng, (B, d))
    target = jax.random.normal(jax.random.fold_in(rng, 7), (B, d))
    stacked = pipeline.stack_stage_params(stages)

    def loss_pipe(p):
        y = pipeline.pipeline_apply(_stage_fn, p, x, mesh=mesh,
                                    n_microbatch=2)
        return jnp.mean((y - target) ** 2)

    def loss_seq(p):
        y = x
        for i in range(4):
            y = _stage_fn(jax.tree_util.tree_map(lambda a: a[i], p), y)
        return jnp.mean((y - target) ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                   np.asarray(g_seq[k]),
                                   rtol=1e-4, atol=1e-5)


def test_pipelined_trainer_learns():
    mesh = _pipe_mesh(4)
    rng = jax.random.PRNGKey(3)
    d, B = 4, 8
    stages = _make_stages(rng, 4, d)
    x = jax.random.normal(rng, (B, d))
    target = jnp.zeros((B, d))

    tr = pipeline.PipelinedTrainer(
        _stage_fn, lambda y, t: jnp.mean((y - t) ** 2), mesh,
        n_microbatch=2, learning_rate=0.2)
    params = tr.place_params(stages)
    step = tr.step_fn()
    losses = []
    for _ in range(10):
        l, params = step(params, x, target)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.9


def test_moe_routing_reference():
    # capacity ample → every token goes to its argmax expert, scaled by gate
    rng = jax.random.PRNGKey(0)
    d, h, E, B, S = 8, 16, 4, 2, 6
    params = moe.init_moe_params(rng, d, h, E)
    x = jax.random.normal(jax.random.fold_in(rng, 1), (B, S, d))
    out, aux = moe.moe_ffn(params, x, capacity_factor=float(E))
    tokens = np.asarray(x.reshape(B * S, d))
    logits = tokens @ np.asarray(params["router"])
    probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    want = np.zeros_like(tokens)
    for t in range(B * S):
        e = int(np.argmax(probs[t]))
        hdn = np.maximum(tokens[t] @ np.asarray(params["w1"][e]), 0)
        want[t] = probs[t, e] * (hdn @ np.asarray(params["w2"][e]))
    np.testing.assert_allclose(np.asarray(out).reshape(B * S, d), want,
                               rtol=1e-4, atol=1e-5)
    assert float(aux) > 0


def test_moe_capacity_drops_tokens():
    # capacity 1 per expert: at most E tokens survive routing
    rng = jax.random.PRNGKey(4)
    d, h, E, B, S = 4, 8, 2, 1, 8
    params = moe.init_moe_params(rng, d, h, E)
    x = jax.random.normal(rng, (B, S, d))
    out, _ = moe.moe_ffn(params, x, capacity_factor=2.0 / S)  # capacity=1
    nonzero_tokens = np.abs(np.asarray(out).reshape(B * S, d)).sum(-1) > 1e-9
    assert nonzero_tokens.sum() <= E


def test_moe_expert_parallel_matches_dense():
    devs = jax.devices()[:8]
    if len(devs) < 8:
        pytest.skip("need 8 devices")
    mesh = Mesh(np.array(devs).reshape(2, 4), ("data", "expert"))
    rng = jax.random.PRNGKey(5)
    d, h, E, B, S = 8, 16, 4, 4, 8
    params = moe.init_moe_params(rng, d, h, E)
    x = jax.random.normal(rng, (B, S, d))

    dense_out, dense_aux = moe.moe_ffn(params, x, capacity_factor=2.0)

    eshard = NamedSharding(mesh, P("expert"))
    sharded_params = {
        "router": jax.device_put(params["router"], NamedSharding(mesh, P())),
        "w1": jax.device_put(params["w1"], eshard),
        "w2": jax.device_put(params["w2"], eshard),
    }
    xs = jax.device_put(x, NamedSharding(mesh, P("data")))

    @jax.jit
    def run(p, xx):
        return moe.moe_ffn(p, xx, capacity_factor=2.0, mesh=mesh)

    with mesh:
        out, aux = run(sharded_params, xs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense_out),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(aux), float(dense_aux), rtol=1e-5)


def test_moe_differentiable():
    rng = jax.random.PRNGKey(6)
    d, h, E, B, S = 4, 8, 2, 2, 4
    params = moe.init_moe_params(rng, d, h, E)
    x = jax.random.normal(rng, (B, S, d))

    def loss(p):
        out, aux = moe.moe_ffn(p, x)
        return jnp.mean(out ** 2) + 0.01 * aux

    grads = jax.grad(loss)(params)
    for k, g in grads.items():
        assert np.isfinite(np.asarray(g)).all(), k
    assert np.abs(np.asarray(grads["router"])).sum() > 0


def test_remat_matches_nonremat():
    # memonger analog: jax.checkpoint remat must not change numerics
    import mxnet_tpu as mx
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    devs = jax.devices()[:2]
    mesh = Mesh(np.array(devs), ("data",))
    sym = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Activation(mx.sym.FullyConnected(
            mx.sym.Variable("data"), num_hidden=16, name="fc1"),
            act_type="relu"), num_hidden=4, name="fc2"), name="softmax")
    batch_np = {
        "data": np.random.RandomState(0).randn(4, 8).astype(np.float32),
        "softmax_label": np.array([0, 1, 2, 3], np.float32)}
    results = {}
    for remat in (False, True):
        tr = ShardedTrainer(sym, mesh, data_shapes={"data": (4, 8)},
                            label_shapes={"softmax_label": (4,)},
                            momentum=0.9, remat=remat,
                            remat_policy="dots_saveable" if remat else None)
        params, moms, aux = tr.init(seed=0)
        batch = tr.place_batch(batch_np)
        step = tr.step_fn()
        for i in range(3):
            outs, params, moms, aux = step(params, moms, aux, batch,
                                           jax.random.PRNGKey(i))
        results[remat] = {k: np.asarray(v) for k, v in params.items()}
    for k in results[False]:
        np.testing.assert_allclose(results[True][k], results[False][k],
                                   rtol=1e-5, atol=1e-6, err_msg=k)


def test_moe_symbol_op_sharded():
    # MoELayer as a graph node: trains under a data x expert mesh with
    # expert-sharded weights; matches the functional moe_ffn numerics
    import mxnet_tpu as mx
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    devs = jax.devices()[:4]
    if len(devs) < 4:
        pytest.skip("need 4 devices")
    data = mx.sym.Variable("data")
    moe_out = mx.sym.MoELayer(data, num_experts=4, hidden_size=32,
                              name="moe")
    tokens = mx.sym.Reshape(moe_out[0], shape=(-1, 16))
    logits = mx.sym.FullyConnected(tokens, num_hidden=8, name="out")
    label = mx.sym.Reshape(mx.sym.Variable("softmax_label"), shape=(-1,))
    net = mx.sym.Group(
        [mx.sym.SoftmaxOutput(logits, label, name="softmax"),
         mx.sym.MakeLoss(moe_out[1] * 0.01, name="auxl")])
    mesh = Mesh(np.array(devs).reshape(2, 2), ("data", "expert"))
    B, S = 4, 8
    tr = ShardedTrainer(
        net, mesh, data_shapes={"data": (B, S, 16)},
        label_shapes={"softmax_label": (B, S)}, momentum=0.9,
        param_specs={"moe_w1_weight": P("expert"),
                     "moe_w2_weight": P("expert")})
    params, moms, aux = tr.init(seed=0)
    batch = tr.place_batch({
        "data": np.random.RandomState(0).randn(B, S, 16).astype(np.float32),
        "softmax_label": np.random.RandomState(1).randint(
            0, 8, (B, S)).astype(np.float32)})
    step = tr.step_fn()
    for i in range(3):
        outs, params, moms, aux = step(params, moms, aux, batch,
                                       jax.random.PRNGKey(i))
    assert params["moe_w1_weight"].sharding.spec == P("expert")
    assert np.isfinite(float(np.asarray(outs[1])[0]))

    # eager single-device forward matches the functional path
    x = np.random.RandomState(2).randn(2, 4, 16).astype(np.float32)
    gw = np.asarray(params["moe_gate_weight"])
    w1 = np.asarray(params["moe_w1_weight"])
    w2 = np.asarray(params["moe_w2_weight"])
    out_op = mx.nd.MoELayer(mx.nd.array(x), mx.nd.array(gw),
                            mx.nd.array(w1), mx.nd.array(w2),
                            num_experts=4, hidden_size=32)
    fn_out, _ = moe.moe_ffn(
        {"router": jnp.asarray(gw), "w1": jnp.asarray(w1),
         "w2": jnp.asarray(w2)}, jnp.asarray(x))
    np.testing.assert_allclose(out_op[0].asnumpy(), np.asarray(fn_out),
                               rtol=1e-4, atol=1e-5)


# ---------------- 1F1B schedule (round 3) ----------------

def _mse(y, t):
    return jnp.mean((y - t) ** 2)


def test_1f1b_matches_direct_grads():
    """pipeline_train_1f1b's (loss, grads) must equal directly
    differentiating the sequential composition with the same
    per-microbatch loss mean."""
    mesh = _pipe_mesh(4)
    rng = jax.random.PRNGKey(3)
    d, B, M = 4, 8, 4
    stages = _make_stages(rng, 4, d)
    x = jax.random.normal(rng, (B, d))
    target = jax.random.normal(jax.random.fold_in(rng, 11), (B, d))
    stacked = pipeline.stack_stage_params(stages)

    def direct(p):
        mbs = x.reshape(M, B // M, d)
        tgts = target.reshape(M, B // M, d)
        total = 0.0
        for i in range(M):
            y = mbs[i]
            for s in range(4):
                y = _stage_fn(jax.tree_util.tree_map(lambda a: a[s], p), y)
            total = total + _mse(y, tgts[i])
        return total / M

    want_loss, want_grads = jax.value_and_grad(direct)(stacked)
    got_loss, got_grads = pipeline.pipeline_train_1f1b(
        _stage_fn, _mse, stacked, x, target, mesh=mesh, n_microbatch=M)
    np.testing.assert_allclose(np.asarray(got_loss), np.asarray(want_loss),
                               rtol=1e-5)
    for wl, gl in zip(jax.tree_util.tree_leaves(want_grads),
                      jax.tree_util.tree_leaves(got_grads)):
        np.testing.assert_allclose(np.asarray(gl), np.asarray(wl),
                                   rtol=1e-4, atol=1e-5)


def test_1f1b_matches_gpipe_path():
    """Same gradients as differentiating the GPipe pipeline_apply — the
    two schedules are numerically interchangeable."""
    mesh = _pipe_mesh(4)
    rng = jax.random.PRNGKey(4)
    d, B, M = 4, 12, 6
    stages = _make_stages(rng, 4, d)
    x = jax.random.normal(rng, (B, d))
    target = jax.random.normal(jax.random.fold_in(rng, 13), (B, d))
    stacked = pipeline.stack_stage_params(stages)

    def gpipe_loss(p):
        y = pipeline.pipeline_apply(_stage_fn, p, x, mesh=mesh,
                                    n_microbatch=M)
        # same per-microbatch loss mean as the 1F1B schedule applies
        yy = y.reshape(M, B // M, d)
        tt = target.reshape(M, B // M, d)
        return jnp.mean(jax.vmap(_mse)(yy, tt))

    want_loss, want_grads = jax.value_and_grad(gpipe_loss)(stacked)
    got_loss, got_grads = pipeline.pipeline_train_1f1b(
        _stage_fn, _mse, stacked, x, target, mesh=mesh, n_microbatch=M)
    np.testing.assert_allclose(np.asarray(got_loss), np.asarray(want_loss),
                               rtol=1e-5)
    for wl, gl in zip(jax.tree_util.tree_leaves(want_grads),
                      jax.tree_util.tree_leaves(got_grads)):
        np.testing.assert_allclose(np.asarray(gl), np.asarray(wl),
                                   rtol=1e-4, atol=1e-5)


def test_1f1b_heterogeneous_stages():
    """stage_idx-conditioned behavior (the SPMD form of non-homogeneous
    stages): first stage scales, last stage shifts; parity vs direct."""
    mesh = _pipe_mesh(4)
    rng = jax.random.PRNGKey(5)
    d, B, M = 4, 8, 4
    stages = _make_stages(rng, 4, d)
    x = jax.random.normal(rng, (B, d))
    target = jax.random.normal(jax.random.fold_in(rng, 17), (B, d))
    stacked = pipeline.stack_stage_params(stages)

    def het_stage(params, xin, stage_idx):
        y = jnp.tanh(xin @ params["w"] + params["b"])
        y = jnp.where(stage_idx == 0, 2.0 * y, y)     # "embed" stage
        return jnp.where(stage_idx == 3, y + 1.0, y)  # "head" stage

    def direct(p):
        mbs = x.reshape(M, B // M, d)
        tgts = target.reshape(M, B // M, d)
        total = 0.0
        for i in range(M):
            y = mbs[i]
            for s in range(4):
                y = het_stage(jax.tree_util.tree_map(lambda a: a[s], p),
                              y, jnp.int32(s))
            total = total + _mse(y, tgts[i])
        return total / M

    want_loss, want_grads = jax.value_and_grad(direct)(stacked)
    got_loss, got_grads = pipeline.pipeline_train_1f1b(
        het_stage, _mse, stacked, x, target, mesh=mesh, n_microbatch=M)
    np.testing.assert_allclose(np.asarray(got_loss), np.asarray(want_loss),
                               rtol=1e-5)
    for wl, gl in zip(jax.tree_util.tree_leaves(want_grads),
                      jax.tree_util.tree_leaves(got_grads)):
        np.testing.assert_allclose(np.asarray(gl), np.asarray(wl),
                                   rtol=1e-4, atol=1e-5)


# ---------------- top-k routing (round 3) ----------------

def test_router_topk_k1_matches_top1():
    rng = jax.random.PRNGKey(6)
    logits = jax.random.normal(rng, (24, 4))
    d1, c1, a1 = moe.router_top1(logits, capacity=8)
    dk, ck, ak = moe.router_topk(logits, capacity=8, k=1)
    np.testing.assert_allclose(np.asarray(d1), np.asarray(dk), atol=1e-6)
    np.testing.assert_allclose(np.asarray(a1), np.asarray(ak), rtol=1e-6)
    # k=1 gates renormalize to 1.0 at the chosen slot, top1's carry probs
    np.testing.assert_allclose(np.asarray(jnp.sum(ck, axis=(1, 2))),
                               np.ones(24), rtol=1e-5)


def test_router_top2_properties():
    rng = jax.random.PRNGKey(7)
    T, E, C = 32, 4, 32  # capacity = T: drops impossible at any skew
    logits = jax.random.normal(rng, (T, E))
    dispatch, combine, aux = moe.router_topk(logits, capacity=C, k=2)
    d = np.asarray(dispatch)
    # every token lands exactly 2 slots, in 2 DIFFERENT experts
    np.testing.assert_allclose(d.sum(axis=(1, 2)), 2.0)
    assert (d.sum(axis=2) <= 1.0 + 1e-6).all()
    # each expert buffer slot holds at most one token
    assert (d.sum(axis=0) <= 1.0 + 1e-6).all()
    # gates renormalized over the two picks
    np.testing.assert_allclose(np.asarray(combine).sum(axis=(1, 2)),
                               np.ones(T), rtol=1e-5)
    # the two picks are the true top-2 experts by probability
    probs = np.asarray(jax.nn.softmax(logits, axis=-1))
    want = np.sort(np.argsort(-probs, axis=1)[:, :2], axis=1)
    got = np.sort(np.argwhere(d.sum(axis=2) > 0.5)[:, 1].reshape(T, 2),
                  axis=1)
    np.testing.assert_array_equal(got, want)
    assert float(aux) > 0


def test_router_top2_capacity_drops():
    # all tokens prefer expert 0: only `capacity` rank-0 assignments stay
    logits = jnp.tile(jnp.array([[4.0, 2.0, 0.0, -2.0]]), (10, 1))
    dispatch, _, _ = moe.router_topk(logits, capacity=3, k=2)
    d = np.asarray(dispatch)
    assert d[:, 0].sum() == 3.0  # expert 0 full at capacity
    assert d[:, 1].sum() == 3.0  # second choice fills expert 1 likewise
    # dropped tokens simply lose that slot
    assert d.sum() == 6.0


def test_moe_ffn_top2_mesh_matches_dense():
    devs = jax.devices()[:4]
    if len(devs) < 4:
        pytest.skip("need 4 devices")
    mesh = Mesh(np.array(devs), ("expert",))
    rng = jax.random.PRNGKey(8)
    params = moe.init_moe_params(rng, d_model=8, d_hidden=16, num_experts=4)
    x = jax.random.normal(jax.random.fold_in(rng, 1), (2, 16, 8))

    dense_out, dense_aux = moe.moe_ffn(params, x, top_k=2)

    @jax.jit
    def sharded(p, xx):
        return moe.moe_ffn(p, xx, mesh=mesh, top_k=2)

    with mesh:
        out, aux = sharded(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense_out),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(aux), np.asarray(dense_aux),
                               rtol=1e-5)


def test_1f1b_composed_mesh_dp_tp_pp_parity():
    """Composed dp x tp x pp in ONE mesh (round 4): 1F1B with the batch
    sharded over "data", Megatron column/row-split stage weights over
    "model" (partial-sum stage contract via reduce_axes), stages over
    "pipe" — 3 SGD steps must track a plain single-device run exactly."""
    devs = jax.devices()[:8]
    mesh = Mesh(np.array(devs).reshape(2, 2, 2), ("data", "model", "pipe"))
    S, d, h, B, M, lr = 2, 8, 16, 8, 2, 0.1
    rng = np.random.RandomState(7)
    full = {"w1": jnp.asarray(rng.randn(S, d, h).astype(np.float32)) * 0.4,
            "b1": jnp.asarray(rng.randn(S, h).astype(np.float32)) * 0.1,
            "w2": jnp.asarray(rng.randn(S, h, d).astype(np.float32)) * 0.4}
    axes = {"w1": P("pipe", None, "model"), "b1": P("pipe", "model"),
            "w2": P("pipe", "model", None)}

    def stage(p, x):
        return jnp.tanh(x @ p["w1"] + p["b1"]) @ p["w2"]  # partial over model

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    x = jnp.asarray(rng.randn(B, d).astype(np.float32))
    t = jnp.asarray(rng.randn(B, d).astype(np.float32))
    sharded = {k: jax.device_put(v, NamedSharding(mesh, axes[k]))
               for k, v in full.items()}
    xs = jax.device_put(x, NamedSharding(mesh, P("data")))
    ts = jax.device_put(t, NamedSharding(mesh, P("data")))

    @jax.jit
    def composed_step(p, x_, t_):
        loss, g = pipeline.pipeline_train_1f1b(
            stage, loss_fn, p, x_, t_, mesh=mesh, n_microbatch=M,
            batch_axis="data", param_axes=axes, reduce_axes=("model",))
        return loss, jax.tree_util.tree_map(lambda w, gg: w - lr * gg, p, g)

    @jax.jit
    def ref_step(p, x_, t_):
        def full_loss(p_):
            y = x_
            for s in range(S):
                y = jnp.tanh(y @ p_["w1"][s] + p_["b1"][s]) @ p_["w2"][s]
            return loss_fn(y, t_)

        loss, g = jax.value_and_grad(full_loss)(p)
        return loss, jax.tree_util.tree_map(lambda w, gg: w - lr * gg, p, g)

    ref_p = dict(full)
    for _ in range(3):
        l_comp, sharded = composed_step(sharded, xs, ts)
        l_ref, ref_p = ref_step(ref_p, x, t)
        np.testing.assert_allclose(float(l_comp), float(l_ref), rtol=1e-5)
    for k in full:
        np.testing.assert_allclose(np.asarray(jax.device_get(sharded[k])),
                                   np.asarray(ref_p[k]), rtol=1e-4,
                                   atol=1e-5)


def test_1f1b_composed_mesh_dp_pp_ep_moe_parity():
    """Composed dp x pp x ep in ONE mesh (round 4): each pipeline stage
    IS a top-2 MoE FFN with experts sharded over "expert" — manual
    collectives inside the pipeline's shard_map: router column-sharded
    with an all_gather of logits (whose vjp reduce-scatters router grads
    across expert shards), expert outputs emitted as PARTIAL sums under
    reduce_axes=("expert",).  3 SGD steps must track a dense
    single-device run exactly (loss AND params)."""
    from jax import lax

    devs = jax.devices()[:8]
    if len(devs) < 8:
        pytest.skip("need 8 devices")
    mesh = Mesh(np.array(devs).reshape(2, 2, 2), ("data", "pipe", "expert"))
    S, d, h, E, B, M, lr = 2, 8, 16, 4, 8, 2, 0.05
    dp = mesh.shape["data"]
    mb = B // dp // M
    cap = max(int(2 * 2.0 * mb / E), 1)
    EL = E // mesh.shape["expert"]
    rng = np.random.RandomState(9)
    full = {
        "router": jnp.asarray(rng.randn(S, d, E).astype(np.float32)) * 0.3,
        "w1": jnp.asarray(rng.randn(S, E, d, h).astype(np.float32)) * 0.4,
        "w2": jnp.asarray(rng.randn(S, E, h, d).astype(np.float32)) * 0.4,
    }
    axes = {"router": P("pipe", None, "expert"),
            "w1": P("pipe", "expert", None, None),
            "w2": P("pipe", "expert", None, None)}

    def stage(p, x):
        logits = lax.all_gather(x @ p["router"], "expert", axis=1,
                                tiled=True)
        dispatch, combine, _ = moe.router_topk(logits, cap, k=2)
        e0 = lax.axis_index("expert") * EL
        disp_l = lax.dynamic_slice_in_dim(dispatch, e0, EL, 1)
        comb_l = lax.dynamic_slice_in_dim(combine, e0, EL, 1)
        buf = jnp.einsum("tec,td->ecd", disp_l, x)
        hh = jax.nn.relu(jnp.einsum("ecd,edh->ech", buf, p["w1"]))
        out_buf = jnp.einsum("ech,ehd->ecd", hh, p["w2"])
        return jnp.einsum("tec,ecd->td", comb_l, out_buf)

    def stage_ref(p, x):
        dispatch, combine, _ = moe.router_topk(x @ p["router"], cap, k=2)
        buf = jnp.einsum("tec,td->ecd", dispatch, x)
        hh = jax.nn.relu(jnp.einsum("ecd,edh->ech", buf, p["w1"]))
        out_buf = jnp.einsum("ech,ehd->ecd", hh, p["w2"])
        return jnp.einsum("tec,ecd->td", combine, out_buf)

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    x = jnp.asarray(rng.randn(B, d).astype(np.float32))
    t = jnp.asarray(rng.randn(B, d).astype(np.float32))
    sharded = {k: jax.device_put(v, NamedSharding(mesh, axes[k]))
               for k, v in full.items()}
    xs = jax.device_put(x, NamedSharding(mesh, P("data")))
    ts = jax.device_put(t, NamedSharding(mesh, P("data")))

    @jax.jit
    def composed_step(p, x_, t_):
        loss, g = pipeline.pipeline_train_1f1b(
            stage, loss_fn, p, x_, t_, mesh=mesh, n_microbatch=M,
            batch_axis="data", param_axes=axes, reduce_axes=("expert",))
        return loss, jax.tree_util.tree_map(lambda w, gg: w - lr * gg, p, g)

    @jax.jit
    def ref_step(p, x_, t_):
        def full_loss(p_):
            # chunks of mb rows reproduce the dp-shard x microbatch
            # partition (routing capacity is per local microbatch)
            losses = []
            for m in range(B // mb):
                y = x_[m * mb:(m + 1) * mb]
                for s in range(S):
                    y = stage_ref(
                        jax.tree_util.tree_map(lambda a: a[s], p_), y)
                losses.append(loss_fn(y, t_[m * mb:(m + 1) * mb]))
            return sum(losses) / len(losses)

        loss, g = jax.value_and_grad(full_loss)(p)
        return loss, jax.tree_util.tree_map(lambda w, gg: w - lr * gg, p, g)

    ref_p = dict(full)
    for _ in range(3):
        l_comp, sharded = composed_step(sharded, xs, ts)
        l_ref, ref_p = ref_step(ref_p, x, t)
        np.testing.assert_allclose(float(l_comp), float(l_ref), rtol=1e-5)
    for k in full:
        np.testing.assert_allclose(np.asarray(jax.device_get(sharded[k])),
                                   np.asarray(ref_p[k]), rtol=1e-4,
                                   atol=1e-5)


def test_moe_indexed_dispatch_matches_einsum():
    """The no-expert-axis fast path (O(T*E) scatter/gather dispatch) must
    reproduce the dense (T,E,C)-einsum formulation exactly — same
    assignment, same gates, same drops — for top-1 AND top-2."""
    rng = np.random.RandomState(11)
    B, S, d, E, h = 2, 16, 8, 4, 12
    x = jnp.asarray(rng.randn(B, S, d).astype(np.float32))
    params = {
        "router": jnp.asarray(rng.randn(d, E).astype(np.float32) * 0.5),
        "w1": jnp.asarray(rng.randn(E, d, h).astype(np.float32) * 0.3),
        "w2": jnp.asarray(rng.randn(E, h, d).astype(np.float32) * 0.3),
    }
    for k in (1, 2):
        # capacity small enough that drops occur (skewed router)
        out_idx, aux_idx = moe.moe_ffn(params, x, capacity_factor=0.75,
                                       top_k=k)  # mesh=None -> indexed
        tokens = x.reshape(B * S, d)
        cap = max(int(k * 0.75 * B * S / E), 1)
        logits = tokens @ params["router"]
        if k == 1:
            disp, comb, aux_e = moe.router_top1(logits, cap)
        else:
            disp, comb, aux_e = moe.router_topk(logits, cap, k=k)
        buf = jnp.einsum("tec,td->ecd", disp, tokens)
        hh = jax.nn.relu(jnp.einsum("ecd,edh->ech", buf, params["w1"]))
        ob = jnp.einsum("ech,ehd->ecd", hh, params["w2"])
        out_e = jnp.einsum("tec,ecd->td", comb, ob).reshape(B, S, d)
        np.testing.assert_allclose(np.asarray(out_idx), np.asarray(out_e),
                                   rtol=1e-5, atol=1e-6, err_msg="k=%d" % k)
        np.testing.assert_allclose(np.asarray(aux_idx), np.asarray(aux_e),
                                   rtol=1e-6, err_msg="k=%d" % k)
