"""Monitor — per-op tensor stat capture (parity: reference
``python/mxnet/monitor.py``; executor monitor callback,
``graph_executor.cc:131 ExecuteMonCallback``).

The jitted executor doesn't call back per-op; instead ``toc`` re-runs the
graph interpreted (un-jitted) over the executor's current inputs and applies
``stat_func`` to every interior output — same observability, paid only when
the monitor is active (the reference likewise disables bulk-exec for this).
"""

from __future__ import annotations

import logging
import re

from .observability import metrics as _metrics

__all__ = ["Monitor"]

_M_STAT = _metrics.gauge(
    "monitor_stat",
    "Latest per-tensor statistic captured by mx.mon.Monitor", ["tensor"])


class Monitor(object):
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def asum_stat(x):
                return float(abs(x.asnumpy()).mean())

            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

    def install(self, exe):
        exe.set_monitor_callback(self.stat_helper)
        self.exes.append(exe)

    def stat_helper(self, name, arr):
        if not self.activated or not self.re_prog.match(name):
            return
        self.queue.append((self.step, name, self.stat_func(arr)))

    def tic(self):
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        if not self.activated:
            return []
        for exe in self.exes:
            self._capture(exe)
        self.activated = False
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for n, k, v_list in self.queue:
            try:
                # scalar stats become live gauge series (one per tensor);
                # non-scalar stat_func results stay string-only
                _M_STAT.labels(k).set(float(v_list))
            except (TypeError, ValueError):
                pass
            res.append((n, k, str(v_list)))
        self.queue = []
        return res

    def _capture(self, exe):
        """Drive the executor's monitor-callback capture (the callback we
        installed in :meth:`install` receives every interior output)."""
        exe.run_monitor_capture()

    def toc_print(self):
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: %7d %30s %s", n, k, v)
