"""Native runtime tests — the reference's C++ unit tier surfaced through
pytest (reference ``tests/cpp/threaded_engine_test.cc`` pushes random-dep op
graphs then asserts invariants; ``storage_test.cc`` asserts pool reuse).
The same stress also runs as a pure C++ binary via ``make -C native test``.
"""

import ctypes
import json
import os
import random
import threading

import numpy as np
import pytest

from mxnet_tpu import _native, engine, recordio


native = pytest.mark.skipif(not _native.available(),
                            reason="native library not built")


@native
def test_engine_write_serialization():
    # ops writing the same var must serialize in push order
    order = []
    var = engine.new_variable()

    def make(i):
        def fn():
            order.append(i)
        return fn

    for i in range(200):
        engine.push(make(i), mutable_vars=[var], name="w%d" % i)
    engine.wait_for_all()
    assert order == list(range(200))
    engine.delete_variable(var)
    engine.wait_for_all()


@native
def test_engine_random_dependency_stress():
    # mirror of native/tests/engine_test.cc through the Python binding:
    # unsynchronized per-var counters are safe iff writers serialize per var
    rng = random.Random(0)
    nvars, nops = 8, 500
    vars_ = [engine.new_variable() for _ in range(nvars)]
    counters = np.zeros(nvars, dtype=np.int64)
    expected = np.zeros(nvars, dtype=np.int64)

    def make(widx):
        def fn():
            for v in widx:
                cur = counters[v]
                for _ in range(20):
                    pass
                counters[v] = cur + 1
        return fn

    for _ in range(nops):
        perm = rng.sample(range(nvars), 3)
        reads, writes = perm[:1], perm[1:]
        for w in writes:
            expected[w] += 1
        engine.push(make(writes),
                    const_vars=[vars_[r] for r in reads],
                    mutable_vars=[vars_[w] for w in writes])
    engine.wait_for_all()
    np.testing.assert_array_equal(counters, expected)
    for v in vars_:
        engine.wait_for_var(v)
        engine.delete_variable(v)
    engine.wait_for_all()


@native
def test_engine_reads_parallel_with_barrier():
    # readers between two writes all see the first write's value
    var = engine.new_variable()
    box = {"v": 0}
    seen = []
    lock = threading.Lock()

    def write1():
        box["v"] = 1

    def write2():
        box["v"] = 2

    def read():
        with lock:
            seen.append(box["v"])

    engine.push(write1, mutable_vars=[var])
    for _ in range(20):
        engine.push(read, const_vars=[var])
    engine.push(write2, mutable_vars=[var])
    engine.wait_for_all()
    assert seen == [1] * 20
    assert box["v"] == 2


@native
def test_engine_gil_releasing_ops_overlap():
    """MEASURED concurrency, not just op counts: independent ops whose
    bodies release the GIL (sleep here; file IO / large numpy in
    production) must actually run concurrently on the worker pool.  With
    4 normal workers, 4 x 0.3 s sleeps must finish in well under the
    1.2 s serial time — this is the engine.py docstring's overlap claim
    as an assertion (and it holds on a single-core box, since sleeping
    threads need no core)."""
    import time

    if engine.engine_type() == "NaiveEngine":
        pytest.skip("NaiveEngine is synchronous by design")
    # the 4 ops run on the NORMAL pool specifically (num_workers counts
    # all three pools, so it can't gate this)
    if int(os.environ.get("MXTPU_CPU_WORKER_NTHREADS", "4")) < 4:
        pytest.skip("normal pool too small for a 4-way overlap assert")
    n, d = 4, 0.3
    engine.wait_for_all()  # quiesce: earlier tests' ops must not skew timing
    vars_ = [engine.new_variable() for _ in range(n)]
    t0 = time.monotonic()
    for v in vars_:
        engine.push(lambda: time.sleep(d), mutable_vars=[v])
    engine.wait_for_all()
    elapsed = time.monotonic() - t0
    serial = n * d
    # demand >=2x measured overlap (observed ~0.31 s vs 1.2 s serial)
    assert elapsed < serial / 2, (elapsed, serial)
    # contrast: the same ops chained on ONE var serialize (write deps)
    shared = engine.new_variable()
    t0 = time.monotonic()
    for _ in range(n):
        engine.push(lambda: time.sleep(d), mutable_vars=[shared])
    engine.wait_for_all()
    chained = time.monotonic() - t0
    assert chained > serial * 0.9, (chained, serial)
    for v in vars_ + [shared]:
        engine.delete_variable(v)
    engine.wait_for_all()


@native
def test_storage_pool_reuse():
    lib = _native.lib()
    p1 = lib.mxtpu_storage_alloc(1 << 14)
    lib.mxtpu_storage_free(p1, 1 << 14)
    p2 = lib.mxtpu_storage_alloc(1 << 14)
    assert p1 == p2
    lib.mxtpu_storage_direct_free(p2, 1 << 14)
    lib.mxtpu_storage_release_all()


@native
def test_recordio_native_python_bitcompat(tmp_path):
    # native writer → python reader and vice versa must agree byte-for-byte
    path = str(tmp_path / "t.rec")
    payloads = [os.urandom(n) for n in (1, 3, 4, 100, 1000)]

    w = recordio.MXRecordIO(path, "w")
    assert w._nh, "expected native writer"
    for p in payloads:
        w.write(p)
    w.close()

    recordio._FORCE_PYTHON = True
    try:
        r = recordio.MXRecordIO(path, "r")
        assert not r._nh
        got = [r.read() for _ in payloads]
        assert r.read() is None
        r.close()
        assert got == payloads

        path2 = str(tmp_path / "t2.rec")
        w2 = recordio.MXRecordIO(path2, "w")
        for p in payloads:
            w2.write(p)
        w2.close()
    finally:
        recordio._FORCE_PYTHON = False

    r2 = recordio.MXRecordIO(path2, "r")
    assert r2._nh, "expected native reader"
    got2 = [r2.read() for _ in payloads]
    assert r2.read() is None
    r2.close()
    assert got2 == payloads


@native
def test_loader_sharding_and_shuffle(tmp_path):
    path = str(tmp_path / "s.rec")
    w = recordio.MXRecordIO(path, "w")
    recs = [("rec%04d" % i).encode() for i in range(100)]
    for rec in recs:
        w.write(rec)
    w.close()

    # num_parts loaders cover a disjoint union of all records
    seen = []
    for part in range(4):
        ld = _native.RecordLoader(path, part_index=part, num_parts=4)
        seen.extend(list(ld))
        ld.close()
    assert sorted(seen) == sorted(recs)

    # shuffle: deterministic per seed, different across epochs, same multiset
    ld = _native.RecordLoader(path, shuffle=True, seed=7, shuffle_chunk=32)
    ep1 = list(ld)
    ld.reset()
    ep2 = list(ld)
    ld.close()
    assert sorted(ep1) == sorted(recs) and sorted(ep2) == sorted(recs)
    assert ep1 != recs  # actually shuffled
    assert ep1 != ep2   # epoch reshuffle
    ld2 = _native.RecordLoader(path, shuffle=True, seed=7, shuffle_chunk=32)
    assert list(ld2) == ep1  # seed-deterministic
    ld2.close()


@native
def test_profiler_chrome_trace(tmp_path):
    lib = _native.lib()
    lib.mxtpu_profiler_clear()
    lib.mxtpu_profiler_set_state(1)
    var = engine.new_variable()
    for i in range(5):
        engine.push(lambda: None, mutable_vars=[var], name="traced_op")
    engine.wait_for_all()
    lib.mxtpu_profiler_set_state(0)
    out = str(tmp_path / "trace.json")
    n = lib.mxtpu_profiler_dump(out.encode())
    assert n >= 5
    trace = json.load(open(out))
    names = [e["name"] for e in trace["traceEvents"]]
    assert names.count("traced_op") == 5
    for e in trace["traceEvents"]:
        assert e["ph"] == "X" and e["dur"] >= 0
    lib.mxtpu_profiler_clear()


def test_engine_is_load_bearing(tmp_path):
    """Training through PrefetchingIter + local kvstore + checkpoint must
    route host work through the dependency engine (prefetch staging on the
    IO lane, kv updates, checkpoint writes) — the engine op count grows
    during an ordinary fit, and results stay correct."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import engine

    before = engine.op_count()
    rng = np.random.RandomState(0)
    labels = rng.randint(0, 4, 200)
    centers = rng.randn(4, 10) * 3
    data = (centers[labels] + rng.randn(200, 10)).astype(np.float32)
    base = mx.io.NDArrayIter(data, labels.astype(np.float32), batch_size=20,
                             shuffle=True)
    train = mx.io.PrefetchingIter(base)
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                              name="fc"), name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    # pass a KVStore INSTANCE: the "local" string with one device resolves
    # to kv=None in _create_kvstore and would skip the kv engine path
    kv = mx.kv.create("local")
    mod.fit(train, num_epoch=4, optimizer="sgd", kvstore=kv,
            optimizer_params={"learning_rate": 0.3},
            initializer=mx.initializer.Xavier())
    assert kv._key_vars, "kvstore engine path not exercised"
    acc = mod.score(mx.io.NDArrayIter(data, labels.astype(np.float32),
                                      batch_size=20), "acc")
    assert acc[0][1] > 0.9, acc
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 1)  # engine IO-lane write
    after = engine.op_count()
    assert after - before > 20, (before, after)
    # read-after-write ordering: load sees the finished file
    symbol, args, auxs = mx.model.load_checkpoint(prefix, 1)
    assert "fc_weight" in args


def test_c_predict_api(tmp_path):
    """C ABI predict round-trip (reference c_predict_api.h MXPred* tier):
    export a model, serve it from the C++ client, compare numerics."""
    import subprocess

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import deploy

    import shutil
    import sys as _sys

    if shutil.which("make") is None or shutil.which("g++") is None:
        pytest.skip("native toolchain unavailable")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    binary = os.path.join(repo, "native", "build", "predict_test")
    # always invoke make: it is incremental, and a stale binary would
    # silently test code no longer in the tree; PYTHON pins the embedded
    # interpreter to the one running this test (venv-safe)
    r = subprocess.run(["make", "-C", os.path.join(repo, "native"),
                        "predict", "PYTHON=%s" % _sys.executable],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr

    # train-ish model: fixed params, deterministic outputs
    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Variable("data"), num_hidden=3, name="fc"), name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (1, 6))],
             label_shapes=[("softmax_label", (1,))])
    mod.init_params(mx.initializer.Xavier())
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 0)
    artifact = deploy.export_model(prefix, 0, {"data": (1, 6)})

    x = np.linspace(-1, 1, 6, dtype=np.float32).reshape(1, 6)
    want = deploy.load_exported(artifact)(data=x)[0].ravel()
    expected = tmp_path / "expected.txt"
    expected.write_text(
        " ".join("%.8g" % float(v) for v in x.ravel()) + "\n" +
        " ".join("%.8g" % float(v) for v in want) + "\n")

    prior = os.environ.get("PYTHONPATH")
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXTPU_PRED_PLATFORM="cpu",
               PYTHONPATH=repo + ((os.pathsep + prior) if prior else ""))
    r = subprocess.run([binary, artifact, str(expected)],
                       capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "OK" in r.stdout, r.stdout


def _write_idx(path, arr):
    """Write MNIST idx format (magic encodes dtype=uint8 + ndim)."""
    import struct as _struct

    import numpy as np

    arr = np.asarray(arr, dtype=np.uint8)
    with open(path, "wb") as f:
        f.write(_struct.pack(">I", 0x0800 | arr.ndim))
        for d in arr.shape:
            f.write(_struct.pack(">I", d))
        f.write(arr.tobytes())


def test_c_api_trains_lenet(tmp_path):
    """The full C ABI contract (reference c_api.h: MXSymbol*/MXExecutor*/
    MXKVStore*/MXDataIter* tiers): a pure-C client composes LeNet,
    binds an executor, trains via kvstore push/pull with a server-side
    optimizer, reading batches through the DataIter C API — end to end,
    no Python in the client."""
    import shutil
    import subprocess
    import sys as _sys

    import numpy as np

    if shutil.which("make") is None or shutil.which("g++") is None:
        pytest.skip("native toolchain unavailable")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(["make", "-C", os.path.join(repo, "native"),
                        "capi", "PYTHON=%s" % _sys.executable],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr

    # synthetic MNIST: class c = bright 10x10 block in grid cell c + noise
    rng = np.random.RandomState(0)
    n = 512
    labels = rng.randint(0, 10, n)
    images = rng.randint(0, 40, (n, 28, 28))
    for i, c in enumerate(labels):
        row, col = (c // 2) * 5 + 1, (c % 2) * 13 + 2
        images[i, row:row + 10, col:col + 10] += 180
    _write_idx(tmp_path / "img.idx", images.clip(0, 255))
    _write_idx(tmp_path / "lab.idx", labels)

    binary = os.path.join(repo, "native", "build", "train_capi_test")
    prior = os.environ.get("PYTHONPATH")
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXNET_TPU_PLATFORM="cpu",
               PYTHONPATH=repo + ((os.pathsep + prior) if prior else ""))
    r = subprocess.run([binary, str(tmp_path / "img.idx"),
                        str(tmp_path / "lab.idx"), "3", "32"],
                       capture_output=True, text=True, env=env, timeout=600)
    assert r.returncode == 0, (r.stdout, r.stderr)
    line = [l for l in r.stdout.splitlines() if l.startswith("C_API_TRAIN")]
    assert line, r.stdout
    acc = float(line[0].split("acc=")[1])
    assert acc >= 0.9, r.stdout


def test_cpp_frontend_trains_lenet(tmp_path):
    """The header-only C++ TRAINING frontend (cpp-package parity:
    Symbol/Executor/KVStore/DataIter + FeedForward fit loop over the C
    ABI): compile examples/cpp/train_lenet.cpp and converge on synthetic
    MNIST."""
    import shutil
    import subprocess
    import sys as _sys

    import numpy as np

    if shutil.which("make") is None or shutil.which("g++") is None:
        pytest.skip("native toolchain unavailable")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(["make", "-C", os.path.join(repo, "native"),
                        "cpp_train", "PYTHON=%s" % _sys.executable],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr

    rng = np.random.RandomState(5)
    n = 512
    labels = rng.randint(0, 10, n)
    images = rng.randint(0, 40, (n, 28, 28))
    for i, c in enumerate(labels):
        row, col = (c // 2) * 5 + 1, (c % 2) * 13 + 2
        images[i, row:row + 10, col:col + 10] += 180
    _write_idx(tmp_path / "img.idx", images.clip(0, 255))
    _write_idx(tmp_path / "lab.idx", labels)

    binary = os.path.join(repo, "native", "build", "train_lenet")
    prior = os.environ.get("PYTHONPATH")
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXNET_TPU_PLATFORM="cpu",
               PYTHONPATH=repo + ((os.pathsep + prior) if prior else ""))
    prefix = str(tmp_path / "cppmodel")
    r = subprocess.run([binary, str(tmp_path / "img.idx"),
                        str(tmp_path / "lab.idx"), "3", "32", prefix],
                       capture_output=True, text=True, env=env, timeout=600)
    assert r.returncode == 0, (r.stdout, r.stderr)
    line = [l for l in r.stdout.splitlines() if l.startswith("CPP_TRAIN")]
    assert line, r.stdout
    cpp_acc = float(line[0].split("acc=")[1])
    assert cpp_acc >= 0.9, r.stdout

    # cross-frontend round-trip: the C++-trained checkpoint loads into
    # the PYTHON frontend and scores the same data at the same accuracy
    import mxnet_tpu as mx

    sym, args, auxs = mx.model.load_checkpoint(prefix, 1)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (32, 1, 28, 28))], for_training=False)
    mod.set_params(args, auxs)
    it = mx.io.MNISTIter(image=str(tmp_path / "img.idx"),
                         label=str(tmp_path / "lab.idx"), batch_size=32,
                         shuffle=False)
    correct = total = 0
    for b in it:
        mod.forward(b, is_train=False)
        pred = mod.get_outputs()[0].asnumpy().argmax(axis=1)
        truth = b.label[0].asnumpy().astype(np.int64)
        n = 32 - b.pad
        correct += int((pred[:n] == truth[:n]).sum())
        total += n
    py_acc = correct / total
    assert abs(py_acc - cpp_acc) < 0.05, (py_acc, cpp_acc)


def test_cpp_frontend_bucketing():
    """BucketingModel in the C++ frontend (BucketingModule analog; the
    reference cpp-package had no bucketing): per-bucket executor cache
    with kvstore-authoritative shared weights trains a variable-length
    RNN across interleaved sequence lengths."""
    import shutil
    import subprocess
    import sys as _sys

    if shutil.which("make") is None or shutil.which("g++") is None:
        pytest.skip("native toolchain unavailable")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(["make", "-C", os.path.join(repo, "native"),
                        "cpp_train", "PYTHON=%s" % _sys.executable],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr

    binary = os.path.join(repo, "native", "build", "train_bucketing")
    prior = os.environ.get("PYTHONPATH")
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXNET_TPU_PLATFORM="cpu",
               PYTHONPATH=repo + ((os.pathsep + prior) if prior else ""))
    r = subprocess.run([binary, "6", "32"], capture_output=True,
                       text=True, env=env, timeout=900)
    assert r.returncode == 0, (r.stdout, r.stderr)
    line = [l for l in r.stdout.splitlines()
            if l.startswith("CPP_BUCKETING")]
    assert line, r.stdout
    acc = float(line[0].split("acc=")[1].split()[0])
    assert acc >= 0.85, r.stdout
    assert "buckets=2" in line[0], r.stdout


def test_perl_frontend_trains_lenet(tmp_path):
    """The perl frontend (reference perl-package/AI-MXNet + AI-MXNetCAPI:
    an ExtUtils::MakeMaker-built XS binding over the flat C ABI): build
    AI::MXNetTPU with MakeMaker, then train LeNet to >=0.9 accuracy from
    pure perl — the 'every frontend binds the C API' contract in a
    non-C-family language."""
    import shutil
    import subprocess
    import sys as _sys

    import numpy as np

    perl = shutil.which("perl")
    if perl is None or shutil.which("make") is None:
        pytest.skip("perl/make unavailable")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    probe = subprocess.run(
        [perl, "-MExtUtils::MakeMaker", "-e", "1"], capture_output=True)
    if probe.returncode != 0:
        pytest.skip("ExtUtils::MakeMaker unavailable")

    r = subprocess.run(["make", "-C", os.path.join(repo, "native"),
                        "capi", "PYTHON=%s" % _sys.executable],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr

    # MakeMaker writes its build tree next to the sources: build from a
    # copy under tmp_path so the repo stays clean
    pkg = os.path.join(repo, "perl-package", "AI-MXNetTPU")
    build = tmp_path / "AI-MXNetTPU"
    shutil.copytree(pkg, build)
    env = dict(os.environ, MXTPU_NATIVE=os.path.join(repo, "native"),
               JAX_PLATFORMS="cpu", MXNET_TPU_PLATFORM="cpu",
               PYTHONPATH=repo + ((os.pathsep + os.environ["PYTHONPATH"])
                                  if os.environ.get("PYTHONPATH") else ""))
    r = subprocess.run([perl, "Makefile.PL"], cwd=build, env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(["make"], cwd=build, env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr

    # synthetic MNIST (same generator as the C client's gate)
    rng = np.random.RandomState(0)
    n = 512
    labels = rng.randint(0, 10, n)
    images = rng.randint(0, 40, (n, 28, 28))
    for i, c in enumerate(labels):
        row, col = (c // 2) * 5 + 1, (c % 2) * 13 + 2
        images[i, row:row + 10, col:col + 10] += 180
    _write_idx(tmp_path / "img.idx", images.clip(0, 255))
    _write_idx(tmp_path / "lab.idx", labels)

    blib = os.path.join(str(build), "blib")
    env["PERL5LIB"] = (os.path.join(blib, "lib") + os.pathsep
                      + os.path.join(blib, "arch"))
    r = subprocess.run(
        [perl, str(build / "t" / "train_lenet.pl"),
         str(tmp_path / "img.idx"), str(tmp_path / "lab.idx"), "3", "32"],
        capture_output=True, text=True, env=env, timeout=600)
    assert r.returncode == 0, (r.stdout, r.stderr)
    line = [l for l in r.stdout.splitlines() if l.startswith("PERL_TRAIN")]
    assert line, r.stdout
    acc = float(line[0].split("acc=")[1])
    assert acc >= 0.9, r.stdout


def test_c_api_imperative_autograd(tmp_path):
    """The imperative + autograd + dtype C ABI tiers (reference
    MXImperativeInvoke, src/c_api/c_api_ndarray.cc:322, and MXAutograd*,
    include/mxnet/c_api.h): a pure-C client runs mx.nd ops on device
    arrays, takes a gradient through the tape, and round-trips a
    bfloat16 tensor bit-exactly across the ABI."""
    import shutil
    import subprocess
    import sys as _sys

    if shutil.which("make") is None or shutil.which("g++") is None:
        pytest.skip("native toolchain unavailable")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(["make", "-C", os.path.join(repo, "native"),
                        "build/imperative_capi_test",
                        "PYTHON=%s" % _sys.executable],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXNET_TPU_PLATFORM="cpu",
               PYTHONPATH=repo + ((os.pathsep + os.environ["PYTHONPATH"])
                                  if os.environ.get("PYTHONPATH") else ""))
    r = subprocess.run(
        [os.path.join(repo, "native", "build", "imperative_capi_test")],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "C_API_IMPERATIVE ok" in r.stdout, r.stdout


def test_generated_cpp_ops_in_sync():
    """The generated C++ op surface (OpWrapperGenerator analog,
    cpp-package/src/OpWrapperGenerator/OpWrapperGenerator.py:1) must
    match a fresh generation from the live registry — registering a new
    op without regenerating fails CI."""
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [_sys.executable, os.path.join(repo, "tools", "gen_cpp_ops.py"),
         "--check"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr


def test_generated_cpp_ops_compile_and_run():
    """Compile + run a C++ client built EXCLUSIVELY from generated
    mxtpu::train::op:: builders (typed attrs, optional-tensor defaults,
    a variable-input Concat, enum string attrs) — executor forward and
    backward included."""
    import shutil
    import subprocess
    import sys as _sys

    if shutil.which("make") is None or shutil.which("g++") is None:
        pytest.skip("native toolchain unavailable")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(["make", "-C", os.path.join(repo, "native"),
                        "build/gen_ops_test", "PYTHON=%s" % _sys.executable],
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXNET_TPU_PLATFORM="cpu",
               PYTHONPATH=repo + ((os.pathsep + os.environ["PYTHONPATH"])
                                  if os.environ.get("PYTHONPATH") else ""))
    r = subprocess.run(
        [os.path.join(repo, "native", "build", "gen_ops_test")],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "GEN_OPS ok" in r.stdout, r.stdout
