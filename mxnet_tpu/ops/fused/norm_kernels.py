"""Fused layernorm / activation epilogues (transformer hot path c).

Three small Pallas kernels that fold the elementwise epilogues XLA
would otherwise schedule as separate HLOs:

* ``LayerNorm``/``fused`` — the registry op (op convention): fp32
  mean/var + ``lax.rsqrt`` + affine in one VMEM pass.  Minor-axis norm
  only; other ``axis`` values delegate to stock inside the variant.
* ``lm_layer_norm``/``fused`` — the LM's ``_lm_ln`` twin
  (``models/transformer.py``): same math spelled with ``jnp.sqrt`` on
  already-fp32 activations, because the generation lane's bitwise gate
  pins that exact spelling.
* ``lm_gelu_bias``/``fused`` — the FFN epilogue ``gelu(h + bias)``.

All three replay stock's op sequence exactly, so they are ``bitwise``
class; the parity harness holds them to byte equality on the CPU
interpret path.  Whole-array single-program kernels: the epilogue
tensors the LM dispatches fit VMEM; a blocked row grid is the TPU-scale
follow-up and changes nothing about the contract.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax import nn as jnn

from ..registry import register_variant
from .parity import register_parity

__all__ = ["fused_layer_norm_op", "fused_lm_layer_norm",
           "fused_lm_gelu_bias"]

_LN_EPS = 1e-5   # transformer.py's _LN_EPS; asserted equal in parity


def _interpret():
    return jax.default_backend() != "tpu"


def _ln_op_kernel(x_ref, g_ref, b_ref, o_ref, *, eps):
    # stock spelling: ops/attention.py _layer_norm (fp32 + lax.rsqrt)
    x = x_ref[...].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps)
    o_ref[...] = (y * g_ref[...].astype(jnp.float32)
                  + b_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def fused_layer_norm_op(attrs, data, gamma, beta):
    """Op-convention variant of the ``LayerNorm`` registry op."""
    import jax.experimental.pallas as pl

    axis = attrs["axis"]
    if axis not in (-1, data.ndim - 1):
        # non-minor axis: the registry op's generality, stock's job
        from .. import attention as _att

        return _att._layer_norm(attrs, data, gamma, beta)
    kernel = functools.partial(_ln_op_kernel, eps=attrs["eps"])
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(data.shape, data.dtype),
        interpret=_interpret(),
    )(data, gamma, beta)


register_variant("LayerNorm", "fused", fused_layer_norm_op,
                 backends=("tpu",), parity="bitwise")


def _lm_ln_kernel(x_ref, g_ref, b_ref, o_ref, *, eps):
    # stock spelling: models/transformer.py _lm_ln (fp32 in, jnp.sqrt)
    x = x_ref[...]
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mean) / jnp.sqrt(var + eps)
    o_ref[...] = y * g_ref[...] + b_ref[...]


def fused_lm_layer_norm(x, gamma, beta):
    """Plain-convention twin of ``transformer._lm_ln`` (fp32 LM path)."""
    import jax.experimental.pallas as pl

    kernel = functools.partial(_lm_ln_kernel, eps=_LN_EPS)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=_interpret(),
    )(x, gamma, beta)


register_variant("lm_layer_norm", "fused", fused_lm_layer_norm,
                 backends=("tpu",), parity="bitwise")


def _gelu_bias_kernel(h_ref, b_ref, o_ref):
    o_ref[...] = jnn.gelu(h_ref[...] + b_ref[...])


def fused_lm_gelu_bias(h, bias):
    """FFN epilogue ``gelu(h + bias)`` in one pass (``_lm_ffn``)."""
    import jax.experimental.pallas as pl

    return pl.pallas_call(
        _gelu_bias_kernel,
        out_shape=jax.ShapeDtypeStruct(h.shape, h.dtype),
        interpret=_interpret(),
    )(h, bias)


register_variant("lm_gelu_bias", "fused", fused_lm_gelu_bias,
                 backends=("tpu",), parity="bitwise")


# ----------------------------------------------------------------------
# parity grids
# ----------------------------------------------------------------------


def _seed(case):
    import zlib

    return zlib.adler32(repr(case).encode())


def _ln_op_case(case):
    import numpy as np

    from .. import attention as _att

    dtype, shape = case
    rng = np.random.default_rng(_seed(case))
    c = shape[-1]
    data = jnp.asarray(rng.standard_normal(shape), jnp.float32) \
        .astype(dtype)
    gamma = jnp.asarray(rng.standard_normal((c,)), jnp.float32)
    beta = jnp.asarray(rng.standard_normal((c,)), jnp.float32)
    attrs = {"axis": -1, "eps": 1e-5}
    stock = functools.partial(_att._layer_norm, attrs)
    fused = functools.partial(fused_layer_norm_op, attrs)
    return stock, fused, (data, gamma, beta)


register_parity(
    "LayerNorm", "fused", _ln_op_case,
    grid=(
        ("float32", (4, 7, 33)),         # ragged minor dim
        ("float32", (2, 128)),
        ("bfloat16", (3, 5, 64)),
        ("float16", (2, 9, 17)),
    ))


def _lm_ln_case(case):
    import numpy as np

    def stock(x, gamma, beta):
        from ...models import transformer as _t

        return _t._lm_ln_stock(x, gamma, beta)

    shape = case
    rng = np.random.default_rng(_seed(case))
    c = shape[-1]
    x = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    gamma = jnp.asarray(rng.standard_normal((c,)), jnp.float32)
    beta = jnp.asarray(rng.standard_normal((c,)), jnp.float32)
    return stock, fused_lm_layer_norm, (x, gamma, beta)


register_parity(
    "lm_layer_norm", "fused", _lm_ln_case,
    grid=((2, 16, 32), (1, 1, 32), (3, 21, 33)))


def _gelu_case(case):
    import numpy as np

    def stock(h, bias):
        from ...models import transformer as _t

        return _t._lm_gelu_bias_stock(h, bias)

    shape = case
    rng = np.random.default_rng(_seed(case))
    f = shape[-1]
    h = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    bias = jnp.asarray(rng.standard_normal((f,)), jnp.float32)
    return stock, fused_lm_gelu_bias, (h, bias)


register_parity(
    "lm_gelu_bias", "fused", _gelu_case,
    grid=((2, 16, 128), (1, 1, 64), (3, 17, 65)))
