"""Kill stray training processes across the cluster (role parity:
reference ``tools/kill-mxnet.py`` — ps|grep|kill over every host in a
hostfile; used by the reference's benchmark sweep to clean up between
configs).

TPU-native form: local mode (the simulated one-host cluster the dist
tests and ``benchmark.py`` use) finds processes by the launcher's
environment markers (``MXNET_TPU_COORDINATOR`` / ``MXNET_TPU_PS_SECRET``
in ``/proc/<pid>/environ``) rather than a fragile ``grep <prog>`` —
matching by env can't kill an unrelated process that merely shares a
script name.  With ``--hostfile``, the same sweep runs over ssh like the
reference.

    python tools/kill_mxnet.py                # local: kill stray workers
    python tools/kill_mxnet.py --dry-run      # list only
    python tools/kill_mxnet.py --hostfile H --prog train_imagenet.py
"""

import argparse
import os
import signal
import subprocess
import sys

_MARKERS = (b"MXNET_TPU_COORDINATOR=", b"MXNET_TPU_PS_SECRET=",
            b"MXNET_TPU_SERVER_ADDR_FILE=")


def find_local(coordinator=None):
    """PIDs (not ours) whose environment carries a launcher marker;
    ``coordinator`` restricts to one cluster's processes (its
    ``MXNET_TPU_COORDINATOR`` value) so killing a stray sweep can never
    take down an unrelated healthy cluster on the same host."""
    skip = set()
    pid = os.getpid()
    # exclude the whole ancestor chain: an operator's shell with an
    # exported marker (e.g. inside a launcher-managed job) must never be
    # a kill target of its own cleanup
    while pid > 1 and pid not in skip:
        skip.add(pid)
        try:
            with open("/proc/%d/stat" % pid) as f:
                pid = int(f.read().rsplit(") ", 1)[1].split()[1])
        except (OSError, IndexError, ValueError):
            break
    out = []
    needles = None
    if coordinator:
        # workers carry MXNET_TPU_COORDINATOR (jax.distributed bootstrap);
        # PS servers carry the inert MXNET_TPU_CLUSTER_ID stamp
        needles = [("MXNET_TPU_COORDINATOR=%s" % coordinator).encode()
                   + b"\0",
                   ("MXNET_TPU_CLUSTER_ID=%s" % coordinator).encode()
                   + b"\0"]
    for pid in os.listdir("/proc"):
        if not pid.isdigit() or int(pid) in skip:
            continue
        try:
            with open("/proc/%s/environ" % pid, "rb") as f:
                env = f.read()
        except OSError:
            continue
        if needles is not None and not any(n in env for n in needles):
            continue
        if any(m in env for m in _MARKERS):
            try:
                with open("/proc/%s/cmdline" % pid, "rb") as f:
                    cmd = f.read().replace(b"\0", b" ").decode(
                        "utf-8", "replace").strip()
            except OSError:
                cmd = "?"
            out.append((int(pid), cmd))
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--hostfile", type=str, default=None,
                    help="kill over ssh on every host (reference mode)")
    ap.add_argument("--prog", type=str, default="mxnet_tpu",
                    help="remote mode: substring to match in ps output")
    ap.add_argument("--coordinator", type=str, default=None,
                    help="only kill processes of the cluster with this "
                         "MXNET_TPU_COORDINATOR value")
    ap.add_argument("--signal", type=int, default=signal.SIGTERM)
    ap.add_argument("--dry-run", action="store_true")
    args = ap.parse_args()

    if args.hostfile:
        import shlex

        if args.coordinator:
            ap.error("--coordinator scoping needs /proc environ access "
                     "and only works in local mode; remote sweeps match "
                     "by --prog name per host")

        # bracket trick ([m]xnet...) so pgrep -f never matches the remote
        # shell running this very pipeline (the reference's grep -v grep);
        # shlex.quote keeps metacharacters in --prog from executing
        pattern = "[%s]%s" % (args.prog[0], args.prog[1:]) \
            if args.prog else args.prog
        kill_cmd = ("pgrep -u \"$USER\" -f %s | xargs -r kill -%d"
                    % (shlex.quote(pattern), args.signal))
        with open(args.hostfile) as f:
            hosts = [h.split(":")[0].strip() for h in f if h.strip()]
        for host in hosts:
            print("%s: %s" % (host, kill_cmd))
            if not args.dry_run:
                subprocess.run(["ssh", "-oStrictHostKeyChecking=no", host,
                                kill_cmd], check=False)
        return 0

    victims = find_local(args.coordinator)
    for pid, cmd in victims:
        print("%s%d  %s" % ("would kill " if args.dry_run else "kill ",
                            pid, cmd[:120]))
        if not args.dry_run:
            try:
                os.kill(pid, args.signal)
            except OSError as exc:
                print("  failed: %s" % exc)
    print("%d process(es)" % len(victims))
    return 0


if __name__ == "__main__":
    sys.exit(main())
