"""Optimizers vs python reference updaters (parity model: reference
``tests/python/unittest/test_optimizer.py``)."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def _run(opt, w0, g, steps=3):
    """Apply `opt` for `steps` steps on a copy of w0 with constant grad g."""
    w = mx.nd.array(w0.copy())
    state = opt.create_state(0, w)
    for _ in range(steps):
        opt.update(0, w, mx.nd.array(g), state)
    return w.asnumpy()


def _prep(g, rescale, clip):
    g = g * rescale
    if clip is not None:
        g = np.clip(g, -clip, clip)
    return g


def test_sgd_matches_numpy():
    w0 = np.random.uniform(-1, 1, (5, 4)).astype(np.float32)
    g = np.random.uniform(-1, 1, (5, 4)).astype(np.float32)
    for momentum in (0.0, 0.9):
        for wd in (0.0, 0.05):
            for clip in (None, 0.1):
                opt = mx.optimizer.SGD(learning_rate=0.1, momentum=momentum,
                                       wd=wd, rescale_grad=0.5,
                                       clip_gradient=clip)
                got = _run(opt, w0, g)
                w = w0.copy()
                mom = np.zeros_like(w)
                for _ in range(3):
                    gg = _prep(g, 0.5, clip)
                    mom = momentum * mom - 0.1 * (gg + wd * w)
                    w = w + mom
                assert_almost_equal(got, w, rtol=1e-5, atol=1e-6)


def test_adam_matches_numpy():
    w0 = np.random.uniform(-1, 1, (4, 3)).astype(np.float32)
    g = np.random.uniform(-1, 1, (4, 3)).astype(np.float32)
    b1, b2, eps = 0.9, 0.999, 1e-8
    opt = mx.optimizer.Adam(learning_rate=0.01, beta1=b1, beta2=b2,
                            epsilon=eps, wd=0.02)
    got = _run(opt, w0, g)
    w = w0.copy()
    mean = np.zeros_like(w)
    var = np.zeros_like(w)
    for t in range(1, 4):
        gg = g + 0.02 * w
        mean = b1 * mean + (1 - b1) * gg
        var = b2 * var + (1 - b2) * gg * gg
        lr = 0.01 * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        w = w - lr * mean / (np.sqrt(var) + eps)
    assert_almost_equal(got, w, rtol=1e-4, atol=1e-6)


def test_rmsprop_matches_numpy():
    w0 = np.random.uniform(-1, 1, (4, 3)).astype(np.float32)
    g = np.random.uniform(-1, 1, (4, 3)).astype(np.float32)
    opt = mx.optimizer.RMSProp(learning_rate=0.01, gamma1=0.95)
    got = _run(opt, w0, g)
    w = w0.copy()
    n = np.zeros_like(w)
    for _ in range(3):
        n = 0.95 * n + 0.05 * g * g
        w = w - 0.01 * g / np.sqrt(n + 1e-8)
    assert_almost_equal(got, w, rtol=1e-4, atol=1e-6)


def test_adagrad_matches_numpy():
    w0 = np.random.uniform(-1, 1, (4,)).astype(np.float32)
    g = np.random.uniform(-1, 1, (4,)).astype(np.float32)
    opt = mx.optimizer.AdaGrad(learning_rate=0.1, eps=1e-7)
    got = _run(opt, w0, g)
    w = w0.copy()
    h = np.zeros_like(w)
    for _ in range(3):
        h = h + g * g
        w = w - 0.1 * g / np.sqrt(h + 1e-7)
    assert_almost_equal(got, w, rtol=1e-4, atol=1e-6)


def test_nag_differs_from_sgd():
    w0 = np.random.uniform(-1, 1, (4,)).astype(np.float32)
    g = np.random.uniform(-1, 1, (4,)).astype(np.float32)
    sgd = _run(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9), w0, g)
    nag = _run(mx.optimizer.NAG(learning_rate=0.1, momentum=0.9), w0, g)
    assert not np.allclose(sgd, nag)


def test_create_by_name_and_registry():
    for name in ("sgd", "adam", "rmsprop", "adagrad", "adadelta", "ftrl",
                 "nag", "sgld", "dcasgd", "test", "ccsgd"):
        opt = mx.optimizer.create(name)
        assert isinstance(opt, mx.optimizer.Optimizer)


def test_lr_wd_mult():
    opt = mx.optimizer.SGD(learning_rate=1.0,
                           param_idx2name={0: "w_weight", 1: "b_bias"}, wd=0.1)
    opt.set_lr_mult({"w_weight": 0.5})
    opt.set_wd_mult({})
    assert opt._get_lr(0) == 0.5
    assert opt._get_lr(1) == 1.0
    # bias gets wd_mult 0 by the _weight/_gamma convention
    assert opt._get_wd(1) == 0.0
    assert abs(opt._get_wd(0) - 0.1) < 1e-12


def test_lr_scheduler_factor():
    sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.5)
    opt = mx.optimizer.SGD(learning_rate=1.0, lr_scheduler=sched)
    w = mx.nd.zeros((2,))
    g = mx.nd.ones((2,))
    lrs = []
    for _ in range(6):
        opt.update(0, w, g, None)
        lrs.append(opt._get_lr(0))
    assert lrs[0] == 1.0
    assert lrs[-1] < lrs[0]


def test_multifactor_scheduler():
    sched = mx.lr_scheduler.MultiFactorScheduler(step=[3, 6], factor=0.1)
    sched.base_lr = 1.0
    assert abs(sched(1) - 1.0) < 1e-9
    assert abs(sched(4) - 0.1) < 1e-9
    assert abs(sched(7) - 0.01) < 1e-9


def test_updater_and_serialization():
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9)
    upd = mx.optimizer.get_updater(opt)
    w = mx.nd.array(np.ones((3,), np.float32))
    g = mx.nd.array(np.full((3,), 0.5, np.float32))
    upd(0, g, w)
    states = upd.get_states()
    upd2 = mx.optimizer.get_updater(mx.optimizer.SGD(learning_rate=0.1,
                                                     momentum=0.9))
    upd2.set_states(states)
    assert 0 in upd2.states
