"""Stochastic depth (parity: reference ``example/stochastic-depth/`` —
``sd_module.py`` StochasticDepthModule + ``sd_mnist.py`` harness).

A residual block whose compute branch is randomly disabled per batch
during training (probability ``death_rate``) and replaced by its
expectation at eval time.  The reference implements this as a
``BaseModule`` composition: compute branch and skip branch are separate
Modules, with a host-side random gate deciding per batch whether the
compute branch runs.  That architecture is *already* TPU-idiomatic —
the gate is data-independent host control flow choosing between two
separately-jitted graphs, so no data-dependent branching ever enters a
traced computation; we keep it, expressed over this framework's Module
API (each branch is a whole-graph fused jit).

Differences from the reference, by design:

- the per-batch random stream is a seeded generator drawn once per
  forward (the reference refills a pool of ``np.random.rand`` samples;
  same distribution, reproducible here),
- eval-time expectation scales the compute branch by ``1 - death_rate``
  exactly as the reference does (``sd_module.py`` ``forward``),
- the chain is assembled with ``SequentialModule(auto_wiring=True)``
  as in ``sd_mnist.py``.

Synthetic oriented-grating digits stand in for MNIST (no-egress env).

    python examples/stochastic_depth.py
"""

import argparse
import logging
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

import mxnet_tpu as mx


class StochasticDepthModule(mx.mod.BaseModule):
    """Two-branch residual module with a random per-batch gate.

    ``symbol_compute`` is the residual (compute) branch; ``symbol_skip``
    the shortcut (identity when None).  During training the compute
    branch is executed with probability ``1 - death_rate`` and its
    output added to the skip path; at eval it always runs, scaled by
    ``1 - death_rate`` (the survival expectation).
    """

    def __init__(self, symbol_compute, symbol_skip=None,
                 data_names=("data",), label_names=None, logger=logging,
                 context=None, death_rate=0.0, seed=0):
        super().__init__(logger=logger)
        context = context if context is not None else mx.cpu()
        self._compute = mx.mod.Module(
            symbol_compute, data_names=data_names,
            label_names=label_names, logger=logger, context=context)
        self._skip = None
        if symbol_skip is not None:
            self._skip = mx.mod.Module(
                symbol_skip, data_names=data_names,
                label_names=label_names, logger=logger, context=context)
        self._open_rate = 1.0 - death_rate
        self._gate_open = True
        self._rng = np.random.RandomState(seed)
        self._outputs = None
        self._input_grads = None
        self.gate_history = []  # per-train-batch gate record (for tests)

    # ---- shape/name plumbing: the compute branch is authoritative ----
    @property
    def data_names(self):
        return self._compute.data_names

    @property
    def output_names(self):
        return self._compute.output_names

    @property
    def data_shapes(self):
        return self._compute.data_shapes

    @property
    def label_shapes(self):
        return self._compute.label_shapes

    @property
    def output_shapes(self):
        return self._compute.output_shapes

    def get_params(self):
        arg, aux = self._compute.get_params()
        if self._skip is not None:
            arg, aux = dict(arg), dict(aux)
            skip_arg, skip_aux = self._skip.get_params()
            if set(arg) & set(skip_arg):
                raise ValueError("branches must not share parameter names")
            arg.update(skip_arg)
            aux.update(skip_aux)
        return arg, aux

    def init_params(self, *args, **kwargs):
        self._compute.init_params(*args, **kwargs)
        if self._skip is not None:
            self._skip.init_params(*args, **kwargs)
        self.params_initialized = True

    def bind(self, *args, **kwargs):
        self._compute.bind(*args, **kwargs)
        if self._skip is not None:
            self._skip.bind(*args, **kwargs)
        self.binded = True
        self.inputs_need_grad = self._compute.inputs_need_grad

    def init_optimizer(self, *args, **kwargs):
        self._compute.init_optimizer(*args, **kwargs)
        if self._skip is not None:
            self._skip.init_optimizer(*args, **kwargs)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self._compute.for_training

        if self._skip is not None:
            self._skip.forward(data_batch, is_train=is_train)
            self._outputs = [o.copy() for o in self._skip.get_outputs()]
        else:
            self._outputs = [d.copy() for d in data_batch.data]

        if is_train:
            self._gate_open = bool(self._rng.rand() < self._open_rate)
            self.gate_history.append(self._gate_open)
            if self._gate_open:
                self._compute.forward(data_batch, is_train=True)
                for out, comp in zip(self._outputs,
                                     self._compute.get_outputs()):
                    out += comp
        else:
            # eval: expectation over the gate
            self._compute.forward(data_batch, is_train=False)
            for out, comp in zip(self._outputs, self._compute.get_outputs()):
                out += self._open_rate * comp

    def get_outputs(self, merge_multi_context=True):
        return self._outputs

    def backward(self, out_grads=None):
        if self._skip is not None:
            self._skip.backward(out_grads=out_grads)
            self._input_grads = [g.copy()
                                 for g in self._skip.get_input_grads()]
        else:
            self._input_grads = [g.copy() for g in out_grads]

        if self._gate_open:
            self._compute.backward(out_grads=out_grads)
            for mine, comp in zip(self._input_grads,
                                  self._compute.get_input_grads()):
                mine += comp

    def get_input_grads(self, merge_multi_context=True):
        return self._input_grads

    def update(self):
        # a closed gate means the compute branch's grad arrays still hold
        # the previous open batch's gradients — applying them would repeat
        # a stale update, so only step the branch that actually ran
        if self._gate_open:
            self._compute.update()
        if self._skip is not None:
            self._skip.update()

    def update_metric(self, eval_metric, labels):
        pass  # interior residual block: no labels

    def install_monitor(self, mon):
        self._compute.install_monitor(mon)
        if self._skip is not None:
            self._skip.install_monitor(mon)


def _conv_bn(name, data, num_filter, with_relu, stride=(1, 1)):
    net = mx.sym.Convolution(data, name=name, num_filter=num_filter,
                             kernel=(3, 3), stride=stride, pad=(1, 1),
                             no_bias=True)
    net = mx.sym.BatchNorm(net, name=name + "_bn", fix_gamma=False,
                           momentum=0.9, eps=2e-5)
    if with_relu:
        net = mx.sym.Activation(net, name=name + "_relu", act_type="relu")
    return net


def build_chain(num_blocks=2, death_rates=(0.3, 0.3), num_filter=8,
                num_classes=4, context=None, seed=0):
    """sd_mnist.py topology: stem conv module, then N stochastic-depth
    residual blocks, then the relu+flatten+softmax head, chained with
    auto-wiring."""
    context = context if context is not None else mx.cpu()
    stem = _conv_bn("conv0", mx.sym.Variable("data"), num_filter,
                    with_relu=True)
    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(stem, label_names=None, context=context))

    sd_blocks = []
    for i in range(num_blocks):
        body = _conv_bn("blk%d_conv0" % i, mx.sym.Variable("data_%d" % i),
                        num_filter, with_relu=True)
        body = _conv_bn("blk%d_conv1" % i, body, num_filter,
                        with_relu=False)
        blk = StochasticDepthModule(
            body, data_names=["data_%d" % i], context=context,
            death_rate=death_rates[i], seed=seed + 101 * i)
        sd_blocks.append(blk)
        seq.add(blk, auto_wiring=True)

    head_in = mx.sym.Variable("data_final")
    head = mx.sym.Activation(head_in, act_type="relu")
    head = mx.sym.FullyConnected(mx.sym.Flatten(head),
                                 num_hidden=num_classes)
    head = mx.sym.SoftmaxOutput(head, name="softmax")
    seq.add(mx.mod.Module(head, data_names=["data_final"], context=context),
            auto_wiring=True, take_labels=True)
    return seq, sd_blocks


def make_data(rng, n, side=16, num_classes=4):
    xs = np.zeros((n, 1, side, side), np.float32)
    ys = rng.randint(0, num_classes, n)
    yy, xx = np.mgrid[0:side, 0:side]
    for i, c in enumerate(ys):
        ang = np.pi / num_classes * c + rng.uniform(-0.08, 0.08)
        wave = np.sin(0.9 * (np.cos(ang) * xx + np.sin(ang) * yy)
                      + rng.uniform(0, 2 * np.pi))
        xs[i, 0] = 0.5 + 0.4 * wave + rng.normal(0, 0.05, (side, side))
    return xs, ys.astype(np.float32)


def run(epochs=8, batch=50, death_rate=0.3, seed=0, log=True):
    rng = np.random.RandomState(seed)
    np.random.seed(seed + 1)
    xs, ys = make_data(rng, 600)
    xv, yv = make_data(rng, 200)

    seq, blocks = build_chain(death_rates=(death_rate, death_rate),
                              seed=seed)
    train = mx.io.NDArrayIter({"data": xs}, {"softmax_label": ys},
                              batch_size=batch, shuffle=False)
    val = mx.io.NDArrayIter({"data": xv}, {"softmax_label": yv},
                            batch_size=batch, shuffle=False)
    metric = mx.metric.Accuracy()
    seq.fit(train, num_epoch=epochs, optimizer="adam",
            optimizer_params={"learning_rate": 2e-3},
            initializer=mx.initializer.Xavier(), eval_metric=metric)
    metric.reset()
    seq.score(val, metric)
    _, val_acc = metric.get()

    gates = np.concatenate([np.asarray(b.gate_history, bool)
                            for b in blocks])
    closed_frac = 1.0 - gates.mean() if gates.size else 0.0
    if log:
        logging.info("val_acc=%.3f gate_closed_frac=%.3f",
                     val_acc, closed_frac)
    return {"val_acc": val_acc, "closed_frac": closed_frac,
            "n_gate_draws": float(gates.size)}


def main():
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--death-rate", type=float, default=0.3)
    args = p.parse_args()
    stats = run(epochs=args.epochs, death_rate=args.death_rate)
    print("stochastic_depth: val_acc=%.3f closed_frac=%.3f"
          % (stats["val_acc"], stats["closed_frac"]))


if __name__ == "__main__":
    main()
