"""SLO error budgets and multi-window burn-rate alerting.

PR-8's serving SLO enforcement was a single static p99 threshold rule
(``request_p99_slo``).  This module upgrades the serving tier to real
**error budgets** (the SRE-workbook model): a declarative
:class:`SLO` states an objective over a window — "99.9% of requests
answered" (availability), "99% of requests under 500 ms" (latency) —
and budget consumption is *computed from the metrics the tier already
emits* (``serving_requests_total``, ``serving_rejected_total``, the
``serving_request_seconds`` histogram), never double-counted by new
instrumentation.

Two consumption surfaces:

- :func:`report` — the ``/slo`` JSON (``exporters.start_metrics_
  server``) and ``tools/slo_report.py``: per-SLO good/bad totals,
  error rate, and the fraction of error budget remaining (negative =
  exhausted).  Also sets ``slo_error_budget_remaining{slo, tenant}``
  so the budget itself federates like any gauge — ``tenant="all"`` is
  the aggregate, and the availability SLO gets one row per tenant
  (PR-16) so a quota-saturating tenant's exhausted budget never masks
  an innocent tenant's healthy one.
- :func:`burn_rules` — multi-window **burn-rate** rules registered
  into :func:`~.watchdog.default_rules`: for each SLO a *fast* window
  (default 5 min, threshold 14.4× — the classic "2% of a 30-day
  budget in one hour" page) at ``severity="terminal"`` (rising edge →
  exactly one flight-recorder bundle) and a *slow* window (default
  1 h, threshold 6×) at warning.  Burn rate is
  ``(Δbad / Δtotal) / (1 - objective)`` over the trailing window — 1×
  means "consuming exactly the budget", sustained >1× means the
  budget dies before the window does.  The fast-burn rule names are in
  the autoscaler's default ``WATCHED_RULES``: a sustained fast burn
  drives a scale-up.

Burn rules ride the stock :class:`~.watchdog.Watchdog` machinery via
the ``value_fn`` seam (the rule computes its quantity from the parsed
exposition itself), so they evaluate identically over the local
registry or a :class:`~.federation.FederatedCollector` — a
cluster-wide error budget needs no extra code.  Thresholds and
windows come from the ``MXNET_TPU_SLO_*`` env rows (docs/env_vars.md).
With ``MXNET_TPU_METRICS=0`` :func:`report` returns an empty report
without parsing anything — the standard constant-time guard.
"""

from __future__ import annotations

import os

from . import federation as _federation
from . import metrics as _metrics
from . import watchdog as _watchdog

__all__ = ["SLO", "BurnRateRule", "default_slos", "burn_rules",
           "report", "FAST_BURN_RULES"]

_M_BUDGET = _metrics.gauge(
    "slo_error_budget_remaining",
    "Fraction of the SLO's error budget left (1 = untouched, <=0 = "
    "exhausted); tenant=\"all\" is the aggregate, per-tenant rows "
    "cover availability", ["slo", "tenant"])
_M_BURN = _metrics.gauge(
    "slo_burn_rate",
    "Error-budget burn rate over the trailing window (1 = consuming "
    "exactly the budget)", ["slo", "window"])


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return float(default)


class SLO(object):
    """One declarative objective over a window.

    ``kind="availability"``: ``objective`` is the fraction of requests
    that must be answered (good = ``serving_requests_total``, bad =
    ``serving_rejected_total``).  ``kind="latency"``: ``objective`` is
    the fraction that must finish under ``threshold_s`` (good/bad from
    the ``serving_request_seconds`` buckets).  ``window_s`` is the
    budget window burn rates are normalized against."""

    def __init__(self, name, objective, window_s=3600.0,
                 kind="availability", threshold_s=None):
        if not 0.0 < float(objective) < 1.0:
            raise ValueError("objective must be in (0, 1), got %r"
                             % (objective,))
        if kind not in ("availability", "latency"):
            raise ValueError("kind must be availability|latency, got %r"
                             % (kind,))
        self.name = str(name)
        self.objective = float(objective)
        self.window_s = float(window_s)
        self.kind = kind
        self.threshold_s = (None if threshold_s is None
                            else float(threshold_s))
        if kind == "latency" and self.threshold_s is None:
            self.threshold_s = _env_float(
                "MXNET_TPU_SLO_LATENCY_THRESHOLD_S", 0.5)

    @property
    def budget(self):
        """The error budget: the tolerated bad fraction."""
        return 1.0 - self.objective

    # -- counting from parsed exposition ------------------------------

    def counts(self, fams):
        """``(good, bad)`` cumulative totals from parsed exposition
        ``fams`` (``federation._parse``), or ``None`` when the serving
        tier has emitted nothing yet."""
        if self.kind == "availability":
            good = self._sum(fams, "serving_requests_total")
            bad = self._sum(fams, "serving_rejected_total")
            if good is None and bad is None:
                return None
            return (good or 0.0, bad or 0.0)
        return self._latency_counts(fams)

    @staticmethod
    def _sum(fams, metric, suffix="", selector=None):
        fam = fams.get(metric)
        if fam is None:
            return None
        vals = [v for _, v in _watchdog._matching(fam, metric, selector,
                                                  suffix)]
        return sum(vals) if vals else None

    def tenant_counts(self, fams, tenant):
        """``(good, bad)`` for one tenant (availability only: good =
        ``serving_tenant_requests_total``, bad = the tenant's rows of
        ``serving_rejected_total``), or ``None`` when the tenant has no
        samples."""
        if self.kind != "availability":
            return None
        sel = {"tenant": tenant}
        good = self._sum(fams, "serving_tenant_requests_total",
                         selector=sel)
        bad = self._sum(fams, "serving_rejected_total", selector=sel)
        if good is None and bad is None:
            return None
        return (good or 0.0, bad or 0.0)

    def _latency_counts(self, fams):
        # untyped exposition (no ``# TYPE`` line) groups the bucket
        # samples under the sample name rather than the family name
        fam = (fams.get("serving_request_seconds")
               or fams.get("serving_request_seconds_bucket"))
        if fam is None:
            return None
        cum = {}
        for ld, v in _watchdog._matching(fam, "serving_request_seconds",
                                         None, "_bucket"):
            le = ld.get("le", "")
            try:
                ub = float("inf") if le == "+Inf" else float(le)
            except ValueError:
                continue
            cum[ub] = cum.get(ub, 0.0) + v
        if not cum:
            return None
        total = cum[max(cum)]
        under = 0.0
        for ub in sorted(cum):
            if ub >= self.threshold_s:
                under = cum[ub]
                break
        else:
            under = total
        return (under, max(total - under, 0.0))

    def _budget_row(self, counts):
        good, bad = counts if counts is not None else (0.0, 0.0)
        total = good + bad
        error_rate = (bad / total) if total else 0.0
        consumed = error_rate / self.budget if self.budget else 0.0
        return good, bad, total, error_rate, consumed

    def snapshot(self, fams):
        """The ``/slo`` row: totals, error rate, budget remaining."""
        counts = self.counts(fams)
        good, bad = counts if counts is not None else (0.0, 0.0)
        total = good + bad
        error_rate = (bad / total) if total else 0.0
        consumed = error_rate / self.budget if self.budget else 0.0
        row = {
            "slo": self.name, "kind": self.kind,
            "objective": self.objective, "window_s": self.window_s,
            "good": good, "bad": bad, "total": total,
            "error_rate": round(error_rate, 6),
            "budget": round(self.budget, 6),
            "budget_consumed": round(consumed, 6),
            "budget_remaining": round(1.0 - consumed, 6),
            "exhausted": bool(total and consumed >= 1.0),
        }
        if self.kind == "latency":
            row["threshold_s"] = self.threshold_s
        return row


class BurnRateRule(_watchdog.Rule):
    """A watchdog rule whose quantity is an SLO's burn rate over the
    trailing ``window_s``: ``(Δbad / Δtotal) / budget``.  Uses the
    ``value_fn`` seam — the rule derives (good, bad) from the parsed
    exposition itself, then delegates the threshold/sustain/edge logic
    to the stock :class:`~.watchdog.Rule` machinery."""

    def __init__(self, name, slo, window_name, *, window_s, threshold,
                 severity, description=""):
        super().__init__(
            name, "serving_requests_total", stat="value", op=">=",
            threshold=threshold, kind="threshold", window_s=window_s,
            severity=severity, description=description)
        self.slo = slo
        self.window_name = window_name
        self.value_fn = self._burn_rate
        self._counts = []        # [(t, good, bad)] within window_s
        self._m_burn = _M_BURN.labels(slo.name, window_name)

    def _burn_rate(self, fams):
        # called by Watchdog.evaluate with the parsed scrape; time is
        # injected through update(), so stamp samples there
        self._pending = self.slo.counts(fams)
        return self._pending

    def update(self, raw, now):
        if raw is not None:
            good, bad = raw
            self._counts = [(t, g, b) for t, g, b in self._counts
                            if now - t <= self.window_s]
            if self._counts and (good < self._counts[0][1]
                                 or bad < self._counts[0][2]):
                # counters went backwards (registry reset): restart
                self._counts = []
            base = self._counts[0] if self._counts else (now, good, bad)
            self._counts.append((now, good, bad))
            d_total = (good + bad) - (base[1] + base[2])
            d_bad = bad - base[2]
            if d_total <= 0:
                raw = None           # no traffic in window: no burn
            else:
                raw = (d_bad / d_total) / self.slo.budget
                self._m_burn.set(raw)
        return super().update(raw, now)


def default_slos():
    """The stock SLO pair from the ``MXNET_TPU_SLO_*`` env rows:
    availability (default 99.9%) and latency (default 99% under
    ``MXNET_TPU_SLO_LATENCY_THRESHOLD_S``)."""
    window = _env_float("MXNET_TPU_SLO_WINDOW_S", 3600.0)
    return [
        SLO("availability",
            _env_float("MXNET_TPU_SLO_AVAILABILITY", 0.999),
            window_s=window, kind="availability"),
        SLO("latency", _env_float("MXNET_TPU_SLO_LATENCY", 0.99),
            window_s=window, kind="latency"),
    ]


#: The burn-rule names that mean "the error budget is dying fast" —
#: grown into the autoscaler's default ``WATCHED_RULES``.
FAST_BURN_RULES = ("slo_availability_fast_burn", "slo_latency_fast_burn")


def burn_rules(slos=None):
    """Fast + slow burn-rate rules for every SLO (registered into
    :func:`~.watchdog.default_rules`).  Fast: trailing
    ``MXNET_TPU_SLO_FAST_WINDOW_S`` (default 5 min) vs
    ``MXNET_TPU_SLO_FAST_BURN`` (default 14.4×), terminal — the rising
    edge dumps exactly one flight bundle.  Slow: trailing
    ``MXNET_TPU_SLO_SLOW_WINDOW_S`` (default 1 h) vs
    ``MXNET_TPU_SLO_SLOW_BURN`` (default 6×), warning."""
    fast_w = _env_float("MXNET_TPU_SLO_FAST_WINDOW_S", 300.0)
    slow_w = _env_float("MXNET_TPU_SLO_SLOW_WINDOW_S", 3600.0)
    fast_t = _env_float("MXNET_TPU_SLO_FAST_BURN", 14.4)
    slow_t = _env_float("MXNET_TPU_SLO_SLOW_BURN", 6.0)
    rules = []
    for slo in (slos if slos is not None else default_slos()):
        rules.append(BurnRateRule(
            "slo_%s_fast_burn" % slo.name, slo, "fast",
            window_s=fast_w, threshold=fast_t, severity="terminal",
            description="the %s error budget is burning >= %gx over "
                        "the fast window — at this rate it exhausts "
                        "in %.0fs" % (slo.name, fast_t,
                                      slo.window_s / fast_t)))
        rules.append(BurnRateRule(
            "slo_%s_slow_burn" % slo.name, slo, "slow",
            window_s=slow_w, threshold=slow_t, severity="warning",
            description="the %s error budget is burning >= %gx over "
                        "the slow window" % (slo.name, slow_t)))
    return rules


def _tenants_in(fams):
    """Tenant label values present in the per-tenant serving counters."""
    tenants = set()
    for metric in ("serving_tenant_requests_total",
                   "serving_rejected_total"):
        fam = fams.get(metric)
        if fam is None:
            continue
        for ld, _ in _watchdog._matching(fam, metric, None, ""):
            t = ld.get("tenant")
            if t:
                tenants.add(t)
    return sorted(tenants)


def report(source=None, slos=None):
    """The ``/slo`` payload: one row per SLO (see
    :meth:`SLO.snapshot`), computed from ``source`` — ``None`` (the
    process-global registry), anything with ``render()``, or raw
    exposition text.  Sets ``slo_error_budget_remaining{slo, tenant}``:
    ``tenant="all"`` is the aggregate every dashboard already reads;
    availability additionally gets one row per tenant seen in the
    per-tenant serving counters, so a saturating tenant's dead budget
    never hides an innocent tenant's healthy one.  An empty report (no
    parsing) when metrics are disabled."""
    if not _metrics.metrics_enabled():
        return {"slos": [], "disabled": True}
    if source is None:
        text = _metrics.REGISTRY.render()
    elif callable(getattr(source, "render", None)):
        text = source.render()
    else:
        text = str(source)
    fams = _federation._parse(text)
    tenants = _tenants_in(fams)
    rows = []
    for slo in (slos if slos is not None else default_slos()):
        row = slo.snapshot(fams)
        _M_BUDGET.labels(slo.name, "all").set(row["budget_remaining"])
        if slo.kind == "availability" and tenants:
            per_tenant = {}
            for tenant in tenants:
                counts = slo.tenant_counts(fams, tenant)
                if counts is None:
                    continue
                _, _, total, _, consumed = slo._budget_row(counts)
                remaining = round(1.0 - consumed, 6)
                _M_BUDGET.labels(slo.name, tenant).set(remaining)
                per_tenant[tenant] = {
                    "total": total, "budget_remaining": remaining,
                    "exhausted": bool(total and consumed >= 1.0)}
            if per_tenant:
                row["tenants"] = per_tenant
        rows.append(row)
    return {"slos": rows}
