"""Fused-kernel operator tier (ISSUE 19): Pallas / hand-fused variants
behind the ``ops.registry`` dispatch seam, each with a falsifiable
stock twin in :mod:`.parity`.

Importing this package registers every shipped variant (the kernel
modules call :func:`~mxnet_tpu.ops.registry.register_variant` +
:func:`.parity.register_parity` at import time); ``mxnet_tpu.ops``
imports it last, after the stock op modules it shadows.  Selection
semantics — kill-switch, per-op override, backend eligibility,
fallback-once — live in ``ops/registry.py``; see
``docs/how_to/kernels.md`` for the variant model and how to add one.
"""

from . import parity                               # noqa: F401
from . import attention_kernels                    # noqa: F401
from . import norm_kernels                         # noqa: F401
from . import optimizer_kernels                    # noqa: F401
from .parity import register_parity, run_parity    # noqa: F401

__all__ = ["parity", "register_parity", "run_parity",
           "attention_kernels", "norm_kernels", "optimizer_kernels"]
