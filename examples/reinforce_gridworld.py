"""Policy-gradient RL (parity: reference ``example/reinforcement-learning/``
— policy network trained with REINFORCE; no gym dependency, the
environment is an in-file 5x5 gridworld).

The agent starts at a random cell and must reach the goal corner within
a step budget; the policy net (MLP over one-hot position) is trained
with the REINFORCE gradient computed through ``mx.contrib.autograd``
(the imperative tape — the surface the reference's RL examples drive).

    python examples/reinforce_gridworld.py [--episodes 1500]
"""

import argparse
import logging
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

import mxnet_tpu as mx
from mxnet_tpu.contrib import autograd as ag

N = 5              # grid side
GOAL = (N - 1, N - 1)
MAX_STEPS = 2 * N  # step budget per episode
ACTIONS = [(-1, 0), (1, 0), (0, -1), (0, 1)]  # up/down/left/right


def _state_vec(pos):
    v = np.zeros((1, N * N), np.float32)
    v[0, pos[0] * N + pos[1]] = 1.0
    return v


def _step(pos, a):
    dr, dc = ACTIONS[a]
    nr = min(max(pos[0] + dr, 0), N - 1)
    nc = min(max(pos[1] + dc, 0), N - 1)
    return (nr, nc)


class Policy:
    """Two-layer softmax policy; params + grad buffers on the tape."""

    def __init__(self, rng, hidden=32):
        def mk(shape, scale):
            return mx.nd.array(rng.randn(*shape).astype(np.float32) * scale)

        self.params = [mk((N * N, hidden), 0.3), mk((1, hidden), 0.0),
                       mk((hidden, len(ACTIONS)), 0.3),
                       mk((1, len(ACTIONS)), 0.0)]
        self.grads = [mx.nd.zeros(p.shape) for p in self.params]
        ag.mark_variables(self.params, self.grads)

    def logits(self, x):
        w1, b1, w2, b2 = self.params
        h = mx.nd.tanh(mx.nd.broadcast_add(mx.nd.dot(x, w1), b1))
        return mx.nd.broadcast_add(mx.nd.dot(h, w2), b2)

    def probs_np(self, x):
        z = self.logits(mx.nd.array(x)).asnumpy()
        e = np.exp(z - z.max(axis=1, keepdims=True))
        return e / e.sum(axis=1, keepdims=True)

    def sgd(self, lr):
        for p, g in zip(self.params, self.grads):
            p[:] = p.asnumpy() - lr * g.asnumpy()


def run(episodes=1500, lr=0.05, gamma=0.95, seed=0, log=True):
    rng = np.random.RandomState(seed)
    pol = Policy(rng)
    success_window = []
    rate = 0.0
    ep = 0

    for ep in range(episodes):
        pos = (rng.randint(N), rng.randint(N))
        states, actions, rewards = [], [], []
        for _ in range(MAX_STEPS):
            sv = _state_vec(pos)
            a = int(rng.choice(len(ACTIONS), p=pol.probs_np(sv)[0]))
            nxt = _step(pos, a)
            states.append(sv)
            actions.append(a)
            rewards.append(1.0 if nxt == GOAL else -0.02)
            pos = nxt
            if pos == GOAL:
                break
        success_window.append(1.0 if pos == GOAL else 0.0)

        # discounted returns -> REINFORCE loss = -sum G_t log pi(a_t|s_t)
        G, returns = 0.0, []
        for r in reversed(rewards):
            G = r + gamma * G
            returns.append(G)
        returns = np.array(returns[::-1], np.float32)
        returns = returns - returns.mean()  # variance-reducing baseline

        X = np.concatenate(states, axis=0)
        weights = np.zeros((len(actions), len(ACTIONS)), np.float32)
        weights[np.arange(len(actions)), actions] = returns

        with ag.train_section():
            z = pol.logits(mx.nd.array(X))
            logp = mx.nd.log_softmax(z, axis=1)
            loss = mx.nd.sum(-logp * mx.nd.array(weights))
            ag.compute_gradient([loss])
        pol.sgd(lr)

        if len(success_window) >= 100:
            rate = float(np.mean(success_window[-100:]))
            if log and ep % 200 == 0:
                logging.info("episode %d: success_rate(100)=%.2f", ep, rate)
            if rate > 0.95:
                break
    return {"success_rate": rate, "episodes": ep + 1}


def main():
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser(description="REINFORCE gridworld")
    p.add_argument("--episodes", type=int, default=1500)
    args = p.parse_args()
    stats = run(episodes=args.episodes)
    print("final:", stats)
    assert stats["success_rate"] > 0.9, stats


if __name__ == "__main__":
    main()
