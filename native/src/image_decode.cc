/*!
 * Parallel JPEG decode + augment pipeline (parity: reference
 * ``src/io/iter_image_recordio_2.cc:104-112,296`` — OMP-parallel decode
 * inside the iterator).  N worker threads pull raw records from the
 * threaded sharded loader (recordio.cc, already multi-consumer-safe),
 * decode JPEG with libjpeg (DCT-scaled: the IDCT runs at 1/2, 1/4 or 1/8
 * resolution when the target is much smaller than the source — most of
 * the decode win on large photos), bilinear-resize, crop (center or
 * random), optionally mirror, and emit fixed-size uint8 HWC samples into
 * a bounded queue.  The GIL is never involved: Python only memcpy's
 * finished batches.
 *
 * Non-JPEG payloads (PNG / raw npy) are counted + skipped; the Python
 * binding probes the first record and falls back to the PIL path for
 * non-JPEG datasets.
 */
#include <cstddef>
#include <cstdio>  /* jpeglib.h needs size_t/FILE declared first */

#include <jpeglib.h>
#include <setjmp.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <thread>
#include <vector>

#include "mxtpu/c_api.h"

namespace mxtpu_decode {
namespace {

/* ---- libjpeg with longjmp error recovery (corrupt records must not
 * abort the process) ---- */

struct JpegErr {
  jpeg_error_mgr pub;
  jmp_buf jb;
};

void JpegErrExit(j_common_ptr cinfo) {
  longjmp(reinterpret_cast<JpegErr *>(cinfo->err)->jb, 1);
}

/* Decode JPEG bytes to RGB; uses DCT scaling so the output is the
 * smallest libjpeg size whose shorter edge still >= min_edge (0 = full
 * size).  Returns false on corrupt/non-JPEG data. */
bool DecodeJpeg(const uint8_t *buf, size_t len, int min_edge,
                std::vector<uint8_t> *out, int *w, int *h) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = JpegErrExit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  if (min_edge > 0) {
    int shorter = std::min(cinfo.image_width, cinfo.image_height);
    int denom = 1;
    while (denom < 8 && shorter / (denom * 2) >= min_edge) denom *= 2;
    cinfo.scale_num = 1;
    cinfo.scale_denom = denom;
  }
  jpeg_start_decompress(&cinfo);
  *w = static_cast<int>(cinfo.output_width);
  *h = static_cast<int>(cinfo.output_height);
  out->resize(static_cast<size_t>(*w) * *h * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = out->data() + static_cast<size_t>(cinfo.output_scanline) *
                                     *w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

/* Bilinear RGB resize (uint8). */
void Resize(const std::vector<uint8_t> &src, int sw, int sh,
            std::vector<uint8_t> *dst, int dw, int dh) {
  dst->resize(static_cast<size_t>(dw) * dh * 3);
  const float sx = static_cast<float>(sw) / dw;
  const float sy = static_cast<float>(sh) / dh;
  for (int y = 0; y < dh; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    int y0 = std::max(0, std::min(sh - 1, static_cast<int>(fy)));
    int y1 = std::min(sh - 1, y0 + 1);
    float wy = fy - y0;
    if (wy < 0) wy = 0;
    for (int x = 0; x < dw; ++x) {
      float fx = (x + 0.5f) * sx - 0.5f;
      int x0 = std::max(0, std::min(sw - 1, static_cast<int>(fx)));
      int x1 = std::min(sw - 1, x0 + 1);
      float wx = fx - x0;
      if (wx < 0) wx = 0;
      for (int c = 0; c < 3; ++c) {
        float v00 = src[(static_cast<size_t>(y0) * sw + x0) * 3 + c];
        float v01 = src[(static_cast<size_t>(y0) * sw + x1) * 3 + c];
        float v10 = src[(static_cast<size_t>(y1) * sw + x0) * 3 + c];
        float v11 = src[(static_cast<size_t>(y1) * sw + x1) * 3 + c];
        float v = v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                  v10 * wy * (1 - wx) + v11 * wy * wx;
        (*dst)[(static_cast<size_t>(y) * dw + x) * 3 + c] =
            static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}

struct Sample {
  std::vector<uint8_t> px;  // out_h * out_w * 3, HWC RGB
  float label = 0.f;
  bool ok = false;  // false = undecodable record (consumer skips it)
};

struct DecodeLoader {
  void *loader = nullptr;
  int out_h, out_w, resize_shorter;
  bool rand_crop, rand_mirror;
  unsigned seed;
  int n_workers;
  size_t queue_size;

  std::vector<std::thread> workers;
  std::mutex m;
  std::condition_variable cv_prod, cv_cons;
  /* Reorder buffer keyed by record ticket: workers finish out of
   * order, but the consumer drains tickets IN ORDER, so batch content is
   * deterministic for any worker count (the reference's OMP decode is
   * per-batch-deterministic the same way). */
  std::map<long, Sample> done;
  long next_ticket = 0;  // next record ticket to hand to a worker
  long next_out = 0;     // next ticket the consumer will emit
  std::mutex pop_m;      // serializes record pop + ticket assignment
  int active = 0;        // workers still running
  bool stopping = false;
  std::atomic<long> skipped{0};  // undecodable / non-JPEG records
  unsigned epoch = 0;

  DecodeLoader(void *ld, int nw, int oh, int ow, int rs, bool rc, bool rm,
               unsigned sd, size_t qs)
      : loader(ld), out_h(oh), out_w(ow), resize_shorter(rs), rand_crop(rc),
        rand_mirror(rm), seed(sd), n_workers(nw < 1 ? 1 : nw),
        queue_size(qs < 1 ? 64 : qs) {
    Start();
  }

  ~DecodeLoader() {
    Stop();
    mxtpu_loader_free(loader);
  }

  void Start() {
    stopping = false;
    active = n_workers;
    for (int i = 0; i < n_workers; ++i)
      workers.emplace_back([this, i] { Run(i); });
  }

  void Stop() {
    {
      std::lock_guard<std::mutex> lk(m);
      stopping = true;
    }
    cv_prod.notify_all();
    cv_cons.notify_all();
    for (auto &t : workers)
      if (t.joinable()) t.join();
    workers.clear();
  }

  void Run(int worker_id) {
    (void)worker_id;
    std::vector<uint8_t> decoded, resized;
    for (;;) {
      {
        std::lock_guard<std::mutex> lk(m);
        if (stopping) break;
      }
      char *rec = nullptr;
      size_t len = 0;
      long ticket;
      {
        // pop + ticket must be one atomic step: ticket order IS record
        // order, which the reorder buffer restores at the consumer
        std::lock_guard<std::mutex> lk(pop_m);
        int r = mxtpu_loader_next(loader, &rec, &len);
        if (r <= 0) break;  // eof or error: this worker retires
        ticket = next_ticket++;
      }
      // crop/mirror draws are a stateless function of (seed, epoch,
      // ticket): augmentation is bit-reproducible no matter which worker
      // handles which record or in what order
      uint64_t rng = (seed + 1) * 0x9E3779B97F4A7C15ull ^
                     (static_cast<uint64_t>(epoch) * 0xBF58476D1CE4E5B9ull) ^
                     (static_cast<uint64_t>(ticket) + 0x94D049BB133111EBull);
      auto next_u32 = [&rng]() {
        rng ^= rng << 13;
        rng ^= rng >> 7;
        rng ^= rng << 17;
        return static_cast<uint32_t>(rng >> 32);
      };
      Sample s;
      if (ParseAndDecode(reinterpret_cast<uint8_t *>(rec), len, &decoded,
                         &resized, next_u32, &s)) {
        s.ok = true;
      } else {
        skipped.fetch_add(1, std::memory_order_relaxed);
      }
      mxtpu_buf_free(rec);
      {
        std::unique_lock<std::mutex> lk(m);
        // always admit tickets within the window ahead of the consumer
        // (worker spread <= n_workers <= queue_size), else a full buffer
        // of later tickets could deadlock the one the consumer awaits
        cv_prod.wait(lk, [&] {
          return stopping || done.size() < queue_size ||
                 ticket < next_out + static_cast<long>(queue_size);
        });
        if (stopping) break;
        done.emplace(ticket, std::move(s));
        cv_cons.notify_all();
      }
    }
    std::lock_guard<std::mutex> lk(m);
    if (--active == 0) cv_cons.notify_all();
  }

  template <typename Rng>
  bool ParseAndDecode(const uint8_t *rec, size_t len,
                      std::vector<uint8_t> *decoded,
                      std::vector<uint8_t> *resized, Rng &&next_u32,
                      Sample *out) {
    // IRHeader: <IfQQ = flag u32, label f32, id u64, id2 u64 (24 bytes);
    // flag>0 means `flag` float labels precede the image payload
    // (mxnet_tpu/recordio.py pack/unpack framing)
    if (len < 24) return false;
    uint32_t flag;
    float label;
    std::memcpy(&flag, rec, 4);
    std::memcpy(&label, rec + 4, 4);
    size_t off = 24;
    if (flag > 0) {
      if (len < off + static_cast<size_t>(flag) * 4) return false;
      std::memcpy(&label, rec + off, 4);  // first label float
      off += static_cast<size_t>(flag) * 4;
    }
    const uint8_t *img = rec + off;
    size_t img_len = len - off;
    if (img_len < 2 || img[0] != 0xFF || img[1] != 0xD8) return false;

    int w = 0, h = 0;
    int min_edge = resize_shorter > 0 ? resize_shorter
                                      : std::max(out_h, out_w);
    if (!DecodeJpeg(img, img_len, min_edge, decoded, &w, &h)) return false;

    // resize: shorter edge to resize_shorter, or just enough to crop
    const std::vector<uint8_t> *src = decoded;
    int target_short = resize_shorter;
    if (target_short <= 0 && (w < out_w || h < out_h))
      target_short = std::max(out_w, out_h);
    if (target_short > 0 && std::min(w, h) != target_short) {
      int nw, nh;
      if (w < h) {
        nw = target_short;
        nh = std::max(out_h, static_cast<int>(
                                 1.0 * h * target_short / w + 0.5));
      } else {
        nh = target_short;
        nw = std::max(out_w, static_cast<int>(
                                 1.0 * w * target_short / h + 0.5));
      }
      Resize(*decoded, w, h, resized, nw, nh);
      src = resized;
      w = nw;
      h = nh;
    }
    if (w < out_w || h < out_h) return false;

    // crop
    int x0 = (w - out_w) / 2, y0 = (h - out_h) / 2;
    if (rand_crop) {
      x0 = w == out_w ? 0 : static_cast<int>(next_u32() % (w - out_w + 1));
      y0 = h == out_h ? 0 : static_cast<int>(next_u32() % (h - out_h + 1));
    }
    bool mirror = rand_mirror && (next_u32() & 1);
    out->px.resize(static_cast<size_t>(out_h) * out_w * 3);
    for (int y = 0; y < out_h; ++y) {
      const uint8_t *row =
          src->data() + ((static_cast<size_t>(y0) + y) * w + x0) * 3;
      uint8_t *dst = out->px.data() + static_cast<size_t>(y) * out_w * 3;
      if (!mirror) {
        std::memcpy(dst, row, static_cast<size_t>(out_w) * 3);
      } else {
        for (int x = 0; x < out_w; ++x) {
          const uint8_t *p = row + (out_w - 1 - x) * 3;
          dst[x * 3] = p[0];
          dst[x * 3 + 1] = p[1];
          dst[x * 3 + 2] = p[2];
        }
      }
    }
    out->label = label;
    return true;
  }

  int NextBatch(int max_n, unsigned char *data, float *labels) {
    std::vector<Sample> grabbed;
    {
      std::unique_lock<std::mutex> lk(m);
      while (static_cast<int>(grabbed.size()) < max_n) {
        // wait for the IN-ORDER next ticket (not just any finished one)
        cv_cons.wait(lk, [this] {
          return done.count(next_out) || active == 0 || stopping;
        });
        auto it = done.find(next_out);
        if (it == done.end()) break;  // workers retired: epoch end
        Sample s = std::move(it->second);
        done.erase(it);
        ++next_out;
        cv_prod.notify_all();
        if (s.ok) grabbed.push_back(std::move(s));
        // !ok (undecodable) slots are skipped without counting
      }
      if (grabbed.empty()) return 0;
    }
    size_t stride = static_cast<size_t>(out_h) * out_w * 3;
    for (size_t i = 0; i < grabbed.size(); ++i) {
      std::memcpy(data + i * stride, grabbed[i].px.data(), stride);
      labels[i] = grabbed[i].label;
    }
    return static_cast<int>(grabbed.size());
  }

  void Reset() {
    Stop();
    {
      std::lock_guard<std::mutex> lk(m);
      done.clear();
      next_ticket = 0;
      next_out = 0;
      ++epoch;
    }
    mxtpu_loader_reset(loader);
    Start();
  }
};

}  // namespace
}  // namespace mxtpu_decode

extern "C" {

void *mxtpu_decode_loader_create(const char *path, int part_index,
                                 int num_parts, int shuffle, unsigned seed,
                                 int queue_size, int shuffle_chunk,
                                 int n_workers, int out_h, int out_w,
                                 int resize_shorter, int rand_crop,
                                 int rand_mirror) {
  void *loader = mxtpu_loader_create(path, part_index, num_parts, shuffle,
                                     seed, queue_size, shuffle_chunk);
  if (!loader) return nullptr;
  return new ::mxtpu_decode::DecodeLoader(
      loader, n_workers, out_h, out_w, resize_shorter, rand_crop != 0,
      rand_mirror != 0, seed, static_cast<size_t>(queue_size));
}

int mxtpu_decode_loader_next_batch(void *h, int max_n, unsigned char *data,
                                   float *labels) {
  return static_cast<::mxtpu_decode::DecodeLoader *>(h)->NextBatch(
      max_n, data, labels);
}

long mxtpu_decode_loader_skipped(void *h) {
  return static_cast<::mxtpu_decode::DecodeLoader *>(h)->skipped.load();
}

void mxtpu_decode_loader_reset(void *h) {
  static_cast<::mxtpu_decode::DecodeLoader *>(h)->Reset();
}

void mxtpu_decode_loader_free(void *h) {
  delete static_cast<::mxtpu_decode::DecodeLoader *>(h);
}

}  // extern "C"
