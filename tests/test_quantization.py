"""Model-level PTQ passes (mxnet_tpu.contrib.quantization): BN fold
exactness, int8 graph rewrite vs fake-quant parity, NHWC quantized conv,
and the __dtype__ variable-hint plumbing the rewrite relies on."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import quantization as Q


def _fwd(sym, args, auxs, x, ctx=None):
    exe = sym.simple_bind(ctx or mx.cpu(), grad_req="null",
                          data=tuple(x.shape))
    for k, v in args.items():
        if k in exe.arg_dict:
            exe.arg_dict[k][:] = v
    for k, v in auxs.items():
        if k in exe.aux_dict:
            exe.aux_dict[k][:] = v
    exe.arg_dict["data"][:] = x
    return exe.forward(is_train=False)[0].asnumpy()


def _conv_bn_net(layout=None, no_bias=True):
    kw = {"layout": layout} if layout else {}
    net = mx.sym.Convolution(mx.sym.Variable("data"), kernel=(3, 3),
                             num_filter=8, pad=(1, 1), no_bias=no_bias,
                             name="conv0", **kw)
    net = mx.sym.BatchNorm(net, name="bn0", fix_gamma=False,
                           **({"axis": 3} if layout == "NHWC" else {}))
    net = mx.sym.Activation(net, act_type="relu", name="relu0")
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=5,
                                name="fc1")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _params(rng, layout=None, no_bias=True):
    wshape = (8, 3, 3, 4) if layout == "NHWC" else (8, 4, 3, 3)
    args = {"conv0_weight": mx.nd.array(rng.randn(*wshape) * 0.2),
            "bn0_gamma": mx.nd.array(rng.rand(8) + 0.5),
            "bn0_beta": mx.nd.array(rng.randn(8) * 0.1),
            "fc1_weight": mx.nd.array(rng.randn(5, 8 * 36) * 0.1),
            "fc1_bias": mx.nd.array(rng.randn(5) * 0.1)}
    if not no_bias:
        args["conv0_bias"] = mx.nd.array(rng.randn(8) * 0.1)
    auxs = {"bn0_moving_mean": mx.nd.array(rng.randn(8) * 0.1),
            "bn0_moving_var": mx.nd.array(rng.rand(8) + 0.5)}
    return args, auxs


def _data(rng, layout=None):
    return (rng.randn(4, 6, 6, 4) if layout == "NHWC"
            else rng.randn(4, 4, 6, 6)).astype(np.float32)


@pytest.mark.parametrize("no_bias", [True, False])
def test_fold_bn_exact(no_bias):
    """Folded conv+bias must equal conv->BN(inference stats) to float
    rounding; gamma/beta/moving stats disappear from the params."""
    rng = np.random.RandomState(0)
    net = _conv_bn_net(no_bias=no_bias)
    args, auxs = _params(rng, no_bias=no_bias)
    x = _data(rng)
    y0 = _fwd(net, args, auxs, x)
    fsym, fargs, fauxs = Q.fold_bn(net, args, auxs)
    y1 = _fwd(fsym, fargs, fauxs, x)
    np.testing.assert_allclose(y1, y0, rtol=1e-5, atol=1e-6)
    assert "bn0_gamma" not in fargs and "bn0_moving_mean" not in fauxs
    assert "conv0_bias" in fargs
    assert "bn0" not in fsym.tojson()


def test_fold_bn_skips_shared_conv_output():
    """A conv whose output feeds the BN AND something else must not fold
    (the scale would corrupt the second consumer)."""
    data = mx.sym.Variable("data")
    conv = mx.sym.Convolution(data, kernel=(1, 1), num_filter=4,
                              no_bias=True, name="convs")
    bn = mx.sym.BatchNorm(conv, name="bns")
    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Flatten(bn + conv), num_hidden=3, name="fcs"),
        name="softmax")
    rng = np.random.RandomState(1)
    args = {"convs_weight": mx.nd.array(rng.randn(4, 2, 1, 1)),
            "bns_gamma": mx.nd.array(rng.rand(4) + 0.5),
            "bns_beta": mx.nd.array(rng.randn(4)),
            "fcs_weight": mx.nd.array(rng.randn(3, 4 * 9) * 0.1),
            "fcs_bias": mx.nd.array(rng.randn(3))}
    auxs = {"bns_moving_mean": mx.nd.array(rng.randn(4) * 0.1),
            "bns_moving_var": mx.nd.array(rng.rand(4) + 0.5)}
    fsym, fargs, fauxs = Q.fold_bn(net, args, auxs)
    assert "BatchNorm" in fsym.tojson()  # kept, not corrupted
    x = rng.randn(2, 2, 3, 3).astype(np.float32)
    np.testing.assert_allclose(_fwd(fsym, fargs, fauxs, x),
                               _fwd(net, args, auxs, x),
                               rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("layout", [None, "NHWC"])
def test_quantize_model_end_to_end(layout):
    """Full pipeline on both conv layouts: int8 predictions track fp32
    closely on in-range data (symmetric calib on the same batch)."""
    rng = np.random.RandomState(2)
    net = _conv_bn_net(layout=layout)
    args, auxs = _params(rng, layout=layout)
    x = _data(rng, layout=layout)
    y0 = _fwd(net, args, auxs, x)
    qsym, qargs, qauxs = Q.quantize_model(net, args, auxs,
                                          [{"data": x}], mx.cpu())
    y1 = _fwd(qsym, qargs, qauxs, x)
    assert qargs["conv0_weight"].asnumpy().dtype == np.int8
    assert qargs["fc1_weight"].asnumpy().dtype == np.int8
    # int8 quantization noise on softmax probabilities
    np.testing.assert_allclose(y1, y0, atol=0.02)
    assert (y1.argmax(axis=1) == y0.argmax(axis=1)).mean() == 1.0


def test_quantize_excluded_nodes_stay_float():
    rng = np.random.RandomState(3)
    net = _conv_bn_net()
    args, auxs = _params(rng)
    x = _data(rng)
    qsym, qargs, qauxs = Q.quantize_model(
        net, args, auxs, [{"data": x}], mx.cpu(),
        excluded_sym_names=["conv0"])
    assert qargs["conv0_weight"].asnumpy().dtype == np.float32
    assert qargs["fc1_weight"].asnumpy().dtype == np.int8
    j = qsym.tojson()
    assert "_contrib_quantized_conv" not in j
    assert "_contrib_quantized_fully_connected" in j


def test_dtype_hint_drives_simple_bind_allocation():
    """__dtype__ Variable hints must survive into simple_bind's array
    allocation (int8 params bind as int8 without a type_dict)."""
    v = mx.sym.Variable("w", shape=(4, 4), dtype="int8")
    out = mx.sym.Cast(v, dtype="float32")
    exe = out.simple_bind(mx.cpu(), grad_req="null")
    assert exe.arg_dict["w"].asnumpy().dtype == np.int8


def test_quantize_tied_weight_with_excluded_consumer_raises():
    """A weight shared between a quantized node and an excluded one
    would be silently rewritten to int8 codes under the float consumer —
    must refuse loudly."""
    from mxnet_tpu.base import MXNetError

    rng = np.random.RandomState(5)
    d = mx.sym.Variable("data")
    w = mx.sym.Variable("shared_w")
    f1 = mx.sym.FullyConnected(d, weight=w, num_hidden=6, no_bias=True,
                               name="fc1")
    f2 = mx.sym.FullyConnected(d, weight=w, num_hidden=6, no_bias=True,
                               name="fc2")
    net = mx.sym.SoftmaxOutput(f1 + f2, name="softmax")
    args = {"shared_w": mx.nd.array(rng.randn(6, 4))}
    with pytest.raises(MXNetError, match="shared"):
        Q.quantize_symbol(net, args, {"fc1": 1.0},
                          excluded_sym_names=["fc2"])
    # both quantized: legal; the tied weight quantizes once with one range
    qsym, qargs = Q.quantize_symbol(net, args, {"fc1": 1.0, "fc2": 1.0})
    assert qargs["shared_w"].asnumpy().dtype == np.int8
    assert np.asarray(qargs["fc1_weight_max"].asnumpy()) \
        == np.asarray(qargs["fc2_weight_max"].asnumpy())


def test_quantize_shared_input_single_quantize_node():
    """Two convs reading the same tensor (the ResNet downsample-block
    shape) share ONE _contrib_quantize node — not one per consumer."""
    rng = np.random.RandomState(6)
    d = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(d, kernel=(1, 1), num_filter=4, no_bias=True,
                            name="ca")
    c2 = mx.sym.Convolution(d, kernel=(3, 3), num_filter=4, pad=(1, 1),
                            no_bias=True, name="cb")
    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Flatten(c1 + c2), num_hidden=3, name="fcq"),
        name="softmax")
    args = {"ca_weight": mx.nd.array(rng.randn(4, 2, 1, 1)),
            "cb_weight": mx.nd.array(rng.randn(4, 2, 3, 3) * 0.2),
            "fcq_weight": mx.nd.array(rng.randn(3, 4 * 25) * 0.1),
            "fcq_bias": mx.nd.array(rng.randn(3))}
    x = rng.randn(2, 2, 5, 5).astype(np.float32)
    qsym, qargs, qauxs = Q.quantize_model(net, args, {}, [{"data": x}],
                                          mx.cpu())
    j = qsym.tojson()
    # ca+cb share one quantize of `data`; the FC has its own
    assert j.count('"_contrib_quantize"') == 2
    y = _fwd(qsym, qargs, qauxs, x)
    y0 = _fwd(net, args, {}, x)
    assert (y.argmax(axis=1) == y0.argmax(axis=1)).all()


def test_quantize_bf16_outputs():
    """out_dtype='bfloat16' (the chip-winning configuration —
    docs/PERF.md int8-at-model-level): rescaled outputs and biases carry
    bf16, predictions stay within bf16+int8 noise of fp32."""
    rng = np.random.RandomState(7)
    net = _conv_bn_net()
    args, auxs = _params(rng)
    x = _data(rng)
    y0 = _fwd(net, args, auxs, x)
    qsym, qargs, qauxs = Q.quantize_model(net, args, auxs, [{"data": x}],
                                          mx.cpu(), out_dtype="bfloat16")
    y1 = _fwd(qsym, qargs, qauxs, x).astype(np.float32)
    np.testing.assert_allclose(y1, y0, atol=0.03)
    assert (y1.argmax(axis=1) == y0.argmax(axis=1)).mean() == 1.0
    assert str(qargs["conv0_bias"].asnumpy().dtype) == "bfloat16"
