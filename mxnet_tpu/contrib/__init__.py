"""contrib package (parity: reference ``python/mxnet/contrib/__init__.py``:
autograd API + ``_contrib_*`` op namespaces + tensorboard hook)."""

from . import autograd


class _ContribNamespace(object):
    """``mx.contrib.sym.MultiBoxPrior`` → ``sym._contrib_MultiBoxPrior``
    (parity: reference ``contrib/__init__.py:4-10`` exposing ``_contrib_*``
    ops without the prefix)."""

    def __init__(self, base_module):
        self._base = base_module

    def __getattr__(self, name):
        base = object.__getattribute__(self, "_base")
        for candidate in ("_contrib_" + name, name):
            if hasattr(base, candidate):
                return getattr(base, candidate)
        raise AttributeError("no contrib op %r" % name)


def _make_namespaces():
    from .. import ndarray as _nd_mod
    from .. import symbol as _sym_mod

    return _ContribNamespace(_sym_mod), _ContribNamespace(_nd_mod)


sym, nd = _make_namespaces()
ndarray, symbol = nd, sym


class TensorBoard(object):
    """Log metrics to tensorboard if installed (parity:
    ``contrib/tensorboard.py:LogMetricsCallback``)."""

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        try:
            from tensorboard.summary.writer.event_file_writer import EventFileWriter  # noqa
            import tensorboard  # noqa
        except ImportError:
            raise ImportError("tensorboard not installed")
        self.logging_dir = logging_dir

    def __call__(self, param):
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)


LogMetricsCallback = TensorBoard
