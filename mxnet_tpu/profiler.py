"""Profiler (parity: reference ``python/mxnet/profiler.py`` +
``src/engine/profiler.cc``).

The reference hooks the engine to emit chrome://tracing JSON.  The TPU-native
equivalent is the jax/XLA profiler (xplane): ``profiler_set_state('run')``
starts a jax trace; ``dump_profile()`` stops it and leaves a trace viewable in
TensorBoard/Perfetto.  The ``profiler_set_config`` filename becomes the trace
directory.
"""

from __future__ import annotations

import logging
import os

__all__ = ["profiler_set_config", "profiler_set_state", "dump_profile"]

_STATE = {"mode": "symbolic", "dir": "profile_output", "running": False}


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """(parity: ``profiler.py:profiler_set_config``)"""
    _STATE["mode"] = mode
    _STATE["dir"] = os.path.splitext(filename)[0]


def profiler_set_state(state="stop"):
    """'run' starts an xplane trace; 'stop' ends it (parity:
    ``profiler.py:profiler_set_state``)."""
    import jax

    if state == "run" and not _STATE["running"]:
        os.makedirs(_STATE["dir"], exist_ok=True)
        jax.profiler.start_trace(_STATE["dir"])
        _STATE["running"] = True
    elif state == "stop" and _STATE["running"]:
        jax.profiler.stop_trace()
        _STATE["running"] = False
    else:
        logging.debug("profiler state change to %r ignored", state)


def dump_profile():
    """Stop + flush the trace (parity: ``profiler.py:dump_profile``)."""
    profiler_set_state("stop")
