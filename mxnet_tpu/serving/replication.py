"""Serving replication and brownout: replica groups + a failover router.

One replica is a single point of failure; the serving tier runs a
**replica group** — N schedulers hosting the same models — and a
router that spreads load round-robin and, when a replica dies, retries
its accepted-but-unanswered requests on a peer.  The contract is
brownout, not blackout:

- **Accepted requests are never dropped.**  A request a dead replica
  had admitted fails over to a live peer with ``force=True`` — the
  peer re-admits it past its own overload/drain shedding, because the
  request already cost the caller an accept.
- **New load sheds gracefully.**  With a replica gone the survivors'
  queues fill sooner; the overflow is shed with typed 429/503, every
  shed accounted in ``serving_rejected_total``.

Membership reuses the PR-3 machinery in ``kvstore_async``: the group
publishes ``serving:<group>`` records through ``_membership_publish``
(epoch-monotonic, replica lists merge), a fenced replica's epoch is
left behind so a zombie refuses new work, and liveness is the same
heartbeat idea — every scheduler's dispatch loop beats ``last_beat``,
and :meth:`ReplicaGroup.detect` fences any replica whose beat went
stale.  ``serving_failover_total`` counts fences;
``serving_replica_up{replica}`` tracks liveness for the exposition.

With ``isolated_metrics=True`` each replica gets its own metrics
registry, and :meth:`ReplicaGroup.federation_targets` hands them to
``observability.federation`` under the standard ``{shard, role,
epoch}`` identity — one exposition, per-replica serving rows.
"""

from __future__ import annotations

import threading
import time

from ..observability import metrics as _metrics
from . import admission as _admission
from .scheduler import Scheduler

__all__ = ["ReplicaGroup", "ServingRouter"]

_M_FAILOVER = _metrics.counter(
    "serving_failover_total",
    "Replica fences: a dead/stale replica removed from its group",
    ["group"])
_M_UP = _metrics.gauge(
    "serving_replica_up",
    "1 while the serving replica is live, 0 once fenced", ["replica"])


def _group_key(group):
    return "serving:%s" % group


class ReplicaGroup(object):
    """N serving replicas (schedulers) behind one membership record.

    ``isolated_metrics=True`` gives each replica a private
    ``observability.metrics.Registry`` so federation can render them as
    distinct members; the default shares the process-global registry
    (the single-process common case).
    """

    def __init__(self, replicas=2, group="serving",
                 isolated_metrics=False):
        from .. import kvstore_async as _kv

        self.group = group
        self.epoch = 0
        self._lock = threading.Lock()
        self._fenced = set()
        self.registries = []
        self.schedulers = []
        for i in range(int(replicas)):
            reg = _metrics.Registry() if isolated_metrics else None
            self.registries.append(reg)
            sched = Scheduler(metrics_registry=reg,
                              name="%s/%d" % (group, i))
            self.schedulers.append(sched)
            _M_UP.labels(sched.name).set(1)
        _kv._membership_publish(
            _group_key(group), self.epoch,
            [s.name for s in self.schedulers],
            primary=self.schedulers[0].name)

    # -- models -------------------------------------------------------

    def register(self, name, backends, buckets=None, max_queue=None):
        """Register ``name`` on every replica.  ``backends`` is either
        a list (one backend per replica — each replica needs its OWN
        Predictor/ExportedModel, executors are not shared) or a
        zero-arg factory called once per replica."""
        if callable(backends):
            backends = [backends() for _ in self.schedulers]
        if len(backends) != len(self.schedulers):
            from ..base import MXNetError

            raise MXNetError(
                "group %r has %d replicas, got %d backends"
                % (self.group, len(self.schedulers), len(backends)))
        for sched, backend in zip(self.schedulers, backends):
            sched.register(name, backend, buckets=buckets,
                           max_queue=max_queue)

    def warmup(self, name):
        """Pre-bind every bucket on every live replica."""
        for _, sched in self.live():
            sched.warmup(name)

    # -- membership ---------------------------------------------------

    def live(self):
        """``[(index, scheduler)]`` for replicas not yet fenced."""
        with self._lock:
            fenced = set(self._fenced)
        return [(i, s) for i, s in enumerate(self.schedulers)
                if i not in fenced and s.alive]

    def membership(self):
        from .. import kvstore_async as _kv

        return _kv._membership_lookup(_group_key(self.group))

    def kill(self, index):
        """Crash replica ``index`` (chaos drills): queued requests fail
        with ``ReplicaDeadError`` for the router to retry, then the
        group fences it out of membership."""
        self.schedulers[index].kill()
        self.fence(index)

    def fence(self, index):
        """Remove replica ``index`` from the group: bump the membership
        epoch past it (PR-3 monotonic publish — the zombie's old epoch
        can never win again), fail anything it still holds, and account
        the failover.  Idempotent."""
        from .. import kvstore_async as _kv

        with self._lock:
            if index in self._fenced:
                return
            self._fenced.add(index)
            self.epoch += 1
            epoch = self.epoch
            fenced = set(self._fenced)
        zombie = self.schedulers[index]
        zombie.fence(epoch)
        _M_UP.labels(zombie.name).set(0)
        _M_FAILOVER.labels(self.group).inc()
        survivors = [s.name for i, s in enumerate(self.schedulers)
                     if i not in fenced]
        for i, s in enumerate(self.schedulers):
            if i not in fenced:
                s.epoch = epoch
        _kv._membership_publish(
            _group_key(self.group), epoch, survivors or [zombie.name],
            primary=survivors[0] if survivors else zombie.name)

    def detect(self, heartbeat_timeout_s=1.0):
        """Heartbeat sweep: fence every replica whose dispatch loops
        stopped beating.  Returns the indices fenced this sweep."""
        now = time.monotonic()
        with self._lock:
            fenced = set(self._fenced)
        # NOT live(): a replica that died without being fenced is exactly
        # what this sweep exists to find
        stale = [i for i, s in enumerate(self.schedulers)
                 if i not in fenced
                 and (not s.alive
                      or now - s.last_beat > heartbeat_timeout_s)]
        for i in stale:
            self.fence(i)
        return stale

    # -- observability ------------------------------------------------

    def federation_targets(self):
        """Per-replica federation targets (``isolated_metrics=True``):
        each replica's registry under ``{shard, role, epoch}``."""
        targets = []
        for i, s in enumerate(self.schedulers):
            if self.registries[i] is None:
                continue
            targets.append({"shard": i, "role": "serving",
                            "epoch": s.epoch,
                            "registry": self.registries[i]})
        return targets

    def close(self):
        for _, sched in self.live():
            sched.close()


class ServingRouter(object):
    """Round-robin request router with peer failover.

    Sheds (:class:`~.admission.ServerOverloadedError` /
    :class:`~.admission.ServerDrainingError`) try the next replica and
    only surface when every replica shed.  A replica that dies holding
    an accepted request is fenced and the request re-admitted on a peer
    with ``force=True`` — the brownout guarantee."""

    def __init__(self, group):
        self._group = group
        self._rr = 0
        self._lock = threading.Lock()

    def _rotation(self):
        live = self._group.live()
        if not live:
            return []
        with self._lock:
            start = self._rr
            self._rr += 1
        return live[start % len(live):] + live[:start % len(live)]

    @staticmethod
    def _remaining_ms(req):
        """Carry the original absolute deadline onto the retry."""
        if req.deadline is None:
            return 0  # deadline_from_ms(0) -> no deadline
        return max((req.deadline - time.monotonic()) * 1e3, 0.001)

    def request(self, model, inputs, deadline_ms=None, timeout=30.0):
        shed = None
        for index, sched in self._rotation():
            try:
                req = sched.submit(model, inputs, deadline_ms=deadline_ms)
            except _admission.ReplicaDeadError:
                self._group.fence(index)
                continue
            except (_admission.ServerOverloadedError,
                    _admission.ServerDrainingError) as exc:
                shed = exc
                continue
            try:
                return req.result(timeout=timeout)
            except _admission.ReplicaDeadError:
                # accepted but unanswered: fence the replica, finish
                # the request on a peer — never drop accepted work
                self._group.fence(index)
                return self._retry_on_peer(model, req, timeout)
        if shed is not None:
            raise shed
        raise _admission.ReplicaDeadError(
            "group %r has no live serving replica" % self._group.group)

    def _retry_on_peer(self, model, req, timeout):
        for index, sched in self._group.live():
            try:
                peer = sched.submit(model, req.inputs,
                                    deadline_ms=self._remaining_ms(req),
                                    force=True)
                return peer.result(timeout=timeout)
            except _admission.ReplicaDeadError:
                self._group.fence(index)
        raise _admission.ReplicaDeadError(
            "request to %r accepted by a dead replica and no peer is "
            "left in group %r" % (model, self._group.group))
