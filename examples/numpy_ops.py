"""Custom operators in Python (parity: reference ``example/numpy-ops/
custom_softmax.py`` — a CustomOp/CustomOpProp pair implementing softmax
with numpy, registered and used inside a Symbol graph).

    python examples/numpy_ops.py [--tpus 0]

NB: python callbacks lower to PJRT host send/recv; some tunneled dev
backends don't support them (run on cpu there — real TPU runtimes do).
"""

import argparse
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

import mxnet_tpu as mx


class NumpySoftmax(mx.CustomOp):
    """Softmax + cross-entropy grad computed in numpy on the host
    (the async-safe callback path; reference custom-inl.h:43)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        e = np.exp(x - x.max(axis=1, keepdims=True))
        self.assign(out_data[0], req[0], mx.nd.array(
            e / e.sum(axis=1, keepdims=True)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        label = in_data[1].asnumpy().astype(int)
        prob = out_data[0].asnumpy().copy()
        prob[np.arange(prob.shape[0]), label] -= 1.0
        self.assign(in_grad[0], req[0], mx.nd.array(prob / prob.shape[0]))


@mx.operator.register("numpy_softmax")
class NumpySoftmaxProp(mx.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return [in_shape[0], (in_shape[0][0],)], [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return NumpySoftmax()


def main():
    parser = argparse.ArgumentParser(description="CustomOp demo")
    parser.add_argument("--num-epochs", type=int, default=8)
    parser.add_argument("--tpus", type=str, default=None)
    args = parser.parse_args()

    # initializer + NDArrayIter shuffle draw from the global stream: pin it
    # so the accuracy gate is deterministic
    np.random.seed(1)
    rng = np.random.RandomState(0)
    centers = rng.randn(4, 8) * 3.0
    labels = rng.randint(0, 4, 400)
    data = (centers[labels] + rng.randn(400, 8)).astype(np.float32)
    it = mx.io.NDArrayIter(data, labels.astype(np.float32), batch_size=40,
                           shuffle=True)

    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                                name="fc")
    net = mx.sym.Custom(net, mx.sym.Variable("softmax_label"),
                        op_type="numpy_softmax", name="softmax")
    mod = mx.mod.Module(net, context=mx.context.devices_from_arg(args.tpus))
    mod.fit(it, num_epoch=args.num_epochs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.3},
            initializer=mx.initializer.Xavier())
    acc = mod.score(mx.io.NDArrayIter(data, labels.astype(np.float32),
                                      batch_size=40), "acc")
    print("custom-op model accuracy: %s" % acc)
    assert acc[0][1] > 0.9, acc


if __name__ == "__main__":
    main()
