"""Score a checkpointed model on a validation set (parity: reference
``example/image-classification/score.py`` — load prefix/epoch, run metrics
over an iterator).

    python examples/image_classification/score.py --model prefix,epoch \
        [--data-val path.rec] [--tpus 0]
"""

import argparse
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)
sys.path.insert(0, os.path.dirname(os.path.dirname(_HERE)))

import mxnet_tpu as mx


def score(model, data_val, metrics, tpus=None, batch_size=32,
          data_shape=(3, 28, 28), num_examples=640, seed=99):
    prefix, epoch = model.split(",")
    sym, arg_params, aux_params = mx.model.load_checkpoint(
        prefix, int(epoch))
    devs = mx.context.devices_from_arg(tpus)
    if data_val and not os.path.exists(data_val):
        sys.exit("--data-val %r does not exist" % data_val)
    if data_val:
        it = mx.io.ImageRecordIter(path_imgrec=data_val,
                                   data_shape=data_shape,
                                   batch_size=batch_size)
    else:
        print("note: no --data-val given; scoring on the synthetic "
              "separable-digit set")
        # synthetic fallback: the same separable-digit generator the train
        # examples use, so a checkpoint from train_mnist scores sensibly
        import types

        from common import data as common_data

        fake_args = types.SimpleNamespace(batch_size=batch_size,
                                          num_examples=num_examples,
                                          data_dir="data/mnist")
        kv = types.SimpleNamespace(num_workers=1, rank=0)
        _, it = common_data.get_mnist_iter(fake_args, kv)

    mod = mx.mod.Module(symbol=sym, context=devs)
    mod.bind(for_training=False, data_shapes=it.provide_data,
             label_shapes=it.provide_label)
    mod.set_params(arg_params, aux_params)
    results = mod.score(it, metrics)
    for name, value in results:
        print("%s=%f" % (name, value))
    return results


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description="score a model")
    parser.add_argument("--model", type=str, required=True,
                        help="prefix,epoch of the checkpoint")
    parser.add_argument("--data-val", type=str, default=None)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--data-shape", type=str, default="3,28,28")
    parser.add_argument("--tpus", type=str, default=None)
    args = parser.parse_args()
    shape = tuple(int(x) for x in args.data_shape.split(","))
    score(args.model, args.data_val,
          [mx.metric.create("acc"), mx.metric.create("top_k_accuracy",
                                                     top_k=5)],
          tpus=args.tpus, batch_size=args.batch_size, data_shape=shape)
