"""PTB LSTM language model with bucketing (parity: reference
``example/rnn/lstm_bucketing.py`` — BucketingModule + stacked LSTMCell;
BASELINE config #4).

Reads PTB text from ``--data-dir`` if present (ptb.train.txt / ptb.valid.txt),
else generates a synthetic Markov-chain corpus so the example runs with zero
downloads.
"""

import argparse
import os

import numpy as np

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))
import mxnet_tpu as mx

parser = argparse.ArgumentParser(
    description="Train an LSTM language model with bucketing",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--data-dir", type=str, default="data/ptb")
parser.add_argument("--num-layers", type=int, default=2)
parser.add_argument("--num-hidden", type=int, default=200)
parser.add_argument("--num-embed", type=int, default=200)
parser.add_argument("--tpus", type=str, default=None)
parser.add_argument("--kv-store", type=str, default="device")
parser.add_argument("--num-epochs", type=int, default=25)
parser.add_argument("--lr", type=float, default=0.01)
parser.add_argument("--optimizer", type=str, default="sgd")
parser.add_argument("--mom", type=float, default=0.0)
parser.add_argument("--wd", type=float, default=0.00001)
parser.add_argument("--batch-size", type=int, default=32)
parser.add_argument("--disp-batches", type=int, default=50)
parser.add_argument("--num-sentences", type=int, default=2000,
                    help="synthetic corpus size when no PTB files found")
buckets = [10, 20, 30, 40, 50, 60]
start_label = 1
invalid_label = 0


def tokenize_text(fname, vocab=None, invalid_label=-1, start_label=0):
    with open(fname) as f:
        lines = f.readlines()
    lines = [filter(None, i.split(" ")) for i in lines]
    sentences, vocab = mx.rnn.encode_sentences(
        lines, vocab=vocab, invalid_label=invalid_label,
        start_label=start_label)
    return sentences, vocab


def synthetic_corpus(num_sentences, vocab_size=500, seed=3):
    """Markov-chain sentences: learnable non-uniform bigram structure."""
    rng = np.random.RandomState(seed)
    trans = rng.dirichlet(np.ones(vocab_size) * 0.05, size=vocab_size)
    sents = []
    for _ in range(num_sentences):
        n = rng.randint(5, 60)
        s = [int(rng.randint(start_label, vocab_size))]
        for _ in range(n - 1):
            s.append(int(rng.choice(vocab_size, p=trans[s[-1]])))
        sents.append([max(t, start_label) for t in s])
    return sents


if __name__ == "__main__":
    import logging

    head = "%(asctime)-15s %(message)s"
    logging.basicConfig(level=logging.INFO, format=head)
    args = parser.parse_args()

    train_file = os.path.join(args.data_dir, "ptb.train.txt")
    if os.path.exists(train_file):
        train_sent, vocab = tokenize_text(
            train_file, start_label=start_label, invalid_label=invalid_label)
        val_sent, vocab = tokenize_text(
            os.path.join(args.data_dir, "ptb.valid.txt"), vocab=vocab,
            start_label=start_label, invalid_label=invalid_label)
    else:
        logging.info("no PTB data under %s; using synthetic corpus", args.data_dir)
        sents = synthetic_corpus(args.num_sentences)
        split = int(len(sents) * 0.9)
        train_sent, val_sent = sents[:split], sents[split:]
        vocab = {i: i for i in range(501)}

    data_train = mx.rnn.BucketSentenceIter(train_sent, args.batch_size,
                                           buckets=buckets,
                                           invalid_label=invalid_label)
    data_val = mx.rnn.BucketSentenceIter(val_sent, args.batch_size,
                                         buckets=buckets,
                                         invalid_label=invalid_label)

    stack = mx.rnn.SequentialRNNCell()
    for i in range(args.num_layers):
        stack.add(mx.rnn.LSTMCell(num_hidden=args.num_hidden,
                                  prefix="lstm_l%d_" % i))

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data=data, input_dim=len(vocab),
                                 output_dim=args.num_embed, name="embed")
        stack.reset()
        outputs, states = stack.unroll(seq_len, inputs=embed,
                                       merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(data=pred, num_hidden=len(vocab),
                                     name="pred")
        label = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(data=pred, label=label, name="softmax")
        return pred, ("data",), ("softmax_label",)

    contexts = mx.context.devices_from_arg(args.tpus)

    model = mx.mod.BucketingModule(
        sym_gen=sym_gen,
        default_bucket_key=data_train.default_bucket_key,
        context=contexts)

    model.fit(
        train_data=data_train,
        eval_data=data_val,
        eval_metric=mx.metric.Perplexity(invalid_label),
        kvstore=args.kv_store,
        optimizer=args.optimizer,
        optimizer_params={"learning_rate": args.lr, "momentum": args.mom,
                          "wd": args.wd},
        initializer=mx.initializer.Xavier(factor_type="in", magnitude=2.34),
        num_epoch=args.num_epochs,
        batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                   args.disp_batches))
