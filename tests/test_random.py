"""Random sampling moment checks (parity model: reference
``tests/python/unittest/test_random.py``)."""

import numpy as np

import mxnet_tpu as mx


def test_uniform_moments():
    mx.random.seed(7)
    x = mx.nd.uniform(low=-2.0, high=4.0, shape=(2000,)).asnumpy()
    assert abs(x.mean() - 1.0) < 0.15
    assert x.min() >= -2.0 and x.max() < 4.0


def test_normal_moments():
    mx.random.seed(8)
    x = mx.nd.normal(loc=3.0, scale=2.0, shape=(4000,)).asnumpy()
    assert abs(x.mean() - 3.0) < 0.15
    assert abs(x.std() - 2.0) < 0.15


def test_seed_determinism():
    mx.random.seed(123)
    a = mx.nd.uniform(shape=(100,)).asnumpy()
    mx.random.seed(123)
    b = mx.nd.uniform(shape=(100,)).asnumpy()
    np.testing.assert_array_equal(a, b)
    c = mx.nd.uniform(shape=(100,)).asnumpy()
    assert not np.array_equal(a, c)


def test_sym_random():
    mx.random.seed(5)
    u = mx.sym.uniform(low=0, high=1, shape=(500,))
    ex = u.bind(mx.cpu(), {})
    x = ex.forward()[0].asnumpy()
    assert 0.0 <= x.min() and x.max() < 1.0
    assert abs(x.mean() - 0.5) < 0.1


def test_gamma_moments():
    mx.random.seed(9)
    # Gamma(shape=3, scale=2): mean 6, var 12
    x = mx.nd._random_gamma(alpha=3.0, beta=2.0, shape=(4000,)).asnumpy()
    assert abs(x.mean() - 6.0) < 0.5
    assert abs(x.var() - 12.0) < 3.0


def test_exponential_moments():
    mx.random.seed(10)
    x = mx.nd._random_exponential(lam=2.0, shape=(4000,)).asnumpy()
    assert abs(x.mean() - 0.5) < 0.1


def test_poisson_moments():
    mx.random.seed(11)
    x = mx.nd._random_poisson(lam=4.0, shape=(4000,)).asnumpy()
    assert abs(x.mean() - 4.0) < 0.3
    assert abs(x.var() - 4.0) < 0.6


def test_sample_ops_per_distribution_params():
    """_sample_* draw per-row samples for an array of params."""
    mx.random.seed(12)
    mu = mx.nd.array(np.array([0.0, 10.0], np.float32))
    sigma = mx.nd.array(np.array([1.0, 1.0], np.float32))
    x = mx.nd._sample_normal(mu=mu, sigma=sigma, shape=(2000,)).asnumpy()
    assert x.shape == (2, 2000)
    assert abs(x[0].mean() - 0.0) < 0.2
    assert abs(x[1].mean() - 10.0) < 0.2
