"""Ahead-of-time model export (parity: reference ``amalgamation/`` — the
single-artifact deployment build of the predict API for mobile/JS, plus the
``MXPredCreate``-from-bytes flow of ``c_predict_api.h``).

TPU-native equivalent: ``jax.export`` serializes the predictor's forward as
a **StableHLO artifact** — one portable blob, loadable by any process with
jax (or any StableHLO runtime) **without this framework installed**, with
parameters baked in or passed at call time.  That is the amalgamation
story re-based on the XLA ecosystem's stable interchange format.
"""

from __future__ import annotations

import io
import json
import zipfile

import numpy as _np

from .base import MXNetError

__all__ = ["export_model", "load_exported", "ExportedModel"]

_MANIFEST = "MXTPU_EXPORT.json"
_HLO = "forward.stablehlo"
_PARAMS = "params.npz"


def export_model(prefix, epoch, input_shapes, ctx=None, bake_params=True):
    """Export checkpoint artifacts to one deployable ``.mxtpu`` zip.

    Parameters
    ----------
    prefix, epoch : the ``save_checkpoint`` artifacts to load.
    input_shapes : dict name -> shape for the serving signature.
    bake_params : fold the weights into the artifact (single-blob deploy);
        otherwise the artifact takes them as a call argument.

    Returns the artifact path ``prefix-export.mxtpu``.
    """
    import jax
    from jax import export as jax_export

    from . import predict

    pred = predict.load(prefix, epoch, ctx=ctx, input_shapes=input_shapes)
    exe = pred._exec
    args, auxs = exe._gather()
    input_names = sorted(input_shapes)
    param_names = sorted(n for n in args if n not in input_shapes)

    def fwd(params, *inputs):
        all_args = dict(params)
        all_args.update(dict(zip(input_names, inputs)))
        outs, _ = exe._run(all_args, auxs, jax.random.PRNGKey(0), False)
        return tuple(outs)

    params = {n: args[n] for n in param_names}
    in_structs = [jax.ShapeDtypeStruct(tuple(input_shapes[n]),
                                       _np.dtype(_np.float32))
                  for n in input_names]
    if bake_params:
        import functools

        fixed = jax.jit(functools.partial(fwd, params))
        exported = jax_export.export(fixed)(*in_structs)
    else:
        pstructs = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
                    for n, v in params.items()}
        exported = jax_export.export(jax.jit(fwd))(pstructs, *in_structs)

    path = "%s-export.mxtpu" % prefix
    with zipfile.ZipFile(path, "w") as z:
        z.writestr(_MANIFEST, json.dumps({
            "format": 1,
            "inputs": {n: list(input_shapes[n]) for n in input_names},
            "baked": bool(bake_params),
        }))
        z.writestr(_HLO, exported.serialize())
        if not bake_params:
            buf = io.BytesIO()
            _np.savez(buf, **{n: _np.asarray(v) for n, v in params.items()})
            z.writestr(_PARAMS, buf.getvalue())
    return path


class ExportedModel(object):
    """Loaded deployment artifact: ``model(data=...) -> [numpy outputs]``."""

    def __init__(self, path):
        from jax import export as jax_export

        with zipfile.ZipFile(path) as z:
            manifest = json.loads(z.read(_MANIFEST))
            self._exported = jax_export.deserialize(z.read(_HLO))
            self._params = None
            if not manifest["baked"]:
                with _np.load(io.BytesIO(z.read(_PARAMS))) as f:
                    self._params = {k: f[k] for k in f.files}
        self.input_names = sorted(manifest["inputs"])
        self.input_shapes = {k: tuple(v)
                             for k, v in manifest["inputs"].items()}

    def __call__(self, **inputs):
        vals = []
        for n in self.input_names:
            if n not in inputs:
                raise MXNetError("missing input %r" % n)
            v = _np.asarray(inputs[n], dtype=_np.float32)
            if tuple(v.shape) != self.input_shapes[n]:
                raise MXNetError("input %r shape %s != exported %s"
                                 % (n, v.shape, self.input_shapes[n]))
            vals.append(v)
        if self._params is not None:
            out = self._exported.call(self._params, *vals)
        else:
            out = self._exported.call(*vals)
        return [_np.asarray(o) for o in out]


def load_exported(path):
    """(parity: ``MXPredCreate`` from an amalgamated artifact)"""
    return ExportedModel(path)
