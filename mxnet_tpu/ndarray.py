"""NDArray — the imperative array type (parity: reference
``include/mxnet/ndarray.h`` + ``python/mxnet/ndarray.py``).

The reference NDArray pairs a ``Storage::Handle`` with an ``Engine::VarHandle``
so reads/writes order through the dependency engine.  Here the backing store is
a ``jax.Array``: XLA's async dispatch IS the engine (every op returns
immediately with a future-backed buffer; ``wait_to_read`` blocks on the ready
event, replacing ``WaitToRead``'s engine var wait).  Mutation (``a[:] = x``,
``+=``, optimizer updates) rebinds the underlying buffer — the functional
equivalent of the reference's in-place engine writes, with XLA buffer donation
recovering the memory.

Every registered op materializes as a function in this module at import time,
mirroring how the reference generates ``mx.nd.*`` from the C op registry
(``python/mxnet/ndarray.py:_init_ndarray_module``).
"""

from __future__ import annotations

import builtins
import functools
import sys

import jax
import jax.numpy as jnp
import numpy as _np

from . import random as _random
from .base import MXNetError, mx_dtype, numeric_types
from .context import Context, current_context
from .ops.registry import OP_REGISTRY, _ALIAS, get_op

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "concatenate", "load", "save", "imresize", "onehot_encode",
           "waitall", "multiply", "subtract", "divide", "true_divide",
           "moveaxis", "imdecode"]


class NDArray:
    """Multi-dimensional array with async semantics on a device context."""

    __slots__ = ("_data", "_ctx", "_writable", "_tape_entry")

    def __init__(self, data, ctx=None, writable=True):
        if isinstance(data, NDArray):
            data = data._data
        self._data = data
        self._ctx = ctx if ctx is not None else current_context()
        self._writable = writable
        self._tape_entry = None  # autograd tape hook (contrib.autograd)

    # -- basic properties ---------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return _np.dtype(self._data.dtype)

    @property
    def size(self):
        s = 1
        for d in self.shape:
            s *= d
        return s

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self):
        return self._ctx

    @property
    def handle(self):  # API-compat shim (reference exposes a C handle)
        return self

    def __len__(self):
        return self.shape[0]

    def __repr__(self):
        return "<NDArray %s @%s>" % ("x".join(str(d) for d in self.shape), self._ctx)

    # -- synchronization (parity: WaitToRead / WaitForAll) ------------
    def wait_to_read(self):
        jax.block_until_ready(self._data)

    def asnumpy(self):
        return _np.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(-1)[0]

    # -- conversion / movement ----------------------------------------
    def astype(self, dtype):
        return NDArray(self._data.astype(mx_dtype(dtype)), self._ctx)

    def copy(self):
        return NDArray(self._data + 0, self._ctx)

    def copyto(self, other):
        if isinstance(other, NDArray):
            if other.shape != self.shape:
                raise ValueError(
                    "copyto shape mismatch: %s vs %s" % (self.shape, other.shape))
            other._set_data(jax.device_put(self._data, other._ctx.jax_device))
            return other
        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device), other)
        raise TypeError("copyto does not support type " + str(type(other)))

    def as_in_context(self, context):
        if context == self._ctx:
            return self
        return self.copyto(context)

    # -- mutation ------------------------------------------------------
    def _set_data(self, new_data):
        if not self._writable:
            raise MXNetError("trying to write to a read-only NDArray")
        self._data = new_data

    def __setitem__(self, key, value):
        if isinstance(value, NDArray):
            value = value._data
        elif isinstance(value, numeric_types):
            value = jnp.asarray(value, dtype=self.dtype)
        else:
            value = jnp.asarray(value, dtype=self.dtype)
        # NB: builtins.slice — the generated mx.nd.slice op shadows the name
        # in this module's namespace
        if key == builtins.slice(None) or key is Ellipsis:
            self._set_data(jnp.broadcast_to(value, self.shape).astype(self.dtype))
        else:
            self._set_data(self._data.at[key].set(value))

    def __getitem__(self, key):
        return NDArray(self._data[key], self._ctx)

    def slice(self, start, stop):
        return NDArray(self._data[start:stop], self._ctx)

    # -- shape ops -----------------------------------------------------
    def reshape(self, shape):
        return NDArray(jnp.reshape(self._data, shape), self._ctx)

    @property
    def T(self):
        return NDArray(self._data.T, self._ctx)

    # -- arithmetic (broadcasting, like reference broadcast_* sugar) ---
    def _binary(self, other, fn, op_name=None, scalar_op=None, swap=False):
        # when the autograd tape is active, route through the op registry so
        # the op is recorded (parity: reference sugar maps to broadcast_* /
        # _*_scalar ops which MXImperativeInvoke tapes)
        from .contrib import autograd as _ag

        if _ag.is_training() and (op_name or scalar_op):
            if isinstance(other, (int, float)) and scalar_op:
                # _r*_scalar ops encode the operand order themselves
                return invoke(scalar_op, [self], {"scalar": float(other)})
            if op_name:
                o = other if isinstance(other, NDArray) else \
                    NDArray(jnp.asarray(other, dtype=self.dtype), self._ctx)
                pair = [o, self] if swap else [self, o]
                return invoke(op_name, pair)
        if isinstance(other, NDArray):
            a, b = self._data, other._data
        else:
            a, b = self._data, jnp.asarray(other, dtype=self.dtype)
        if swap:
            a, b = b, a
        return NDArray(fn(a, b), self._ctx)

    def __add__(self, other):
        return self._binary(other, jnp.add, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, jnp.subtract, "broadcast_sub",
                            "_minus_scalar")

    def __rsub__(self, other):
        return self._binary(other, jnp.subtract, "broadcast_sub",
                            "_rminus_scalar", swap=True)

    def __mul__(self, other):
        return self._binary(other, jnp.multiply, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __div__(self, other):
        return self._binary(other, jnp.divide, "broadcast_div", "_div_scalar")

    __truediv__ = __div__

    def __rdiv__(self, other):
        return self._binary(other, jnp.divide, "broadcast_div", "_rdiv_scalar",
                            swap=True)

    __rtruediv__ = __rdiv__

    def __pow__(self, other):
        return self._binary(other, jnp.power, "broadcast_power",
                            "_power_scalar")

    def __mod__(self, other):
        return self._binary(other, jnp.mod, "broadcast_mod", "_mod_scalar")

    def __neg__(self):
        from .contrib import autograd as _ag

        if _ag.is_training():
            return invoke("negative", [self])
        return NDArray(-self._data, self._ctx)

    def __iadd__(self, other):
        o = other._data if isinstance(other, NDArray) else other
        self._set_data(self._data + o)
        return self

    def __isub__(self, other):
        o = other._data if isinstance(other, NDArray) else other
        self._set_data(self._data - o)
        return self

    def __imul__(self, other):
        o = other._data if isinstance(other, NDArray) else other
        self._set_data(self._data * o)
        return self

    def __idiv__(self, other):
        o = other._data if isinstance(other, NDArray) else other
        self._set_data(self._data / o)
        return self

    __itruediv__ = __idiv__

    def __eq__(self, other):
        if isinstance(other, (NDArray,) + numeric_types):
            return self._binary(other, lambda a, b: (a == b).astype(a.dtype))
        return NotImplemented

    def __ne__(self, other):
        if isinstance(other, (NDArray,) + numeric_types):
            return self._binary(other, lambda a, b: (a != b).astype(a.dtype))
        return NotImplemented

    def __gt__(self, other):
        return self._binary(other, lambda a, b: (a > b).astype(a.dtype))

    def __ge__(self, other):
        return self._binary(other, lambda a, b: (a >= b).astype(a.dtype))

    def __lt__(self, other):
        return self._binary(other, lambda a, b: (a < b).astype(a.dtype))

    def __le__(self, other):
        return self._binary(other, lambda a, b: (a <= b).astype(a.dtype))

    def __hash__(self):
        return id(self)

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("ambiguous truth value of multi-element NDArray")


# ----------------------------------------------------------------------
# creation API
# ----------------------------------------------------------------------


def _ctx_or_current(ctx):
    return ctx if ctx is not None else current_context()


def array(source_array, ctx=None, dtype=None):
    """Create an NDArray from any array-like (parity: ``mx.nd.array``)."""
    ctx = _ctx_or_current(ctx)
    if isinstance(source_array, NDArray):
        source_array = source_array.asnumpy()
    if dtype is None:
        # reference semantics: numpy arrays keep their dtype, anything else
        # (lists, scalars) defaults to float32
        if isinstance(source_array, _np.ndarray):
            dtype = source_array.dtype
            if dtype == _np.float64:
                dtype = _np.float32
            elif dtype == _np.int64:
                dtype = _np.int32
        else:
            dtype = _np.float32
    arr = _np.asarray(source_array, dtype=mx_dtype(dtype))
    return NDArray(jax.device_put(jnp.asarray(arr), ctx.jax_device), ctx)


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx, dtype)


def zeros(shape, ctx=None, dtype=None):
    ctx = _ctx_or_current(ctx)
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(
        jax.device_put(jnp.zeros(shape, dtype=mx_dtype(dtype)), ctx.jax_device), ctx
    )


def ones(shape, ctx=None, dtype=None):
    ctx = _ctx_or_current(ctx)
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(
        jax.device_put(jnp.ones(shape, dtype=mx_dtype(dtype)), ctx.jax_device), ctx
    )


def full(shape, val, ctx=None, dtype=None):
    ctx = _ctx_or_current(ctx)
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(
        jax.device_put(jnp.full(shape, val, dtype=mx_dtype(dtype)), ctx.jax_device), ctx
    )


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    ctx = _ctx_or_current(ctx)
    if stop is None:
        start, stop = 0, start
    out = _np.arange(start, stop, step)
    if repeat > 1:
        out = _np.repeat(out, repeat)
    return NDArray(
        jax.device_put(jnp.asarray(out.astype(mx_dtype(dtype))), ctx.jax_device), ctx
    )


def concatenate(arrays, axis=0, always_copy=True):
    return NDArray(
        jnp.concatenate([a._data for a in arrays], axis=axis), arrays[0]._ctx
    )


def onehot_encode(indices, out):
    """(parity: ``mx.nd.onehot_encode``)"""
    depth = out.shape[1]
    out._set_data(jax.nn.one_hot(indices._data.astype(jnp.int32), depth,
                                 dtype=out.dtype))
    return out


def imresize(src, w, h, *args, **kwargs):
    data = jax.image.resize(src._data, (h, w) + src.shape[2:], method="bilinear")
    return NDArray(data, src._ctx)


def waitall():
    """Block until all async work completes (parity: ``mx.nd.waitall``)."""
    (jax.device_put(0.0) + 0).block_until_ready()


# ----------------------------------------------------------------------
# serialization (parity: NDArray::Save/Load, reference ndarray.h:355-370).
# Format: numpy .npz with a manifest — not the dmlc binary format, but the
# same save/load API and name-map semantics.
# ----------------------------------------------------------------------


def _save_npz(fname, arrays, fmt):
    """Single writer of the on-disk container (shared by :func:`save` and
    the engine-deferred checkpoint write): atomic via temp-file + rename so
    a crash mid-write can never leave a truncated file at the final path."""
    import os
    import tempfile

    d = os.path.dirname(os.path.abspath(fname)) or "."
    fd, tmp = tempfile.mkstemp(prefix=".mxtpu_save_", dir=d)
    try:
        with os.fdopen(fd, "wb") as f:  # file object keeps exact name (no .npz)
            _np.savez(f, __mx_format__=fmt, **arrays)
        os.replace(tmp, fname)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def save(fname, data):
    """Save a list or str->NDArray dict (parity: ``mx.nd.save``)."""
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        arrays = {k: v.asnumpy() for k, v in data.items()}
        fmt = "dict"
    else:
        arrays = {"arr_%d" % i: v.asnumpy() for i, v in enumerate(data)}
        fmt = "list"
    _save_npz(fname, arrays, fmt)


def load(fname):
    """Load NDArrays saved by :func:`save`."""
    with _np.load(fname, allow_pickle=False) as f:
        fmt = str(f["__mx_format__"]) if "__mx_format__" in f else "dict"
        keys = [k for k in f.files if k != "__mx_format__"]
        if fmt == "list":
            keys = sorted(keys, key=lambda k: int(k.split("_")[1]))
            return [array(f[k]) for k in keys]
        return {k: array(f[k]) for k in keys}


def load_frombuffer(buf):
    """Load NDArrays from serialized bytes (parity: ``mx.nd.load_frombuffer``
    / ``MXNDArrayLoadFromBuffer`` — the predict API's param path)."""
    import io as _io

    return load(_io.BytesIO(buf))


# ----------------------------------------------------------------------
# op namespace generation (parity: _init_ndarray_module)
# ----------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _jitted_apply(op_name, attrs_key, n_args, n_aux, is_train, with_rng):
    op = get_op(op_name)
    attrs = dict(attrs_key)

    def run(*tensors):
        args = tensors[:n_args]
        auxs = tensors[n_args : n_args + n_aux]
        rng = tensors[-1] if with_rng else None
        outputs, new_aux = op.apply(attrs, args, auxs, is_train=is_train, rng=rng)
        return tuple(outputs) + tuple(new_aux)

    return jax.jit(run)


def invoke(op_name, args, kwargs=None, out=None, is_train=False):
    """Imperative op invoke (parity: ``MXImperativeInvoke``,
    reference ``src/c_api/c_api_ndarray.cc:322``): look up the op, jit-cache by
    (op, attrs), run on the arrays' device, wrap outputs."""
    op = get_op(op_name)
    kwargs = dict(kwargs or {})
    kwargs.pop("name", None)
    ctx = kwargs.pop("ctx", None)
    if isinstance(ctx, str):  # attrs-style ctx string from graph load
        ctx = None
    if op.variable_args and "num_args" not in kwargs:
        kwargs["num_args"] = len(args)
    attrs = op.parse_attrs(kwargs)
    n_declared = len(op.input_names(attrs))
    arg_list = list(args)
    # split aux trailing args (eager BatchNorm passes moving stats positionally)
    n_aux = len(op.aux_names)
    if n_aux and len(arg_list) == n_declared + n_aux:
        aux_list = arg_list[n_declared:]
        arg_list = arg_list[:n_declared]
    else:
        aux_list = []
        n_aux = 0
    for a in arg_list + aux_list:
        if isinstance(a, NDArray):
            ctx = ctx or a._ctx
    ctx = _ctx_or_current(ctx)

    def as_jax(a):
        return a._data if isinstance(a, NDArray) else jnp.asarray(a)

    tensors = [as_jax(a) for a in arg_list] + [as_jax(a) for a in aux_list]
    if op.needs_rng:
        tensors.append(_random.next_key())
    fn = _jitted_apply(
        op_name, op.attrs_key(attrs), len(arg_list), n_aux, is_train,
        op.needs_rng
    )
    if op.mesh_aware:
        # eager calls run dense on the inputs' device: sharding constraints
        # belong to mesh-scoped traced graphs (ShardedTrainer), and a cached
        # eager trace must never bake in an ambient mesh
        from .parallel import default_mesh

        with default_mesh(None):
            results = fn(*tensors)
    else:
        results = fn(*tensors)
    n_out = op.n_outputs(attrs)
    outputs = [NDArray(r, ctx) for r in results[:n_out]]
    # autograd tape hook (contrib.autograd train_section)
    from .contrib import autograd as _ag

    if _ag.is_training():
        _ag._record(op, attrs, arg_list + aux_list, outputs, len(arg_list))
    # write back updated aux state (engine-write equivalent)
    for aux_nd, new in zip(aux_list, results[n_out : n_out + n_aux]):
        if isinstance(aux_nd, NDArray):
            aux_nd._set_data(new)
    if out is not None:
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o, r in zip(outs, outputs):
            o._set_data(r._data)
        return out
    if n_out == 1:
        return outputs[0]
    return outputs


def _make_nd_fn(op_name):
    op = get_op(op_name)

    def fn(*args, **kwargs):
        out = kwargs.pop("out", None)
        # tensor inputs may also be passed by keyword (name=...)
        pos = list(args)
        names = op.arg_names if not op.variable_args else []
        for nm in names:
            if nm in kwargs:
                pos.append(kwargs.pop(nm))
        return invoke(op_name, pos, kwargs, out=out)

    fn.__name__ = op_name
    from .ops.opdocs import op_doc

    fn.__doc__ = "%s\n\n%s" % (
        "Imperative op %r (TPU-native)." % op_name,
        op_doc(op, aliases=[a for a, t in _ALIAS.items() if t == op.name]))
    return fn


def _init_module():
    mod = sys.modules[__name__]
    for name in list(OP_REGISTRY) + list(_ALIAS):
        if not hasattr(mod, name):
            setattr(mod, name, _make_nd_fn(name))
        public = name[1:] if name.startswith("_") else name
        if public and not hasattr(mod, public):
            setattr(mod, public, _make_nd_fn(name))


# populated by mxnet_tpu/__init__ after all op modules import


def multiply(lhs, rhs):
    """Elementwise product (parity: ``ndarray.py:multiply``)."""
    return lhs * rhs


def subtract(lhs, rhs):
    """Elementwise difference (parity: ``ndarray.py:subtract``)."""
    return lhs - rhs


def divide(lhs, rhs):
    """Elementwise quotient (parity: ``ndarray.py:divide``)."""
    return lhs / rhs


true_divide = divide


def moveaxis(tensor, source, destination):
    """Move an axis to a new position (parity: ``ndarray.py:moveaxis``;
    numpy axis semantics — out-of-range axes raise)."""
    nd_ = tensor.ndim

    def _norm(ax, name):
        if not -nd_ <= ax < nd_:
            raise ValueError("%s axis %d out of range for %d-d array"
                             % (name, ax, nd_))
        return ax + nd_ if ax < 0 else ax

    src = _norm(source, "source")
    dst = _norm(destination, "destination")
    axes = list(range(nd_))
    axes.insert(dst, axes.pop(src))
    return NDArray(jnp.transpose(tensor._data, axes), tensor.context)


def imdecode(str_img, clip_rect=(0, 0, 0, 0), out=None, index=0,
             channels=3, mean=None):
    """Decode an encoded image to NDArray (parity: ``ndarray.py:imdecode``).
    Unsupported reference options raise rather than being silently
    ignored; plain decodes delegate to the image package."""
    if out is not None or index != 0 or tuple(clip_rect) != (0, 0, 0, 0) \
            or channels != 3 or mean is not None:
        raise MXNetError(
            "imdecode: only plain 3-channel decodes are supported here; "
            "use mx.image.imdecode + ndarray ops for crop/mean handling")
    from . import image as _image

    return array(_image.imdecode_bytes(str_img))
