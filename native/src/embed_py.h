/*!
 * Shared embedded-CPython plumbing for the C ABI (predict + full C API).
 * The reference's c_api.cc/c_predict_api.cc sit on the same engine
 * internals; here both sit on the same embedded interpreter + host
 * NDArray container.
 */
#ifndef MXTPU_EMBED_PY_H_
#define MXTPU_EMBED_PY_H_

#ifndef PY_SSIZE_T_CLEAN
#define PY_SSIZE_T_CLEAN  /* Py_ssize_t lengths for '#' formats */
#endif
#include <Python.h>

#include <cstdint>
#include <string>
#include <vector>

namespace mxtpu_capi {

/* Host NDArray backing MXTPUNDArrayHandle.  float32 (the overwhelmingly
 * common case) lives in `data`; other dtypes (MXTPU_DTYPE_* codes in
 * c_api.h, the reference's mshadow TypeFlag order) carry raw bytes in
 * `raw` so bf16/f16/int tensors cross the ABI losslessly. */
struct NDArr {
  std::vector<int64_t> shape;
  std::vector<float> data;   /* payload iff dtype == 0 (float32) */
  int dtype = 0;             /* MXTPU_DTYPE_* */
  std::vector<uint8_t> raw;  /* payload iff dtype != 0 */

  void *bytes() {
    return dtype == 0 ? static_cast<void *>(data.data())
                      : static_cast<void *>(raw.data());
  }
  size_t nbytes() const {
    return dtype == 0 ? data.size() * sizeof(float) : raw.size();
  }
};

inline NDArr *nd(void *h) { return static_cast<NDArr *>(h); }

/* Element width for an MXTPU_DTYPE_* code (0 = unknown). */
inline size_t dtype_size(int dtype) {
  switch (dtype) {
    case 0: case 4: return 4;          /* f32, i32 */
    case 1: case 6: return 8;          /* f64, i64 */
    case 2: case 7: return 2;          /* f16, bf16 */
    case 3: case 5: return 1;          /* u8, i8 */
    default: return 0;
  }
}

/* Initialize the process-lifetime interpreter exactly once (no Finalize:
 * handles may outlive any scope). */
void ensure_python();

/* Fetch-and-clear the pending Python exception as text. */
std::string py_error();

/* Thread-local last-error slot shared by the predict and full C APIs. */
void set_err(const std::string &m);
const char *last_err();

/* RAII GIL scope. */
struct Gil {
  PyGILState_STATE st;
  Gil() { st = PyGILState_Ensure(); }
  ~Gil() { PyGILState_Release(st); }
};

}  // namespace mxtpu_capi

#endif  /* MXTPU_EMBED_PY_H_ */
