"""Inference throughput benchmark on synthetic data (parity: reference
``example/image-classification/benchmark_score.py``)."""

import argparse
import logging
import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)
sys.path.insert(0, os.path.dirname(os.path.dirname(_HERE)))  # repo root

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import models

logging.basicConfig(level=logging.INFO)


def _build_symbol(network, image_shape, num_layers, dtype):
    """One network-setup path shared by both scoring modes (host-loop and
    --device-loop must benchmark the identical configuration)."""
    kwargs = {}
    if num_layers:
        kwargs["num_layers"] = num_layers
    if network == "inception-v3":
        image_shape = (3, 299, 299)
    sym = models.get_symbol(network, num_classes=1000,
                            image_shape=image_shape, dtype=dtype, **kwargs)
    return sym, image_shape


def score(network, dev, batch_size, num_batches, image_shape=(3, 224, 224),
          num_layers=None, dtype="float32"):
    sym, image_shape = _build_symbol(network, image_shape, num_layers, dtype)
    data_shape = [("data", (batch_size,) + image_shape)]
    mod = mx.mod.Module(symbol=sym, context=dev)
    mod.bind(for_training=False, inputs_need_grad=False, data_shapes=data_shape)
    mod.init_params(initializer=mx.initializer.Xavier(magnitude=2.0))
    # device-resident synthetic batch: H2D once, not per iteration
    batch = mx.io.DataBatch(
        [mx.nd.array(np.random.uniform(-1, 1, (batch_size,) + image_shape),
                     ctx=dev)], [])
    def sync():
        # scalar fetch: the only true device sync over tunneled PJRT, and it
        # avoids timing the (slow) full-logits host transfer
        import numpy as _n
        _n.asarray(mod.get_outputs()[0]._data.ravel()[0])

    # warmup (compile)
    for _ in range(2):
        mod.forward(batch, is_train=False)
    sync()
    tic = time.time()
    for _ in range(num_batches):
        mod.forward(batch, is_train=False)
    sync()
    return num_batches * batch_size / (time.time() - tic)


def score_device_loop(network, dev, batch_size, num_batches,
                      image_shape=(3, 224, 224), num_layers=None,
                      dtype="float32"):
    """Pure-device inference throughput: ``num_batches`` forwards inside
    ONE jitted ``lax.fori_loop``, so per-batch host dispatch never enters
    the measurement.  This is the apples-to-apples number against the
    reference's local-PCIe GPUs (`benchmark_score.py`): over the
    tunneled PJRT device, per-call dispatch latency (~1-2 ms) dominates
    any sub-2ms step in the host-loop ``score`` — see the BENCH_TABLE.md
    footnote.  Each iteration's input depends on the previous output (a
    1e-30-scaled logit perturbation), so XLA can neither hoist the
    forward out of the loop nor collapse iterations."""
    import jax
    import jax.numpy as jnp

    sym, image_shape = _build_symbol(network, image_shape, num_layers, dtype)
    ex = sym.simple_bind(dev, grad_req="null",
                         data=(batch_size,) + image_shape)
    for name, arr in ex.arg_dict.items():
        if name != "data" and not name.endswith("_label"):
            mx.initializer.Xavier(magnitude=2.0)(name, arr)
    params = {k: v._data for k, v in ex.arg_dict.items() if k != "data"}
    aux = {k: v._data for k, v in ex.aux_dict.items()}
    run = ex._run  # the executor's already-built graph function
    data = jnp.asarray(np.random.uniform(
        -1, 1, (batch_size,) + image_shape).astype(np.float32))
    key = jax.random.PRNGKey(0)

    @jax.jit
    def loop(params, aux, data):
        def body(i, carry):
            acc, d = carry
            args = dict(params)
            args["data"] = d.astype(data.dtype)
            outs, _ = run(args, aux, key, False)
            m = outs[0].astype(jnp.float32).ravel()[0]
            return (acc + m, d + m * 1e-30)
        acc, d = jax.lax.fori_loop(0, num_batches, body, (0.0, data))
        return acc

    np.asarray(loop(params, aux, data))  # compile + warm
    tic = time.time()
    np.asarray(loop(params, aux, data))  # D2H scalar fetch = true sync
    return num_batches * batch_size / (time.time() - tic)


def score_pipeline(network, dev, batch_size, num_batches,
                   image_shape=(3, 224, 224), num_layers=None,
                   dtype="float32"):
    """Serving-shaped device-loop throughput: ``num_batches`` DISTINCT
    batches stacked ``[N, B, ...]`` and scanned in ONE dispatch via
    ``Predictor.forward_pipeline`` — the trainer's ``pipeline_steps``
    applied to inference.  Unlike ``score_device_loop`` (whose synthetic
    chained input isolates pure device compute), this path measures what a
    batch-window serving deployment gets: real per-batch inputs, one H2D
    of the stacked window, one dispatch, stacked logits back."""
    from mxnet_tpu import predict as _predict

    sym, image_shape = _build_symbol(network, image_shape, num_layers, dtype)
    ex = sym.simple_bind(dev, grad_req="null",
                         data=(batch_size,) + image_shape)
    for name, arr in ex.arg_dict.items():
        if name != "data" and not name.endswith("_label"):
            mx.initializer.Xavier(magnitude=2.0)(name, arr)
    pred = _predict.Predictor(
        sym.tojson(),
        {"arg:" + k: v for k, v in ex.arg_dict.items() if k != "data"}
        | {"aux:" + k: v for k, v in ex.aux_dict.items()},
        ctx=dev, input_shapes={"data": (batch_size,) + image_shape})
    stacked = {"data": np.random.uniform(
        -1, 1, (num_batches, batch_size) + image_shape).astype(np.float32)}
    pred.forward_pipeline(stacked)  # compile + warm
    tic = time.time()
    outs = pred.forward_pipeline(stacked)
    np.asarray(outs[0]).ravel()[0]  # already host-side; keep the sync idiom
    return num_batches * batch_size / (time.time() - tic)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--network", type=str, default="all")
    parser.add_argument("--batch-size", type=int, default=0)
    parser.add_argument("--num-batches", type=int, default=10)
    parser.add_argument("--dtype", type=str, default="float32")
    parser.add_argument("--device-loop", action="store_true",
                        help="run all batches inside one jitted fori_loop "
                             "(excludes per-batch tunnel dispatch latency; "
                             "the apples-to-apples number vs local-PCIe "
                             "GPUs for sub-2ms steps)")
    parser.add_argument("--pipeline", action="store_true",
                        help="serving-shaped device loop: N distinct "
                             "batches stacked and scanned in one dispatch "
                             "(Predictor.forward_pipeline)")
    args = parser.parse_args()

    import jax
    dev = mx.tpu(0) if jax.default_backend() == "tpu" else mx.cpu()
    networks = (["alexnet", "vgg", "inception-bn", "inception-v3",
                 "resnet-50", "resnet-152"]
                if args.network == "all" else [args.network])
    batch_sizes = [args.batch_size] if args.batch_size else [1, 32, 64, 128]
    if args.device_loop and args.pipeline:
        parser.error("--device-loop and --pipeline are exclusive modes")
    fn = (score_pipeline if args.pipeline
          else score_device_loop if args.device_loop else score)
    for net in networks:
        logging.info("network: %s", net)
        for b in batch_sizes:
            speed = fn(net, dev, b, args.num_batches, dtype=args.dtype)
            logging.info("batch size %3d, dtype %s, images/sec: %f",
                         b, args.dtype, speed)
