"""Process-global metrics registry: counters, gauges, histograms.

The paper's runtime measures itself through the engine profiler alone
(``OprExecStat``); this registry is the aggregate-side complement —
cheap, always-on numeric series for every subsystem (engine lanes,
prefetch, trainer steps, kvstore RPCs, chaos injections), rendered in
Prometheus text exposition format by :func:`dump_metrics`.

Design points:

- **Pre-resolved handles.**  ``counter(...)`` / ``.labels(...)`` return
  a handle object once; the per-event call (``inc``/``set``/``observe``)
  is a method on that handle — no registry or label-dict lookup on the
  hot path.  Hot seams (``engine.push``) resolve their handles at import
  time.
- **Env gate.**  ``MXNET_TPU_METRICS=0`` disables recording: every
  handle method is then a constant-time guard (one cached-env check and
  return, nothing else — asserted by call-count in
  ``tests/test_observability.py``).  The env var is re-read lazily by
  cache comparison, chaos-style, so tests and jobs can flip it without
  re-importing.
- **Reset keeps handles live.**  ``reset()`` zeroes values but never
  discards families or label children, so module-level pre-resolved
  handles stay wired after a test-suite reset.
"""

from __future__ import annotations

import os
import threading

__all__ = ["Registry", "REGISTRY", "counter", "gauge", "histogram",
           "dump_metrics", "reset_metrics", "metrics_enabled",
           "DEFAULT_BUCKETS"]

#: Prometheus's conventional latency buckets (seconds).
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0)

# --- env gate (lazy, cache-compared like chaos._active_rules) -------------

_env_lock = threading.Lock()
_env_cache = object()   # never equal to a str/None: first call refreshes
_env_enabled = True


def metrics_enabled():
    """True unless ``MXNET_TPU_METRICS`` is 0/false/off.  This is the
    single guard every handle method checks first; keep it one dict.get
    plus an identity compare on the cached string."""
    global _env_cache, _env_enabled
    env = os.environ.get("MXNET_TPU_METRICS")
    if env != _env_cache:
        with _env_lock:
            _env_cache = env
            _env_enabled = ((env or "1").strip().lower()
                            not in ("0", "false", "off"))
    return _env_enabled


# --- value formatting ------------------------------------------------------

def _fmt_value(v):
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return "%d" % int(f) if f == int(f) and abs(f) < 1e15 else repr(f)


def _fmt_label(v):
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
                 .replace("\n", "\\n")


def _series(name, label_names, label_values, suffix="", extra=()):
    pairs = list(zip(label_names, label_values)) + list(extra)
    if not pairs:
        return name + suffix
    return "%s%s{%s}" % (name, suffix, ",".join(
        '%s="%s"' % (k, _fmt_label(v)) for k, v in pairs))


# --- handles ---------------------------------------------------------------

class Counter(object):
    """Monotone counter handle (one label-value combination)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, v=1.0):
        if not metrics_enabled():
            return
        self._record(v)

    def _record(self, v):
        if v < 0:
            raise ValueError("counters only go up (got %r)" % v)
        with self._lock:
            self._value += v

    @property
    def value(self):
        return self._value

    def _reset(self):
        with self._lock:
            self._value = 0.0

    def _render(self, name, label_names, label_values, w,
                exemplars=False):
        w("%s %s\n" % (_series(name, label_names, label_values),
                       _fmt_value(self._value)))


class Gauge(object):
    """Set/inc/dec gauge handle."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v):
        if not metrics_enabled():
            return
        self._record(v, "set")

    def inc(self, v=1.0):
        if not metrics_enabled():
            return
        self._record(v, "inc")

    def dec(self, v=1.0):
        if not metrics_enabled():
            return
        self._record(v, "dec")

    def _record(self, v, op):
        with self._lock:
            if op == "set":
                self._value = float(v)
            elif op == "inc":
                self._value += v
            else:
                self._value -= v

    @property
    def value(self):
        return self._value

    def _reset(self):
        with self._lock:
            self._value = 0.0

    def _render(self, name, label_names, label_values, w,
                exemplars=False):
        w("%s %s\n" % (_series(name, label_names, label_values),
                       _fmt_value(self._value)))


class Histogram(object):
    """Cumulative-bucket histogram handle (Prometheus semantics).

    ``observe(v, exemplar=...)`` attaches an OpenMetrics-style exemplar
    — the LAST trace token seen per bucket — so a latency blip in the
    exposition links to a concrete trace
    (``serving_request_seconds`` carries the request's root-span wire
    token).  Exemplars render only on request
    (``Registry.render(exemplars=True)``): the plain exposition stays
    Prometheus-0.0.4 parseable."""

    __slots__ = ("_lock", "_buckets", "_counts", "_sum", "_count",
                 "_exemplars")

    def __init__(self, buckets):
        self._lock = threading.Lock()
        self._buckets = buckets        # sorted upper bounds, no +Inf
        self._counts = [0] * len(buckets)
        self._sum = 0.0
        self._count = 0
        self._exemplars = {}           # bucket upper bound -> (token, v)

    def observe(self, v, exemplar=None):
        if not metrics_enabled():
            return
        self._record(v, exemplar)

    def _record(self, v, exemplar=None):
        v = float(v)
        with self._lock:
            ub_hit = float("inf")
            for i, ub in enumerate(self._buckets):
                if v <= ub:
                    self._counts[i] += 1
                    ub_hit = ub
                    break
            self._sum += v
            self._count += 1
            if isinstance(exemplar, str) and exemplar:
                self._exemplars[ub_hit] = (exemplar, v)

    def exemplars(self):
        """Snapshot ``{bucket_upper_bound: (trace_token, value)}`` of
        the last exemplar recorded per bucket (``float("inf")`` keys
        the overflow bucket)."""
        with self._lock:
            return dict(self._exemplars)

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def percentile(self, q):
        """Bucket-resolution quantile estimate in [0, 1] (upper bound of
        the bucket holding the q-th observation); None when empty."""
        with self._lock:
            total = self._count
            if not total:
                return None
            rank = q * total
            seen = 0
            for i, ub in enumerate(self._buckets):
                seen += self._counts[i]
                if seen >= rank:
                    return ub
            return float("inf")

    def _reset(self):
        with self._lock:
            self._counts = [0] * len(self._buckets)
            self._sum = 0.0
            self._count = 0
            self._exemplars = {}

    @staticmethod
    def _exm(ex):
        return (" # {trace_id=\"%s\"} %s" % (ex[0], _fmt_value(ex[1]))
                if ex is not None else "")

    def _render(self, name, label_names, label_values, w,
                exemplars=False):
        with self._lock:
            counts, total, ssum = list(self._counts), self._count, self._sum
            exm = dict(self._exemplars) if exemplars else {}
        cum = 0
        for ub, n in zip(self._buckets, counts):
            cum += n
            w("%s %d%s\n" % (_series(name, label_names, label_values,
                                     "_bucket", [("le", _fmt_value(ub))]),
                             cum, self._exm(exm.get(ub))))
        w("%s %d%s\n" % (_series(name, label_names, label_values, "_bucket",
                                 [("le", "+Inf")]), total,
                         self._exm(exm.get(float("inf")))))
        w("%s %s\n" % (_series(name, label_names, label_values, "_sum"),
                       _fmt_value(ssum)))
        w("%s %d\n" % (_series(name, label_names, label_values, "_count"),
                       total))


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Family(object):
    """One metric name: kind, help text, label schema, and the child
    handles (one per label-value combination).  ``labels()`` caches, so
    repeated resolution of the same combination returns the SAME handle
    and callers may pre-resolve once and record forever."""

    def __init__(self, name, help, kind, label_names=(), buckets=None):
        self.name = name
        self.help = help
        self.kind = kind
        self.label_names = tuple(label_names)
        self.buckets = (tuple(sorted(buckets)) if buckets is not None
                        else DEFAULT_BUCKETS) if kind == "histogram" \
            else None
        self._lock = threading.Lock()
        self._children = {}
        if not self.label_names:
            self._default = self._make()
            self._children[()] = self._default
        else:
            self._default = None

    def _make(self):
        if self.kind == "histogram":
            return Histogram(self.buckets)
        return _KINDS[self.kind]()

    def labels(self, *values):
        key = tuple(str(v) for v in values)
        if len(key) != len(self.label_names):
            raise ValueError(
                "%s expects labels %s, got %d value(s)"
                % (self.name, self.label_names, len(key)))
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._make()
                    self._children[key] = child
        return child

    # unlabeled families proxy the single child so the family object IS
    # the hot-path handle
    def inc(self, v=1.0):
        self._default.inc(v)

    def set(self, v):
        self._default.set(v)

    def dec(self, v=1.0):
        self._default.dec(v)

    def observe(self, v, exemplar=None):
        self._default.observe(v, exemplar)

    @property
    def value(self):
        return self._default.value

    @property
    def count(self):
        return self._default.count

    def percentile(self, q):
        return self._default.percentile(q)

    def total(self):
        """Sum of every label-child's value (counters/gauges) — the
        family-wide aggregate, e.g. ``chaos_fired_total`` over all
        sites."""
        with self._lock:
            children = list(self._children.values())
        return sum(c.value for c in children)

    def _reset(self):
        with self._lock:
            for child in self._children.values():
                child._reset()

    def _render(self, w, exemplars=False):
        w("# HELP %s %s\n" % (self.name,
                              self.help.replace("\n", " ").strip()))
        w("# TYPE %s %s\n" % (self.name, self.kind))
        with self._lock:
            items = sorted(self._children.items())
        for key, child in items:
            child._render(self.name, self.label_names, key, w,
                          exemplars=exemplars)


class Registry(object):
    """Thread-safe family registry.  Registering an existing name with a
    matching (kind, labels) signature returns the SAME family, so every
    module can declare the metrics it emits without coordination."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families = {}

    def _register(self, name, help, kind, label_names, buckets=None):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.label_names != tuple(label_names):
                    raise ValueError(
                        "metric %r re-registered as %s%s but exists as %s%s"
                        % (name, kind, tuple(label_names), fam.kind,
                           fam.label_names))
                return fam
            fam = Family(name, help, kind, label_names, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name, help, labels=()):
        return self._register(name, help, "counter", labels)

    def gauge(self, name, help, labels=()):
        return self._register(name, help, "gauge", labels)

    def histogram(self, name, help, labels=(), buckets=None):
        return self._register(name, help, "histogram", labels, buckets)

    def get(self, name):
        return self._families.get(name)

    def render(self, exemplars=False):
        """Prometheus text exposition (version 0.0.4) of every family.
        ``exemplars=True`` appends OpenMetrics-style exemplar
        annotations after histogram bucket samples (opt-in: the default
        exposition stays strictly 0.0.4)."""
        import io

        buf = io.StringIO()
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        for fam in fams:
            fam._render(buf.write, exemplars=exemplars)
        return buf.getvalue()

    def reset(self):
        """Zero every recorded value; families and pre-resolved handles
        survive (tests isolate state without unwiring instrumentation)."""
        with self._lock:
            fams = list(self._families.values())
        for fam in fams:
            fam._reset()


#: The process-global registry all runtime instrumentation records into.
REGISTRY = Registry()


def counter(name, help, labels=()):
    """Register (or fetch) a process-global counter family."""
    return REGISTRY.counter(name, help, labels)


def gauge(name, help, labels=()):
    """Register (or fetch) a process-global gauge family."""
    return REGISTRY.gauge(name, help, labels)


def histogram(name, help, labels=(), buckets=None):
    """Register (or fetch) a process-global histogram family."""
    return REGISTRY.histogram(name, help, labels, buckets)


def dump_metrics(exemplars=False):
    """Snapshot the global registry as Prometheus text exposition."""
    return REGISTRY.render(exemplars=exemplars)


def reset_metrics():
    """Zero the global registry (handles stay live — see
    :meth:`Registry.reset`) and drop the memory ledger's bookings —
    a booking that outlived its zeroed gauges would resurrect at the
    next sample and poison the reconcile gate."""
    REGISTRY.reset()
    from . import memory as _memory

    _memory._reset_ledger()
