"""Checkpoint helpers + BatchEndParam (parity: reference
``python/mxnet/model.py``).

Checkpoint format keeps the reference's two-file contract
(``model.py:319-349``): ``prefix-symbol.json`` (graph JSON, same schema) and
``prefix-%04d.params`` (name->array map with ``arg:``/``aux:`` prefixes; npz
container instead of dmlc binary — same names, same round-trip API).
"""

from __future__ import annotations

import logging
from collections import namedtuple

import numpy as _np

from . import chaos
from . import ndarray as nd
from . import symbol as sym
from .base import MXNetError
from .ndarray import NDArray

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint", "FeedForward"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


# per-path write-ordering variables: checkpoint writes run on the engine's
# IO lane (overlapping training), and any load of the same path becomes a
# read-after-write dependency instead of a race
_ckpt_vars = {}
# async write failures, surfaced at the next checkpoint interaction (the
# engine callback cannot raise across the C ABI)
_ckpt_errors = {}


def _raise_pending_ckpt_error():
    if _ckpt_errors:
        path, exc = next(iter(_ckpt_errors.items()))
        del _ckpt_errors[path]
        raise IOError("async checkpoint write to %r failed: %s"
                      % (path, exc)) from exc


def wait_for_checkpoint(param_path):
    """Block until any in-flight engine write of ``param_path`` lands (and
    surface its error).  Every consumer that opens a ``.params`` file
    directly — rather than via :func:`load_checkpoint` — must call this
    first (read-after-write ordering for the async checkpoint writes)."""
    from . import engine

    engine.wait_for_var(_ckpt_var(param_path))
    _raise_pending_ckpt_error()


def _ckpt_var(path):
    from . import engine

    import os
    key = os.path.abspath(path)
    if key not in _ckpt_vars:
        _ckpt_vars[key] = engine.new_variable()
    return _ckpt_vars[key]


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Save symbol + params (parity: ``model.py:save_checkpoint``).

    The params snapshot is taken synchronously (so later in-place updates
    can't corrupt it) but the file write runs on the dependency engine's
    IO lane, overlapping the next training steps — the engine-ordered
    checkpoint write of the reference (``NDArray::Save`` pushed with the
    array vars as read deps)."""
    from . import engine

    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    # snapshot on the calling thread: device fetch + copy
    arrays = {("arg:%s" % k): v.asnumpy() for k, v in arg_params.items()}
    arrays.update({("aux:%s" % k): v.asnumpy()
                   for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)

    _raise_pending_ckpt_error()

    def write():
        try:
            # chaos site: drop = the write silently never lands (surfaced
            # as a missing file at load), raise = a failed write captured
            # into _ckpt_errors like any real IO failure
            chaos.visit("checkpoint.write", name=param_name)
            nd._save_npz(param_name, arrays, "dict")  # atomic temp+rename
            logging.info("Saved checkpoint to \"%s\"", param_name)
        except chaos.ChaosDrop:
            logging.warning("chaos: checkpoint write %r dropped", param_name)
        except BaseException as exc:  # surfaced at the next save/load
            _ckpt_errors[param_name] = exc

    engine.push(write, mutable_vars=[_ckpt_var(param_name)],
                prop=engine.FnProperty.IO, name="ckpt_write")


def load_checkpoint(prefix, epoch):
    """Load symbol + params (parity: ``model.py:load_checkpoint``)."""
    symbol = sym.load("%s-symbol.json" % prefix)
    param_name = "%s-%04d.params" % (prefix, epoch)
    wait_for_checkpoint(param_name)
    save_dict = nd.load(param_name)
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        if tp == "aux":
            aux_params[name] = v
    return (symbol, arg_params, aux_params)


def _create_kvstore(kvstore, num_device, arg_params):
    """Create kvstore from spec (parity: ``model.py:_create_kvstore``)."""
    from . import kvstore as kvs

    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(_np.prod(p.shape) for p in arg_params.values())
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return (kv, update_on_kvstore)


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    """(parity: ``model.py:_initialize_kvstore``)"""
    for idx, param_on_devs in enumerate(param_arrays):
        kvstore.init(idx, arg_params[param_names[idx]])
        if update_on_kvstore:
            kvstore.pull(idx, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore):
    """Layer-priority push/pull (parity: ``model.py:86-110``).

    All pushes are issued before any pull so the engine-backed kvstore can
    run per-key optimizer ops concurrently on its worker pool; each pull
    then waits only on its own key's var (the reference overlaps exactly
    this way via per-layer priorities)."""
    live = [(index, pair) for index, pair in
            enumerate(zip(param_arrays, grad_arrays))
            if pair[1][0] is not None]
    for index, (_arg_list, grad_list) in live:
        kvstore.push(index, grad_list, priority=-index)
    for index, (arg_list, _grad_list) in live:
        kvstore.pull(index, arg_list, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device, kvstore=None):
    """(parity: ``model.py:_update_params``)"""
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        if kvstore:
            kvstore.push(index, grad_list, priority=-index)
            kvstore.pull(index, grad_list, priority=-index)
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            updater(index * num_device + k, g, w)


class FeedForward(object):
    """Legacy estimator API (parity: ``model.py:FeedForward``, deprecated in
    the reference too — thin wrapper over Module)."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from .initializer import Uniform

        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer if initializer is not None else Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.kwargs = kwargs.copy()
        self._module = None

    def _get_module(self, data_iter):
        from .module.module import Module

        ctx = self.ctx if self.ctx is not None else [None]
        if not isinstance(ctx, (list, tuple)):
            ctx = [ctx]
        mod = Module(self.symbol, context=ctx,
                     data_names=[d[0] for d in data_iter.provide_data],
                     label_names=[l[0] for l in data_iter.provide_label])
        return mod

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        from .io import NDArrayIter

        if not hasattr(X, "provide_data"):
            X = NDArrayIter(X, y, batch_size=self.numpy_batch_size, shuffle=True)
        mod = self._get_module(X)
        mod.fit(X, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer, optimizer_params=self.kwargs,
                initializer=self.initializer,
                arg_params=self.arg_params, aux_params=self.aux_params,
                begin_epoch=self.begin_epoch, num_epoch=self.num_epoch)
        self._module = mod
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        from .io import NDArrayIter

        if not hasattr(X, "provide_data"):
            X = NDArrayIter(X, batch_size=self.numpy_batch_size)
        if reset:
            X.reset()
        mod = self._module
        if mod is None:
            mod = self._get_module(X)
            mod.bind(X.provide_data, for_training=False)
            mod.set_params(self.arg_params or {}, self.aux_params or {},
                           allow_missing=False)
            self._module = mod
        if return_data:
            # reference contract: (outputs, datas, labels), gathered per batch
            outs, datas, labels = [], [], []
            for nbatch, batch in enumerate(X):
                if num_batch is not None and nbatch == num_batch:
                    break
                mod.forward(batch, is_train=False)
                keep = batch.data[0].shape[0] - (batch.pad or 0)
                outs.append(mod.get_outputs()[0].asnumpy()[:keep])
                datas.append(batch.data[0].asnumpy()[:keep])
                if batch.label:
                    labels.append(batch.label[0].asnumpy()[:keep])
            import numpy as _np

            return (_np.concatenate(outs), _np.concatenate(datas),
                    _np.concatenate(labels) if labels else None)
        # always_output_list: a bare NDArray here would be iterated row by
        # row below — hundreds of eager per-row gathers
        outputs = mod.predict(X, num_batch=num_batch, always_output_list=True)
        if len(outputs) == 1:
            return outputs[0].asnumpy()
        return [o.asnumpy() for o in outputs]

    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self.num_epoch
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params, self.aux_params)

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, epoch_size=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               work_load_list=None, eval_end_callback=None,
               eval_batch_end_callback=None, **kwargs):
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger, work_load_list=work_load_list,
                  eval_end_callback=eval_end_callback,
                  eval_batch_end_callback=eval_batch_end_callback)
        return model
