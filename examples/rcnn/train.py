"""Faster R-CNN end-to-end training slice (parity: reference
``example/rcnn/`` — RPN + Proposal + ROIPooling + python ProposalTarget
op + RCNN head, ``src/operator/contrib/proposal.cc``,
``example/rcnn/rcnn/symbol/proposal_target.py``).

Synthetic detection task: each image carries ONE axis-aligned solid
rectangle whose fill intensity pattern encodes its class; the network
must localize it (RPN + proposals) and classify the pooled region
(RCNN head).  The whole two-stage detector trains as one Symbol graph:

    backbone convs -> RPN conv -> {rpn_cls SoftmaxOutput,
                                   rpn_bbox smooth_l1 (MakeLoss)}
                     \\-> Proposal (static-shape TPU redesign)
                          -> ProposalTarget (python CustomOp, host)
                          -> ROIPooling -> FC -> rcnn_cls SoftmaxOutput

    python examples/rcnn/train.py [--num-epochs 6] [--tpus 0]

NB the ProposalTarget CustomOp lowers to host callbacks; tunneled dev
backends may not support them — default context is cpu (real TPU
runtimes do support host callbacks; pass --tpus 1 there).
"""

import argparse
import logging
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(_HERE)))

def _want_tpu(argv):
    for i, a in enumerate(argv):
        if a == "--tpus" and i + 1 < len(argv):
            return argv[i + 1] != "0"
        if a.startswith("--tpus="):
            return a.split("=", 1)[1] != "0"
    return False


if __name__ == "__main__" and not _want_tpu(sys.argv[1:]):
    # the ProposalTarget CustomOp needs host callbacks; force the CPU
    # platform BEFORE the first backend touch (tunneled dev backends lack
    # send/recv callback support — real TPU runtimes have it; pass
    # --tpus 1 there)
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

import mxnet_tpu as mx

# ---- task geometry -------------------------------------------------------
IM = 64                 # image side
STRIDE = 8              # backbone downsampling
FEAT = IM // STRIDE     # feature map side
SCALES = (2.0, 4.0)     # anchor sides = STRIDE*scale = 16, 32 px
RATIOS = (1.0,)
K = len(SCALES) * len(RATIOS)
A = FEAT * FEAT * K     # anchors per image
POST_NMS = 8            # proposals kept per image (static shape)
NUM_CLASSES = 3         # foreground classes; rcnn head adds background=0


def _base_anchors():
    """(K,4) anchors centered at (0,0) in x1,y1,x2,y2 (stride coords)."""
    out = []
    for s in SCALES:
        for r in RATIOS:
            side = STRIDE * s
            w, h = side * np.sqrt(r), side / np.sqrt(r)
            cx = cy = (STRIDE - 1) / 2.0
            out.append([cx - 0.5 * (w - 1), cy - 0.5 * (h - 1),
                        cx + 0.5 * (w - 1), cy + 0.5 * (h - 1)])
    return np.array(out, np.float32)


def _all_anchors():
    base = _base_anchors()
    shifts = np.arange(FEAT, dtype=np.float32) * STRIDE
    sy, sx = np.meshgrid(shifts, shifts, indexing="ij")
    shift = np.stack([sx, sy, sx, sy], axis=-1).reshape(-1, 1, 4)
    return (shift + base[None]).reshape(-1, 4)  # (A,4), HW-major then K


def _iou(boxes, gt):
    """IoU of (N,4) boxes vs one (4,) gt box."""
    x1 = np.maximum(boxes[:, 0], gt[0])
    y1 = np.maximum(boxes[:, 1], gt[1])
    x2 = np.minimum(boxes[:, 2], gt[2])
    y2 = np.minimum(boxes[:, 3], gt[3])
    iw = np.maximum(x2 - x1 + 1, 0)
    ih = np.maximum(y2 - y1 + 1, 0)
    inter = iw * ih
    ab = (boxes[:, 2] - boxes[:, 0] + 1) * (boxes[:, 3] - boxes[:, 1] + 1)
    ag = (gt[2] - gt[0] + 1) * (gt[3] - gt[1] + 1)
    return inter / (ab + ag - inter)


def _bbox_transform(anchors, gt):
    """Regression targets from anchors to gt (reference bbox_transform)."""
    aw = anchors[:, 2] - anchors[:, 0] + 1
    ah = anchors[:, 3] - anchors[:, 1] + 1
    ax = anchors[:, 0] + 0.5 * (aw - 1)
    ay = anchors[:, 1] + 0.5 * (ah - 1)
    gw = gt[2] - gt[0] + 1
    gh = gt[3] - gt[1] + 1
    gx = gt[0] + 0.5 * (gw - 1)
    gy = gt[1] + 0.5 * (gh - 1)
    return np.stack([(gx - ax) / aw, (gy - ay) / ah,
                     np.log(gw / aw), np.log(gh / ah)], axis=1)


# ---- synthetic data ------------------------------------------------------

def make_batch(rng, batch):
    """Images with one class-coded rectangle + RPN training targets."""
    anchors = _all_anchors()
    imgs = rng.uniform(-0.2, 0.2, (batch, 3, IM, IM)).astype(np.float32)
    gts = np.zeros((batch, 5), np.float32)       # [cls,x1,y1,x2,y2]
    rpn_label = np.full((batch, A), -1, np.float32)
    rpn_bbox_target = np.zeros((batch, A, 4), np.float32)
    rpn_bbox_weight = np.zeros((batch, A, 4), np.float32)
    for b in range(batch):
        cls = rng.randint(1, NUM_CLASSES + 1)
        side = rng.randint(14, 30)
        x1 = rng.randint(2, IM - side - 2)
        y1 = rng.randint(2, IM - side - 2)
        gt = np.array([x1, y1, x1 + side, y1 + side], np.float32)
        # class-coded fill: distinct per-channel intensities
        fill = {1: (1.0, -1.0, -1.0), 2: (-1.0, 1.0, -1.0),
                3: (-1.0, -1.0, 1.0)}[cls]
        for c in range(3):
            imgs[b, c, y1:y1 + side, x1:x1 + side] = fill[c]
        gts[b] = [cls, gt[0], gt[1], gt[2], gt[3]]
        iou = _iou(anchors, gt)
        fg = iou >= 0.5
        fg[np.argmax(iou)] = True
        # balanced anchor sampling (reference AnchorLoader: 256 anchors,
        # <=50% fg): training on every bg anchor drowns the handful of fg
        # ones and the learned scores stop ranking anchors near the object
        bg_pool = np.flatnonzero(~fg & (iou < 0.3))
        n_bg = min(len(bg_pool), max(3 * int(fg.sum()), 24))
        bg_sel = rng.choice(bg_pool, size=n_bg, replace=False)
        rpn_label[b, bg_sel] = 0
        rpn_label[b, fg] = 1
        rpn_bbox_target[b, fg] = _bbox_transform(anchors[fg], gt)
        rpn_bbox_weight[b, fg] = 1.0
    # reorder anchor axis (HW-major,K) -> the head's (K,HW) layout used by
    # the (B,2,A) reshape of rpn_cls_score and (B,K*4,H,W) bbox pred
    perm = (np.arange(A).reshape(FEAT * FEAT, K).T).reshape(-1)
    return (imgs, gts, rpn_label[:, perm],
            rpn_bbox_target[:, perm].transpose(0, 2, 1).reshape(
                batch, 4 * K if False else -1, FEAT, FEAT),
            rpn_bbox_weight[:, perm].transpose(0, 2, 1).reshape(
                batch, -1, FEAT, FEAT))


# ---- ProposalTarget as a python CustomOp (reference proposal_target.py) --

class ProposalTarget(mx.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        rois = in_data[0].asnumpy()      # (B*POST,5)
        gts = in_data[1].asnumpy()       # (B,5)
        labels = np.zeros((rois.shape[0],), np.float32)
        for i, roi in enumerate(rois):
            gt = gts[int(roi[0])]
            if _iou(roi[None, 1:5], gt[1:5])[0] >= 0.5:
                labels[i] = gt[0]
        self.assign(out_data[0], req[0], mx.nd.array(labels))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        for g in in_grad:
            g[:] = 0.0


@mx.operator.register("proposal_target")
class ProposalTargetProp(mx.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["rois", "gt_boxes"]

    def list_outputs(self):
        return ["label"]

    def infer_shape(self, in_shape):
        return in_shape, [(in_shape[0][0],)], []

    def create_operator(self, ctx, shapes, dtypes):
        return ProposalTarget()


# ---- the symbol ----------------------------------------------------------

def get_symbol(batch):
    data = mx.sym.Variable("data")
    gt = mx.sym.Variable("gt_boxes")
    rpn_label = mx.sym.Variable("rpn_label")
    bbox_t = mx.sym.Variable("rpn_bbox_target")
    bbox_w = mx.sym.Variable("rpn_bbox_weight")
    im_info = mx.sym.Variable("im_info")

    body = data
    for i, f in enumerate((16, 32, 32)):
        body = mx.sym.Convolution(body, num_filter=f, kernel=(3, 3),
                                  stride=(2, 2), pad=(1, 1),
                                  name="conv%d" % i)
        body = mx.sym.Activation(body, act_type="relu")

    rpn = mx.sym.Activation(
        mx.sym.Convolution(body, num_filter=32, kernel=(3, 3), pad=(1, 1),
                           name="rpn_conv"), act_type="relu")
    rpn_cls_score = mx.sym.Convolution(rpn, num_filter=2 * K, kernel=(1, 1),
                                       name="rpn_cls_score")
    rpn_bbox_pred = mx.sym.Convolution(rpn, num_filter=4 * K, kernel=(1, 1),
                                       name="rpn_bbox_pred")

    # RPN classification over anchors (reference: reshape to (B,2,-1))
    score_rs = mx.sym.reshape(rpn_cls_score, shape=(batch, 2, -1))
    rpn_cls = mx.sym.SoftmaxOutput(score_rs, rpn_label, multi_output=True,
                                   use_ignore=True, ignore_label=-1,
                                   normalization="valid", name="rpn_cls")
    # RPN box regression on fg anchors
    bbox_l1 = mx.sym.smooth_l1(
        mx.sym.broadcast_mul(bbox_w, rpn_bbox_pred - bbox_t), scalar=3.0)
    rpn_bbox_loss = mx.sym.MakeLoss(mx.sym.sum(bbox_l1),
                                    grad_scale=1.0 / (batch * 8),
                                    name="rpn_bbox_loss")

    # proposals from the (blocked-grad) RPN outputs
    cls_act = mx.sym.SoftmaxActivation(mx.sym.BlockGrad(rpn_cls_score),
                                       mode="channel")
    from mxnet_tpu.contrib import sym as contrib_sym

    rois = contrib_sym.Proposal(
        cls_prob=cls_act, bbox_pred=mx.sym.BlockGrad(rpn_bbox_pred),
        im_info=im_info, feature_stride=STRIDE, scales=SCALES,
        ratios=RATIOS, rpn_pre_nms_top_n=64,
        rpn_post_nms_top_n=POST_NMS, rpn_min_size=4, name="rois")

    # host-side matching of proposals to gt (python CustomOp)
    rcnn_label = mx.sym.Custom(rois, gt, op_type="proposal_target",
                               name="rcnn_label")

    pooled = mx.sym.ROIPooling(body, rois, pooled_size=(4, 4),
                               spatial_scale=1.0 / STRIDE, name="roi_pool")
    fc = mx.sym.Activation(
        mx.sym.FullyConnected(mx.sym.Flatten(pooled), num_hidden=64,
                              name="rcnn_fc"), act_type="relu")
    rcnn_cls = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(fc, num_hidden=NUM_CLASSES + 1,
                              name="rcnn_score"),
        mx.sym.BlockGrad(rcnn_label), name="rcnn_cls")

    return mx.sym.Group([rpn_cls, rpn_bbox_loss, rcnn_cls,
                         mx.sym.BlockGrad(rois),
                         mx.sym.BlockGrad(rcnn_label)])


def train(num_epochs=6, batch=8, ctx=None, lr=0.02, seed=0, log=True):
    ctx = ctx or mx.cpu()
    rng = np.random.RandomState(seed)
    # initializers draw from the global numpy stream (reference behavior);
    # pin it so the run is reproducible under any harness
    np.random.seed(seed + 1)
    sym = get_symbol(batch)
    ex = sym.simple_bind(
        ctx, data=(batch, 3, IM, IM), gt_boxes=(batch, 5),
        rpn_label=(batch, A), rpn_bbox_target=(batch, 4 * K, FEAT, FEAT),
        rpn_bbox_weight=(batch, 4 * K, FEAT, FEAT), im_info=(batch, 3),
        grad_req={n: ("null" if n in ("data", "gt_boxes", "rpn_label",
                                      "rpn_bbox_target", "rpn_bbox_weight",
                                      "im_info") else "write")
                  for n in sym.list_arguments()})
    init = mx.initializer.Xavier(magnitude=2.0)
    for name, arr in ex.arg_dict.items():
        if name not in ("data", "gt_boxes", "rpn_label", "rpn_bbox_target",
                        "rpn_bbox_weight", "im_info"):
            init(mx.initializer.InitDesc(name), arr)
    opt = mx.optimizer.SGD(learning_rate=lr, momentum=0.9, wd=1e-4,
                           rescale_grad=1.0 / batch,
                           lr_scheduler=mx.lr_scheduler.FactorScheduler(
                               step=24 * 4, factor=0.5))
    updater = mx.optimizer.get_updater(opt)
    im_info = np.tile(np.array([IM, IM, 1.0], np.float32), (batch, 1))

    stats = {}
    for epoch in range(num_epochs):
        rpn_hits = rpn_tot = rcnn_hits = rcnn_tot = fg_hits = fg_tot = 0
        ious = []
        for _ in range(24):
            imgs, gts, rl, bt, bw = make_batch(rng, batch)
            ex.arg_dict["data"][:] = imgs
            ex.arg_dict["gt_boxes"][:] = gts
            ex.arg_dict["rpn_label"][:] = rl
            ex.arg_dict["rpn_bbox_target"][:] = bt
            ex.arg_dict["rpn_bbox_weight"][:] = bw
            ex.arg_dict["im_info"][:] = im_info
            ex.forward(is_train=True)
            ex.backward()
            for i, name in enumerate(sorted(ex.grad_dict)):
                g = ex.grad_dict[name]
                if g is not None:
                    updater(i, g, ex.arg_dict[name])
            outs = [o.asnumpy() for o in ex.outputs]
            rpn_prob, _, rcnn_prob, rois, rcnn_label = outs
            pred = rpn_prob.argmax(axis=1).reshape(batch, A)
            mask = rl >= 0
            rpn_hits += int((pred[mask] == rl[mask]).sum())
            rpn_tot += int(mask.sum())
            rcnn_pred = rcnn_prob.argmax(axis=1)
            rcnn_hits += int((rcnn_pred == rcnn_label).sum())
            rcnn_tot += rcnn_label.size
            fg = rcnn_label > 0
            fg_hits += int((rcnn_pred[fg] == rcnn_label[fg]).sum())
            fg_tot += int(fg.sum())
            for b in range(batch):
                sl = rois[rois[:, 0] == b]
                if len(sl):
                    ious.append(float(_iou(sl[:, 1:5], gts[b, 1:5]).max()))
        stats = {"rpn_acc": rpn_hits / max(rpn_tot, 1),
                 "rcnn_acc": rcnn_hits / max(rcnn_tot, 1),
                 "fg_rois": fg_tot,
                 "fg_acc": fg_hits / max(fg_tot, 1),
                 "mean_best_iou": float(np.mean(ious)) if ious else 0.0}
        if log:
            logging.info("epoch %d: rpn_acc=%.3f rcnn_acc=%.3f "
                         "fg_acc=%.3f/%d best_iou=%.3f",
                         epoch, stats["rpn_acc"], stats["rcnn_acc"],
                         stats["fg_acc"], stats["fg_rois"],
                         stats["mean_best_iou"])
    return stats


def main():
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser(description="Faster R-CNN synthetic training")
    p.add_argument("--num-epochs", type=int, default=12)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--tpus", type=int, default=0)
    args = p.parse_args()
    ctx = mx.tpu(0) if args.tpus else mx.cpu()
    stats = train(num_epochs=args.num_epochs, batch=args.batch_size,
                  ctx=ctx, lr=args.lr)
    print("final:", stats)
    assert stats["rpn_acc"] > 0.85, stats
    assert stats["mean_best_iou"] > 0.3, stats
    assert stats["fg_rois"] > 0, stats  # ProposalTarget produced fg matches


if __name__ == "__main__":
    main()
