"""Attention ops: Pallas flash attention + ring attention (context parallel).

The reference (2017-era MXNet) has **no** attention or sequence/context
parallelism — SURVEY.md §2.4 lists them as capability gaps the TPU build must
cover natively (§7.10).  Long sequences in the reference are handled only by
bucketing and model-parallel LSTM; here they are handled the TPU way:

* ``flash_attention`` — blockwise-softmax attention.  On TPU the forward is a
  Pallas kernel (one VMEM pass per query block, online softmax, MXU matmuls)
  and the backward is a pair of Pallas kernels (a dk/dv pass and a dq pass,
  both O(block) VMEM, reusing the forward's saved log-sum-exp); elsewhere a
  numerically identical jax fallback runs.
* ``ring_attention`` — context-parallel attention for sequences sharded along
  a mesh ``seq`` axis: K/V blocks rotate around the ring via ``ppermute``
  while each device's query block folds them into an online softmax.  Used
  inside ``shard_map``; communication rides ICI and overlaps with compute.
* ``MultiHeadAttention`` / ``LayerNorm`` symbol ops so transformer models
  compose the same way the reference's CNN/RNN layers do.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .registry import ParamSpec as P, dispatch_variant, register

__all__ = ["flash_attention", "ring_attention", "paged_decode_attention",
           "stable_causal_attention"]

_NEG_INF = -1e30
# Mosaic tiles the last two block dims as (8 sublanes, 128 lanes); per-row
# vectors (lse, delta) cross pallas_call boundaries broadcast over a
# 128-lane trailing dim (the layout jax's own TPU flash kernel uses).
_LANE = 128


def _causal_mask(bq, bk, q_offset, k_offset):
    """Boolean [bq, bk] mask: query global pos >= key global pos."""
    qi = q_offset + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    ki = k_offset + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return qi >= ki


# ----------------------------------------------------------------------
# plain-jax reference path (also the backward's recompute)
# ----------------------------------------------------------------------


def _attention_fwd_ref(q, k, v, causal, sm_scale, return_lse=False):
    """Exact softmax attention on [B, H, T, D] tensors, fp32 softmax."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        mask = _causal_mask(q.shape[2], k.shape[2], 0, 0)
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    p = p / l
    out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)
    if return_lse:
        return out, (m + jnp.log(l))[..., 0]  # [B, H, T] fp32
    return out


# ----------------------------------------------------------------------
# shape-stable attention for the generation lane (prefill/decode parity)
# ----------------------------------------------------------------------
#
# The autoregressive lane promises *bitwise* parity between incremental
# decode through the paged cache and a full-sequence forward pass.  On
# XLA:CPU the dot-general behind ``einsum("bhqd,bhkd->bhqk", ...)`` picks
# different reduction strategies for different q-lengths, so the same
# row's score differs in the last bit between a T-row prefill and a
# 1-row decode step.  A multiply-and-reduce over the head dim is an
# independent per-(b,h,q,k) reduction and compiles to the same sequence
# of adds regardless of how many query rows ride along — that, plus an
# elementwise fp32 softmax and ``-1e30`` masking applied *before* the
# row max (masked lanes underflow to exact 0.0 in exp, contributing
# exact zeros to the p·v contraction), makes every op here stable across
# both the query-length axis and key-dim padding.  Prefill, full
# forward, and paged decode all route through these two helpers so the
# three paths cannot drift.


def _stable_scores(q, k):
    """fp32 [B, H, T, K] scores via mul-reduce (bitwise stable in T/K)."""
    return jnp.sum(q.astype(jnp.float32)[:, :, :, None, :] *
                   k.astype(jnp.float32)[:, :, None, :, :], axis=-1)


def _stable_softmax(s):
    """Row softmax of fp32 scores; masked lanes must already be -1e30."""
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    return p / jnp.sum(p, axis=-1, keepdims=True)


def stable_causal_attention(q, k, v, sm_scale=None):
    """Exact causal attention on ``[B, H, T, D]``, shape-stable bits.

    The generation lane's prefill / full-forward path.  Slower than
    :func:`flash_attention` (materialises the score matrix) but its
    output bits do not depend on the query length — the property the
    paged-decode parity gate relies on.

    Dispatches through the fused tier (``ops/fused``): on eligible
    backends (or under ``MXNET_TPU_OPS_FUSED_OVERRIDE``) the
    tolerance-class flash variant runs instead; ``MXNET_TPU_OPS_FUSED=0``
    pins the stock body below.
    """
    return dispatch_variant("stable_causal_attention",
                            _stable_causal_attention_stock,
                            q, k, v, sm_scale=sm_scale)


def _stable_causal_attention_stock(q, k, v, sm_scale=None):
    if sm_scale is None:
        sm_scale = 1.0 / float(q.shape[-1]) ** 0.5
    s = _stable_scores(q, k) * sm_scale
    mask = _causal_mask(q.shape[2], k.shape[2], k.shape[2] - q.shape[2], 0)
    s = jnp.where(mask[None, None], s, _NEG_INF)
    p = _stable_softmax(s)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


def paged_decode_attention(q, k_step, v_step, k_pages, v_pages,
                           block_tables, context_lens, sm_scale=None):
    """One decode step's attention, K/V gathered through the block table.

    - ``q`` / ``k_step`` / ``v_step``: ``[B, H, D]`` — this step's
      single query per sequence and its freshly projected K/V (written
      back to the pool by the caller *after* the step succeeds, so a
      retried dispatch never leaves half-written pages).
    - ``k_pages`` / ``v_pages``: ``[num_blocks, block_size, H, D]`` —
      one layer's slice of the shared :class:`~mxnet_tpu.ops.kv_cache.
      PagedKVCache` pool.
    - ``block_tables``: ``int32 [B, max_blocks]`` — per-sequence page
      lists, zero-padded (pad rows are masked off below).
    - ``context_lens``: ``int32 [B]`` — valid tokens per sequence,
      INCLUDING the current one (whose K/V arrives via ``k_step``).

    Returns ``[B, H, D]``.  The current token is scattered into the
    gathered keys at position ``context_len - 1`` so the valid keys form
    the same contiguous prefix a full-sequence forward sees — identical
    reduction order, and the padded-key masking keeps garbage in
    unwritten page tails away from the output bits.

    Dispatches through the fused tier: the Pallas block-table kernel
    (``ops/fused/attention_kernels.py``) is bitwise-equal to the stock
    body below, so the decode parity contract survives either way.
    """
    return dispatch_variant("paged_decode_attention",
                            _paged_decode_attention_stock,
                            q, k_step, v_step, k_pages, v_pages,
                            block_tables, context_lens,
                            sm_scale=sm_scale)


def _paged_decode_attention_stock(q, k_step, v_step, k_pages, v_pages,
                                  block_tables, context_lens,
                                  sm_scale=None):
    if sm_scale is None:
        sm_scale = 1.0 / float(q.shape[-1]) ** 0.5
    bsz, max_blocks = block_tables.shape
    blk = k_pages.shape[1]
    heads, dim = k_pages.shape[2], k_pages.shape[3]
    kmax = max_blocks * blk
    rows = jnp.arange(bsz)
    positions = context_lens - 1
    k = k_pages[block_tables].reshape(bsz, kmax, heads, dim)
    v = v_pages[block_tables].reshape(bsz, kmax, heads, dim)
    k = k.at[rows, positions].set(k_step)
    v = v.at[rows, positions].set(v_step)
    k = k.transpose(0, 2, 1, 3)            # [B, H, Kmax, D]
    v = v.transpose(0, 2, 1, 3)
    s = _stable_scores(q[:, :, None, :], k) * sm_scale   # [B, H, 1, Kmax]
    pos = lax.broadcasted_iota(jnp.int32, (1, 1, 1, kmax), 3)
    s = jnp.where(pos < context_lens[:, None, None, None], s, _NEG_INF)
    p = _stable_softmax(s)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return out[:, :, 0, :]


# ----------------------------------------------------------------------
# Pallas TPU forward kernel
# ----------------------------------------------------------------------


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *rest,
                  sm_scale, causal, block_q, block_k, n_k, kv_len):
    """One (batch*head, q-block, k-block) program of the online softmax.

    The k-block grid dimension is sequential ("arbitrary"); VMEM scratch
    (m/l/acc) carries the running max, denominator, and weighted sum across
    k steps, so VMEM holds only one q-block and one k/v-block at a time —
    sequence length is bounded by HBM, not the 16 MB VMEM (the previous
    kernel staged all of K/V per program and capped out near T=8K).

    ``rest`` is ``(lse_ref, m_scr, l_scr, acc_scr)`` when the caller asked
    for the log-sum-exp residual (the VJP forward) and just the three
    scratch refs otherwise — the primal/inference path skips the extra
    [bq, 128] HBM write entirely."""
    import jax.experimental.pallas as pl

    if len(rest) == 4:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        lse_ref = None
        m_scr, l_scr, acc_scr = rest

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    if causal:
        # skip blocks entirely above the diagonal
        run = ki * block_k <= qi * block_q + block_q - 1
    else:
        run = True

    @pl.when(run)
    def _compute():
        # matmuls take the INPUT dtype (bf16 rides the MXU at full rate;
        # an fp32 pre-cast would quarter it) and accumulate fp32; all
        # softmax math stays fp32
        q = q_ref[0]  # [bq, D]
        k = k_ref[0]  # [bk, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        mask = None
        if causal:
            mask = _causal_mask(block_q, block_k, qi * block_q, ki * block_k)
        if kv_len % block_k:
            # ragged tail: padded key columns contribute nothing
            col = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            valid = col < kv_len
            mask = valid if mask is None else (mask & valid)
        if mask is not None:
            s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=1)
        acc_scr[...] = acc_scr[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = m_new

    @pl.when(ki == n_k - 1)
    def _finish():
        l = l_scr[...]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l[:, None]).astype(o_ref.dtype)
        if lse_ref is not None:
            # log-sum-exp residual for the backward kernels (padded rows
            # get -inf + 0; they are sliced off before use).  Broadcast
            # across a 128-lane trailing dim: Mosaic requires the last two
            # block dims to tile (8, 128), so a per-row vector rides as
            # [bq, 128] (the layout jax's own TPU flash kernel uses for
            # its l/m residuals).
            lse = m_scr[...] + jnp.log(l)
            lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref[0].shape)


def _flash_fwd_pallas(q, k, v, causal, sm_scale, block_q=1024, block_k=2048,
                      interpret=False, return_lse=False):
    """Pallas forward on [B, H, T, D].  T is padded to block multiples.

    Default blocks re-tuned r5 on v5e (tools/attn_bench.py sweep at
    b8h16d64): (1024, 2048) beats the old (512, 1024) by 4-14% across
    T=1024..8192 (e.g. 16.6 -> 14.9 ms at T4096); the backward kernels
    keep (1024, 1024) — their dk/dv pass at block_k=2048 exceeds what
    the compiler will schedule."""
    import jax.experimental.pallas as pl

    from jax.experimental.pallas import tpu as pltpu

    B, H, T, D = q.shape
    Tk = k.shape[2]
    block_q = min(block_q, max(8, T))
    block_k = min(block_k, max(8, Tk))
    # ragged shapes: pad to block multiples.  Padded q rows are sliced off
    # the output; padded key columns are masked inside the kernel (kv_len).
    Tp = -(-T // block_q) * block_q
    Tkp = -(-Tk // block_k) * block_k
    if Tp != T:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, Tp - T), (0, 0)))
    if Tkp != Tk:
        pad = ((0, 0), (0, 0), (0, Tkp - Tk), (0, 0))
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    qf = q.reshape(B * H, Tp, D)
    kf = k.reshape(B * H, Tkp, D)
    vf = v.reshape(B * H, Tkp, D)
    n_k = Tkp // block_k
    grid = (B * H, Tp // block_q, n_k)
    kernel = functools.partial(
        _flash_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, n_k=n_k, kv_len=Tk)
    kwargs = {}
    if not interpret:
        params_cls = getattr(pltpu, "CompilerParams",
                             getattr(pltpu, "TPUCompilerParams", None))
        if params_cls is not None:
            kwargs["compiler_params"] = params_cls(
                dimension_semantics=("parallel", "parallel", "arbitrary"))
    out_shape = [jax.ShapeDtypeStruct((B * H, Tp, D), q.dtype)]
    out_specs = [pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))]
    if return_lse:
        out_shape.append(
            jax.ShapeDtypeStruct((B * H, Tp, _LANE), jnp.float32))
        out_specs.append(
            pl.BlockSpec((1, block_q, _LANE), lambda b, i, j: (b, i, 0)))
    res = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
        **kwargs,
    )(qf, kf, vf)
    out = res[0].reshape(B, H, Tp, D)[:, :, :T]
    if return_lse:
        return out, res[1][:, :, 0].reshape(B, H, Tp)[:, :, :T]
    return out


# ----------------------------------------------------------------------
# flash_attention: public entry with custom VJP
# ----------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, sm_scale, interpret):
    return _flash_dispatch(q, k, v, causal, sm_scale, interpret)


def _flash_dispatch(q, k, v, causal, sm_scale, interpret):
    platform = jax.default_backend()
    if interpret:
        return _flash_fwd_pallas(q, k, v, causal, sm_scale,
                                 interpret=platform != "tpu")
    # crossover re-measured r5 (tools/attn_bench.py, docs/PERF.md): the
    # Pallas kernel wins from T>=1024 in the primal too (9.4 vs 12.5 ms
    # at T2048 b8h16d64; ~tie at 512), matching the VJP-forward's
    # threshold — and the blocked kernel is the only option past 8K
    # where exact attention OOMs
    if platform == "tpu" and (q.shape[2] >= 1024 or k.shape[2] >= 1024):
        return _flash_fwd_pallas(q, k, v, causal, sm_scale)
    return _attention_fwd_ref(q, k, v, causal, sm_scale)


def _flash_fwd_vjp(q, k, v, causal, sm_scale, interpret):
    """Forward for the VJP: same dispatch as the primal, but every path
    also emits the per-row log-sum-exp so the backward kernels never have
    to re-derive the softmax statistics."""
    platform = jax.default_backend()
    if interpret:
        out, lse = _flash_fwd_pallas(q, k, v, causal, sm_scale,
                                     interpret=platform != "tpu",
                                     return_lse=True)
    elif platform == "tpu" and (q.shape[2] >= 1024 or k.shape[2] >= 1024):
        # same T>=1024 crossover as the primal (re-measured r5): the
        # Pallas bwd kernels consume the kernel's lse directly, and
        # skipping the [T, T] XLA softmax materialization pays off
        # (measured on the transformer-LM bench, docs/PERF.md)
        out, lse = _flash_fwd_pallas(q, k, v, causal, sm_scale,
                                     return_lse=True)
    else:
        out, lse = _attention_fwd_ref(q, k, v, causal, sm_scale,
                                      return_lse=True)
    return out, (q, k, v, out, lse)


# ----------------------------------------------------------------------
# Pallas TPU backward kernels (dk/dv pass + dq pass)
# ----------------------------------------------------------------------


def _bwd_p_ds(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, qi, kj, *,
              sm_scale, causal, block_q, block_k, kv_len):
    """Shared backward tile math for one (q-block, k-block) pair: the
    attention weights ``p`` and score gradients ``ds`` plus the fp32
    block operands.  Both bwd kernels call this, so the mask and scale
    logic can never diverge between dq and dk/dv."""
    # matmul operands stay in the input dtype (bf16 at full MXU rate),
    # accumulating fp32; softmax statistics math is fp32 throughout
    qb = q_ref[0]    # [bq, D]
    dob = do_ref[0]  # [bq, D]
    kb = k_ref[0]    # [bk, D]
    vb = v_ref[0]
    # [bq, _LANE] lane-broadcast vectors; any-lane reduce recovers them
    lseb = jnp.max(lse_ref[0], axis=1)   # [bq] (+inf on padded q rows)
    dlt = jnp.max(delta_ref[0], axis=1)  # [bq]
    s = jax.lax.dot_general(
        qb, kb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
    p = jnp.exp(s - lseb[:, None])
    mask = None
    if causal:
        mask = _causal_mask(block_q, block_k, qi * block_q, kj * block_k)
    if kv_len % block_k:
        # ragged tail: padded key columns contribute nothing
        col = kj * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = col < kv_len
        mask = valid if mask is None else (mask & valid)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    dp = jax.lax.dot_general(
        dob, vb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - dlt[:, None]) * sm_scale
    return p, ds, qb, dob, kb


def _flash_bwd_dkdv_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                           dk_ref, dv_ref, dk_scr, dv_scr, *,
                           sm_scale, causal, block_q, block_k, n_q, kv_len):
    """One (batch*head, k-block, q-block) program: k-blocks are parallel,
    q-blocks sequential; VMEM scratch accumulates dk/dv for the resident
    k-block while q/do/lse/delta blocks stream past."""
    import jax.experimental.pallas as pl

    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    if causal:
        # q-blocks entirely above the diagonal contribute nothing
        run = qi * block_q + block_q - 1 >= kj * block_k
    else:
        run = True

    @pl.when(run)
    def _compute():
        p, ds, qb, dob, _ = _bwd_p_ds(
            q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, qi, kj,
            sm_scale=sm_scale, causal=causal, block_q=block_q,
            block_k=block_k, kv_len=kv_len)
        dv_scr[...] += jax.lax.dot_general(
            p.astype(dob.dtype), dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(qb.dtype), qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == n_q - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_scr, *,
                         sm_scale, causal, block_q, block_k, n_k, kv_len):
    """One (batch*head, q-block, k-block) program: q-blocks parallel,
    k-blocks sequential; scratch accumulates dq for the resident q-block."""
    import jax.experimental.pallas as pl

    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    if causal:
        run = kj * block_k <= qi * block_q + block_q - 1
    else:
        run = True

    @pl.when(run)
    def _compute():
        _, ds, _, _, kb = _bwd_p_ds(
            q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, qi, kj,
            sm_scale=sm_scale, causal=causal, block_q=block_q,
            block_k=block_k, kv_len=kv_len)
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == n_k - 1)
    def _finish():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, do, causal, sm_scale,
                      block_q=1024, block_k=1024, interpret=False):
    """Two-pass Pallas flash backward on [B, H, T, D]: a dk/dv kernel and
    a dq kernel, each O(block) VMEM — the backward twin of
    ``_flash_fwd_pallas`` (ends the plain-jax recompute that MFU-capped
    the transformer bench; the measured figure lives in the
    ``model_flops_utilization`` gauge / bench.py's ``mfu`` key, not
    here — see docs/PERF.md "MFU is measured, not quoted")."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, T, D = q.shape
    Tk = k.shape[2]
    bq = min(block_q, max(8, T))
    bk = min(block_k, max(8, Tk))
    Tp = -(-T // bq) * bq
    Tkp = -(-Tk // bk) * bk
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if Tp != T:
        pad3 = ((0, 0), (0, 0), (0, Tp - T), (0, 0))
        q = jnp.pad(q, pad3)
        do = jnp.pad(do, pad3)
        # +inf lse on padded q rows makes p = exp(s - inf) = 0 there, so
        # the pads contribute nothing to dk/dv and their dq rows (sliced
        # off below) stay zero
        lse = jnp.pad(lse, ((0, 0), (0, 0), (0, Tp - T)),
                      constant_values=jnp.inf)
        delta = jnp.pad(delta, ((0, 0), (0, 0), (0, Tp - T)))
    if Tkp != Tk:
        pad3 = ((0, 0), (0, 0), (0, Tkp - Tk), (0, 0))
        k = jnp.pad(k, pad3)
        v = jnp.pad(v, pad3)
    BH = B * H
    qf = q.reshape(BH, Tp, D)
    dof = do.reshape(BH, Tp, D)
    kf = k.reshape(BH, Tkp, D)
    vf = v.reshape(BH, Tkp, D)
    # per-row vectors cross as [BH, Tp, _LANE] lane-broadcasts (tiling rule)
    lsef = jnp.broadcast_to(lse.reshape(BH, Tp, 1), (BH, Tp, _LANE))
    deltaf = jnp.broadcast_to(delta.reshape(BH, Tp, 1), (BH, Tp, _LANE))
    n_q = Tp // bq
    n_k = Tkp // bk

    kwargs = {}
    if not interpret:
        params_cls = getattr(pltpu, "CompilerParams",
                             getattr(pltpu, "TPUCompilerParams", None))
        if params_cls is not None:
            kwargs["compiler_params"] = params_cls(
                dimension_semantics=("parallel", "parallel", "arbitrary"))

    dkdv_kernel = functools.partial(
        _flash_bwd_dkdv_kernel, sm_scale=sm_scale, causal=causal,
        block_q=bq, block_k=bk, n_q=n_q, kv_len=Tk)
    dk, dv = pl.pallas_call(
        dkdv_kernel,
        out_shape=[jax.ShapeDtypeStruct((BH, Tkp, D), k.dtype),
                   jax.ShapeDtypeStruct((BH, Tkp, D), v.dtype)],
        grid=(BH, n_k, n_q),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),      # q
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),      # do
            pl.BlockSpec((1, bq, _LANE), lambda b, j, i: (b, i, 0)),  # lse
            pl.BlockSpec((1, bq, _LANE), lambda b, j, i: (b, i, 0)),  # delta
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),      # k
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),      # v
        ],
        out_specs=[pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
                   pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0))],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        interpret=interpret,
        **kwargs,
    )(qf, dof, lsef, deltaf, kf, vf)

    dq_kernel = functools.partial(
        _flash_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
        block_q=bq, block_k=bk, n_k=n_k, kv_len=Tk)
    dq = pl.pallas_call(
        dq_kernel,
        out_shape=jax.ShapeDtypeStruct((BH, Tp, D), q.dtype),
        grid=(BH, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),      # k
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),      # v
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),      # q
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),      # do
            pl.BlockSpec((1, bq, _LANE), lambda b, i, j: (b, i, 0)),  # lse
            pl.BlockSpec((1, bq, _LANE), lambda b, i, j: (b, i, 0)),  # delta
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
        **kwargs,
    )(kf, vf, qf, dof, lsef, deltaf)

    dq = dq.reshape(B, H, Tp, D)[:, :, :T]
    dk = dk.reshape(B, H, Tkp, D)[:, :, :Tk]
    dv = dv.reshape(B, H, Tkp, D)[:, :, :Tk]
    return dq, dk, dv


_BWD_BLOCK_K = 512


def _flash_bwd_scan(q, k, v, o, lse, do, causal, sm_scale):
    """Plain-jax blockwise backward (CPU fallback): one scan over K blocks
    reusing the saved lse — never materializes the [T, T] matrix."""
    B, H, T, D = q.shape
    Tk = k.shape[2]
    bk = min(_BWD_BLOCK_K, Tk)
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    if Tk % bk:
        bk = Tk  # ragged small sequence: single block

    n_k = Tk // bk
    kb = k.astype(jnp.float32).reshape(B, H, n_k, bk, D).transpose(2, 0, 1, 3, 4)
    vb = v.astype(jnp.float32).reshape(B, H, n_k, bk, D).transpose(2, 0, 1, 3, 4)
    k_offs = jnp.arange(n_k) * bk
    qi = lax.broadcasted_iota(jnp.int32, (T, bk), 0)
    ki_local = lax.broadcasted_iota(jnp.int32, (T, bk), 1)

    def scores(k_blk, k_off):
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_blk,
                       preferred_element_type=jnp.float32) * sm_scale
        if causal:
            mask = (qi >= k_off + ki_local)[None, None]
            s = jnp.where(mask, s, _NEG_INF)
        return s

    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1)  # [B,H,T]

    # accumulate dq; emit dk/dv per block
    def grad_step(dq, xs):
        k_blk, v_blk, k_off = xs
        s = scores(k_blk, k_off)
        p = jnp.exp(s - lse[..., None])
        dv_blk = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
        dp = jnp.einsum("bhqd,bhkd->bhqk", dof, v_blk)
        ds = p * (dp - delta[..., None])
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, k_blk) * sm_scale
        dk_blk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf) * sm_scale
        return dq, (dk_blk, dv_blk)

    dq0 = jnp.zeros((B, H, T, D), jnp.float32)
    dq, (dkb, dvb) = lax.scan(grad_step, dq0, (kb, vb, k_offs))
    dk = dkb.transpose(1, 2, 0, 3, 4).reshape(B, H, Tk, D)
    dv = dvb.transpose(1, 2, 0, 3, 4).reshape(B, H, Tk, D)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _flash_bwd_vjp(causal, sm_scale, interpret, res, do):
    """Backward dispatch: Pallas two-pass kernels on TPU (and under
    ``interpret=True`` for CPU testing); plain-jax blockwise scan
    elsewhere."""
    q, k, v, o, lse = res
    platform = jax.default_backend()
    if interpret:
        return _flash_bwd_pallas(q, k, v, o, lse, do, causal, sm_scale,
                                 interpret=platform != "tpu")
    if platform == "tpu":
        return _flash_bwd_pallas(q, k, v, o, lse, do, causal, sm_scale)
    return _flash_bwd_scan(q, k, v, o, lse, do, causal, sm_scale)


_flash.defvjp(_flash_fwd_vjp, _flash_bwd_vjp)


def flash_attention(q, k, v, causal=False, sm_scale=None, interpret=False):
    """Softmax attention over [B, H, T, D] tensors.

    On TPU both directions run as Pallas flash kernels (O(T) memory): the
    online-softmax forward plus a dk/dv pass and a dq pass that reuse the
    forward's log-sum-exp.  ``interpret=True`` forces the Pallas kernels in
    interpreter mode (CPU testing).
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    return _flash(q, k, v, bool(causal), float(sm_scale), bool(interpret))


# ----------------------------------------------------------------------
# ring attention (context parallel, inside shard_map)
# ----------------------------------------------------------------------


def ring_attention(q, k, v, axis_name, causal=False, sm_scale=None):
    """Blockwise ring attention for use **inside** ``shard_map``.

    Each device holds the local sequence shard ``q/k/v: [B, H, T_local, D]``
    of a sequence sharded along mesh axis ``axis_name``.  K/V rotate around
    the ring with ``lax.ppermute`` while the local queries fold each visiting
    block into an online softmax — the all-gather-free long-context pattern
    (PAPERS.md ring-attention family).  Differentiable (pure jax + scan).
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    B, H, Tl, D = q.shape
    qf = q.astype(jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, s):
        o, m, l, kc, vc = carry
        # kc originated on device (my - s) mod n
        src = (my - s) % n
        sc = jnp.einsum("bhqd,bhkd->bhqk", qf, kc.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * sm_scale
        if causal:
            qi = my * Tl + lax.broadcasted_iota(jnp.int32, (Tl, Tl), 0)
            ki = src * Tl + lax.broadcasted_iota(jnp.int32, (Tl, Tl), 1)
            mask = (qi >= ki)[None, None]
            sc = jnp.where(mask, sc, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(sc, axis=-1))
        p = jnp.exp(sc - m_new[..., None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bhkd->bhqd", p, vc.astype(jnp.float32))
        o_new = o * alpha[..., None] + pv
        k_next = lax.ppermute(kc, axis_name, perm)
        v_next = lax.ppermute(vc, axis_name, perm)
        return (o_new, m_new, l_new, k_next, v_next), None

    # derive the initial carry from q so it inherits q's varying-manual-axes
    # type (newer jax rejects scan carries whose vma set changes)
    o0 = qf * 0.0
    m0 = qf[..., 0] * 0.0 + _NEG_INF
    l0 = qf[..., 0] * 0.0
    (o, m, l, _, _), _ = lax.scan(
        jax.checkpoint(step), (o0, m0, l0, k, v), jnp.arange(n))
    l = jnp.where(l == 0.0, 1.0, l)
    return (o / l[..., None]).astype(q.dtype)


# ----------------------------------------------------------------------
# symbol ops: LayerNorm, MultiHeadAttention
# ----------------------------------------------------------------------


@register(
    "LayerNorm",
    arg_names=["data", "gamma", "beta"],
    params={"axis": P("int", -1), "eps": P("float", 1e-5)},
)
def _layer_norm(attrs, data, gamma, beta):
    """Layer normalization (absent in the 2017 reference; required by the
    transformer capability layer)."""
    axis = attrs["axis"]
    x = data.astype(jnp.float32)
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + attrs["eps"])
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    return (y * gamma.reshape(shape).astype(jnp.float32)
            + beta.reshape(shape).astype(jnp.float32)).astype(data.dtype)


def _mha_input_names(attrs):
    names = ["data", "qkv_weight", "out_weight"]
    if not attrs.get("no_bias", True):
        names += ["qkv_bias", "out_bias"]
    return names


@register(
    "MultiHeadAttention",
    aliases=["_contrib_MultiHeadAttention"],
    arg_names=["data", "qkv_weight", "out_weight"],
    input_names_fn=_mha_input_names,
    params={
        "num_heads": P("int", required=True),
        "causal": P("bool", False),
        "no_bias": P("bool", True),
        # mesh axis for context parallelism; '' disables
        "context_parallel_axis": P("str", ""),
        "interpret": P("bool", False),
    },
    mesh_aware=True,
)
def _multi_head_attention(attrs, data, qkv_weight, out_weight,
                          qkv_bias=None, out_bias=None):
    """Self-attention layer on [B, T, C]: fused QKV projection → flash or
    ring attention → output projection.

    When ``context_parallel_axis`` names an axis of the active default mesh
    (``mx.parallel.set_default_mesh``), attention runs as ring attention
    under ``shard_map`` with the sequence dimension sharded along that axis —
    the long-context path the reference lacks (SURVEY.md §5 'Long-context').
    """
    B, T, C = data.shape
    H = attrs["num_heads"]
    D = C // H
    # mixed precision: fp32 master weights cast to the activation dtype
    # (bf16 einsums accumulate fp32 on the MXU; fp16 projections compute in
    # fp32 and cast back — the FC note in ops/nn.py)
    out_dtype = data.dtype
    if data.dtype == jnp.float16:
        data = data.astype(jnp.float32)
    qkv_weight = qkv_weight.astype(data.dtype)
    out_weight = out_weight.astype(data.dtype)
    qkv = jnp.einsum("btc,fc->btf", data, qkv_weight)
    if qkv_bias is not None:
        qkv = qkv + qkv_bias.astype(data.dtype)
    qkv = qkv.reshape(B, T, 3, H, D).transpose(2, 0, 3, 1, 4)  # [3,B,H,T,D]
    q, k, v = qkv[0], qkv[1], qkv[2]

    axis = attrs.get("context_parallel_axis") or ""
    mesh = _default_mesh()
    if axis and mesh is not None and axis in mesh.axis_names \
            and mesh.shape[axis] > 1:
        from jax import shard_map
        from jax.sharding import PartitionSpec

        # keep the batch sharded along the data axis too — otherwise every
        # data-parallel group would all-gather and redundantly compute the
        # full batch's attention
        batch_axis = None
        for cand in ("data", "batch"):
            if cand in mesh.axis_names and cand != axis \
                    and B % mesh.shape[cand] == 0:
                batch_axis = cand
                break
        spec = PartitionSpec(batch_axis, None, axis, None)
        fn = shard_map(
            functools.partial(ring_attention, axis_name=axis,
                              causal=attrs["causal"]),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        out = fn(q, k, v)
    else:
        out = flash_attention(q, k, v, causal=attrs["causal"],
                              interpret=attrs.get("interpret", False))
    out = out.transpose(0, 2, 1, 3).reshape(B, T, C)
    out = jnp.einsum("btc,fc->btf", out, out_weight)
    if out_bias is not None:
        out = out + out_bias.astype(out.dtype)
    return out.astype(out_dtype)


def _default_mesh():
    from ..parallel import get_default_mesh

    return get_default_mesh()


# ----------------------------------------------------------------------
# MoELayer symbol op: expert-parallel FFN inside Symbol graphs
# ----------------------------------------------------------------------


@register(
    "MoELayer",
    aliases=["_contrib_MoELayer"],
    arg_names=["data", "gate_weight", "w1_weight", "w2_weight"],
    num_outputs=2,
    output_names=["output", "aux_loss"],
    params={
        "num_experts": P("int", required=True),
        "hidden_size": P("int", required=True),
        "capacity_factor": P("float", 2.0),
        "expert_axis": P("str", "expert"),
        "top_k": P("int", 1),
    },
    mesh_aware=True,
)
def _moe_layer(attrs, data, gate_weight, w1_weight, w2_weight):
    """Mixture-of-experts FFN as a graph node (capability-gap op — the
    reference has no MoE).  data (B, S, d); gate_weight (d, E);
    w1_weight (E, d, h); w2_weight (E, h, d).  Outputs the mixed tokens
    plus the load-balancing aux loss (add it to the objective via
    ``MakeLoss``).  When the ambient mesh has an ``expert`` axis
    (``ShardedTrainer`` sets it), GSPMD all-to-alls the expert buffers
    across it."""
    from ..parallel import get_default_mesh
    from ..parallel.moe import moe_ffn

    params = {"router": gate_weight, "w1": w1_weight, "w2": w2_weight}
    # moe_ffn itself checks the axis is present on the mesh
    out, aux_loss = moe_ffn(params, data,
                            capacity_factor=attrs["capacity_factor"],
                            expert_axis=attrs["expert_axis"],
                            mesh=get_default_mesh(),
                            top_k=attrs["top_k"])
    return out, aux_loss[None]
