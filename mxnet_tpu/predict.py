"""Deployment predict API (parity: reference ``include/mxnet/c_predict_api.h``
+ ``src/c_api/c_predict_api.cc`` — ``MXPredCreate/SetInput/Forward/
GetOutput/Reshape``, the amalgamation-friendly inference-only surface).

TPU framing: a ``Predictor`` is one AOT-jitted forward executable per input
shape (the ``MXNET_PREDICT_ONLY`` bind of the reference becomes an XLA
compile), with an executable cache keyed by shape so ``reshape`` is cheap
after first compile — the bucketing executors' trick applied to serving.
"""

from __future__ import annotations

import numpy as _np

from .base import MXNetError

__all__ = ["Predictor", "load"]


class Predictor(object):
    """Forward-only model loaded from checkpoint artifacts.

    Parameters
    ----------
    symbol_json : str — Symbol JSON (contents, not path).
    param_bytes : bytes or dict — serialized params (``nd.save`` format) or
        an in-memory ``{'arg:name'/'aux:name' -> NDArray}`` dict.
    ctx : Context
    input_shapes : dict name -> shape
    """

    def __init__(self, symbol_json, param_bytes, ctx=None, input_shapes=None,
                 output_index=None):
        from . import context, ndarray, symbol

        self._ctx = ctx or context.current_context()
        self.symbol = symbol.load_json(symbol_json)
        if isinstance(param_bytes, dict):
            saved = param_bytes
        else:
            saved = ndarray.load_frombuffer(param_bytes)
        self._arg_params, self._aux_params = {}, {}
        for k, v in saved.items():
            if k.startswith("arg:"):
                self._arg_params[k[4:]] = v
            elif k.startswith("aux:"):
                self._aux_params[k[4:]] = v
            else:
                self._arg_params[k] = v
        if not input_shapes:
            raise MXNetError("input_shapes required")
        self._input_shapes = dict(input_shapes)
        self._exec_cache = {}
        self._pipe_cache = {}  # jitted device-loop traces, per (shapes, N)
        self._inputs = {n: None for n in self._input_shapes}
        self._output_index = output_index
        self._bind()

    # -- executor cache ------------------------------------------------
    def _bind(self):
        from . import ndarray

        key = tuple(sorted((n, tuple(s))
                           for n, s in self._input_shapes.items()))
        if key not in self._exec_cache:
            # place loaded params on the serving device (checkpoint loads
            # land on host; every array must live on self._ctx before bind)
            args = {n: v.as_in_context(self._ctx)
                    for n, v in self._arg_params.items()}
            aux = {n: v.as_in_context(self._ctx)
                   for n, v in self._aux_params.items()}
            for n, s in self._input_shapes.items():
                args[n] = ndarray.zeros(s, ctx=self._ctx)
            # loss-layer label args have no saved params: zero-fill at their
            # inferred shapes (the reference's predict-only bind does the
            # same — labels are dead inputs in inference)
            missing = [n for n in self.symbol.list_arguments()
                       if n not in args]
            if missing:
                arg_shapes, _, _ = self.symbol.infer_shape(
                    **{n: tuple(s) for n, s in self._input_shapes.items()})
                shape_map = dict(zip(self.symbol.list_arguments(),
                                     arg_shapes))
                for n in missing:
                    if shape_map.get(n) is None:
                        raise MXNetError(
                            "missing param %r with uninferrable shape" % n)
                    args[n] = ndarray.zeros(shape_map[n], ctx=self._ctx)
            self._exec_cache[key] = self.symbol.bind(
                self._ctx, args, aux_states=aux, grad_req="null")
        self._exec = self._exec_cache[key]

    def reshape(self, input_shapes):
        """Rebind for new input shapes (parity: ``MXPredReshape``); cached
        per shape like bucketing executors."""
        self._input_shapes = dict(input_shapes)
        self._bind()

    # -- the MXPred* surface -------------------------------------------
    def set_input(self, name, value):
        """(parity: ``MXPredSetInput``)"""
        from . import ndarray

        if name not in self._input_shapes:
            raise MXNetError("unknown input %r" % name)
        value = _np.asarray(value, dtype=_np.float32)
        if tuple(value.shape) != tuple(self._input_shapes[name]):
            self.reshape({**self._input_shapes, name: value.shape})
        self._exec.arg_dict[name][:] = ndarray.array(value, ctx=self._ctx)

    def forward(self, **inputs):
        """(parity: ``MXPredForward``); optional inputs by kwarg."""
        for n, v in inputs.items():
            self.set_input(n, v)
        self._exec.forward(is_train=False)
        return self

    def forward_pipeline(self, batches):
        """Run N batches in ONE device dispatch — serving's version of the
        trainer's ``pipeline_steps``: a jitted ``lax.scan`` over stacked
        ``[N, ...]`` inputs pays the host→device dispatch (the ~1-2 ms
        tunnel tax per call — docs/PERF.md "Batch-32 inference") once per
        window instead of once per batch.

        ``batches`` is a list of ``{input: array}`` dicts, each matching
        ``input_shapes``, or a dict of pre-stacked ``[N, ...]`` arrays.
        Returns the outputs as a list of ``[N, ...]``-stacked numpy arrays
        (scoped to a single output when the Predictor was built with
        ``output_index``, like ``get_output``).  The scan trace is cached
        per ``(input shapes, N)``, so serving at a fixed window size
        compiles once."""
        import jax

        if isinstance(batches, dict):
            if not batches:
                raise MXNetError("forward_pipeline needs >= 1 batch")
            stacked = {n: _np.asarray(v) for n, v in batches.items()}
        else:
            if not batches:
                raise MXNetError("forward_pipeline needs >= 1 batch")
            stacked = {n: _np.stack([_np.asarray(b[n]) for b in batches])
                       for n in batches[0]}
        missing = set(self._input_shapes) - set(stacked)
        if missing:
            raise MXNetError("forward_pipeline missing inputs %r"
                             % sorted(missing))
        for n, v in stacked.items():
            if n not in self._input_shapes:
                raise MXNetError("unknown input %r" % n)
            if tuple(v.shape[1:]) != tuple(self._input_shapes[n]):
                raise MXNetError(
                    "input %r batches have shape %r, declared %r"
                    % (n, tuple(v.shape[1:]),
                       tuple(self._input_shapes[n])))
        depths = {v.shape[0] for v in stacked.values()}
        if len(depths) != 1:
            raise MXNetError(
                "inputs disagree on pipeline depth: %r" % sorted(depths))
        depth = depths.pop()
        if depth == 0:
            # a pre-stacked {n: empty [0, ...]} dict would compile a
            # degenerate scan and silently return empty outputs
            raise MXNetError("forward_pipeline needs >= 1 batch")
        ex = self._exec
        stacked = {n: v.astype(ex.arg_dict[n].dtype, copy=False)
                   for n, v in stacked.items()}
        shape_key = tuple(sorted((n, tuple(s))
                                 for n, s in self._input_shapes.items()))
        fn = self._pipe_cache.get((shape_key, depth))
        if fn is None:
            run = ex._run

            def pipe(params, aux, stacked):
                def body(key, batch):
                    args = dict(params)
                    args.update(batch)
                    outs, _ = run(args, aux, key, False)
                    return key, outs

                _, outs = jax.lax.scan(body, jax.random.PRNGKey(0), stacked)
                return outs

            fn = jax.jit(pipe)
            self._pipe_cache[(shape_key, depth)] = fn
        params = {k: v._data for k, v in ex.arg_dict.items()
                  if k not in self._input_shapes}
        aux = {k: v._data for k, v in ex.aux_dict.items()}
        outs = fn(params, aux, stacked)
        if self._output_index is not None:
            outs = [outs[self._output_index]]
        return [_np.asarray(o) for o in outs]

    def get_output(self, index=0):
        """(parity: ``MXPredGetOutput``) → numpy array.  When the Predictor
        was built with ``output_index``, the view is scoped to that single
        output (``MXPredCreatePartialOut`` semantics)."""
        if self._output_index is not None:
            assert index == 0, "output_index-scoped predictor has 1 output"
            index = self._output_index
        return self._exec.outputs[index].asnumpy()

    @property
    def num_outputs(self):
        if self._output_index is not None:
            return 1
        return len(self._exec.outputs)


def load(prefix, epoch, ctx=None, input_shapes=None):
    """Build a Predictor straight from ``save_checkpoint`` artifacts
    (``prefix-symbol.json`` + ``prefix-%04d.params``)."""
    from . import model as _model

    with open("%s-symbol.json" % prefix) as f:
        symbol_json = f.read()
    param_name = "%s-%04d.params" % (prefix, epoch)
    # checkpoint writes are async engine ops: order this read after them
    _model.wait_for_checkpoint(param_name)
    with open(param_name, "rb") as f:
        param_bytes = f.read()
    return Predictor(symbol_json, param_bytes, ctx=ctx,
                     input_shapes=input_shapes)
