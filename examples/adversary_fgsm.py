"""FGSM adversarial examples (parity: reference ``example/adversary/`` —
train a small net, then perturb inputs along the sign of the input
gradient and watch accuracy collapse).

Exercises the ``inputs_need_grad`` executor path (gradients w.r.t. DATA,
not params — the reference gets them from a bound executor the same way).

    python examples/adversary_fgsm.py [--eps 0.3]
"""

import argparse
import logging
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

import mxnet_tpu as mx


def make_data(rng, n):
    """4-class oriented gratings, 1x16x16 (small, conv-separable)."""
    xs = np.zeros((n, 1, 16, 16), np.float32)
    ys = rng.randint(0, 4, n)
    yy, xx = np.mgrid[0:16, 0:16]
    for i, c in enumerate(ys):
        ang = np.pi / 4 * c + rng.uniform(-0.1, 0.1)
        wave = np.sin(0.8 * (np.cos(ang) * xx + np.sin(ang) * yy)
                      + rng.uniform(0, 2 * np.pi))
        xs[i, 0] = 0.5 + 0.4 * wave + rng.normal(0, 0.05, (16, 16))
    return xs, ys.astype(np.float32)


def get_symbol():
    d = mx.sym.Variable("data")
    net = mx.sym.Convolution(d, num_filter=8, kernel=(3, 3), pad=(1, 1),
                             name="c1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=32,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def run(eps=0.3, seed=0, log=True):
    rng = np.random.RandomState(seed)
    np.random.seed(seed + 1)
    xs, ys = make_data(rng, 600)
    xv, yv = make_data(rng, 200)
    batch = 50

    mod = mx.mod.Module(get_symbol(), context=mx.cpu())
    it = mx.io.NDArrayIter(xs, ys, batch_size=batch, shuffle=True)
    mod.fit(it, num_epoch=10, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.initializer.Xavier())

    # adversarial module: same params, inputs_need_grad=True
    adv = mx.mod.Module(get_symbol(), context=mx.cpu())
    adv.bind(data_shapes=[("data", (batch, 1, 16, 16))],
             label_shapes=[("softmax_label", (batch,))],
             for_training=True, inputs_need_grad=True)
    args, auxs = mod.get_params()
    adv.set_params(args, auxs)

    def acc_of(x):
        hits = tot = 0
        for s in range(0, len(x), batch):
            b = mx.io.DataBatch([mx.nd.array(x[s:s + batch])],
                                [mx.nd.array(yv[s:s + batch])])
            adv.forward(b, is_train=False)
            pred = adv.get_outputs()[0].asnumpy().argmax(axis=1)
            hits += int((pred == yv[s:s + batch]).sum())
            tot += batch
        return hits / tot

    clean_acc = acc_of(xv)

    # FGSM: x_adv = x + eps * sign(dL/dx) at the TRUE label
    x_adv = xv.copy()
    for s in range(0, len(xv), batch):
        b = mx.io.DataBatch([mx.nd.array(xv[s:s + batch])],
                            [mx.nd.array(yv[s:s + batch])])
        adv.forward(b, is_train=True)
        adv.backward()
        g = adv.get_input_grads()[0].asnumpy()
        x_adv[s:s + batch] = xv[s:s + batch] + eps * np.sign(g)
    adv_acc = acc_of(x_adv)

    if log:
        logging.info("clean_acc=%.3f adversarial_acc=%.3f (eps=%.2f)",
                     clean_acc, adv_acc, eps)
    return {"clean_acc": clean_acc, "adv_acc": adv_acc}


def main():
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser(description="FGSM adversarial examples")
    p.add_argument("--eps", type=float, default=0.3)
    args = p.parse_args()
    stats = run(eps=args.eps)
    print("final:", stats)
    assert stats["clean_acc"] > 0.9, stats
    assert stats["adv_acc"] < stats["clean_acc"] - 0.3, stats


if __name__ == "__main__":
    main()
