"""``make trace``: run a short pipelined fit with tracing on and
validate the emitted chrome://tracing JSON.

Drives the full observability path end to end on the CPU backend: a
5-step ``ShardedTrainer.fit`` (pipeline_steps=2, so the prefetch feeder
and engine IO lane are load-bearing) under ``profiler_set_state('run')``,
then ``dump_profile()`` and a JSON re-load of the merged trace.  Exits
non-zero if the trace fails to parse, has no span events, or lacks the
cross-thread engine children the span propagation exists to produce.

Run:  python tools/trace_fit.py [out_dir]      (default: ./trace_output)
Open the printed ``trace.json`` at https://ui.perfetto.dev.
"""

import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main():
    import jax
    import numpy as np
    from jax.sharding import Mesh

    import mxnet_tpu as mx
    from mxnet_tpu.io import NDArrayIter
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    out_dir = sys.argv[1] if len(sys.argv) > 1 else "trace_output"

    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=16,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(net, num_hidden=8, name="fc2"),
        name="softmax")
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    tr = ShardedTrainer(net, mesh, data_shapes={"data": (8, 6)},
                        label_shapes={"softmax_label": (8,)},
                        momentum=0.9, rescale_grad=1.0 / 8,
                        pipeline_steps=2)
    rs = np.random.RandomState(0)
    # 5 optimizer steps: 2 flushes of 2 + the odd tail flush
    it = NDArrayIter(rs.randn(40, 6).astype(np.float32),
                     rs.randint(0, 8, (40,)).astype(np.float32),
                     batch_size=8)

    mx.profiler.profiler_set_config(filename=os.path.join(out_dir, "x"))
    mx.profiler.profiler_set_state("run")
    tr.fit(it, num_epoch=1, seed=0)
    path = mx.profiler.dump_profile()

    with open(path, encoding="utf-8") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    spans = [e for e in events if e.get("ph") == "X"]
    engine_children = [
        e for e in spans
        if e.get("cat") == "engine" and e.get("args", {}).get("parent")]
    print("trace: %d events (%d spans, %d cross-thread engine children) "
          "-> %s" % (len(events), len(spans), len(engine_children), path))
    if not spans:
        print("FAIL: no span events recorded", file=sys.stderr)
        return 1
    if not engine_children:
        print("FAIL: no engine spans parented across threads",
              file=sys.stderr)
        return 1
    print("metrics snapshot:\n" + "\n".join(
        line for line in mx.observability.dump_metrics().splitlines()
        if line.startswith(("trainer_steps_total", "prefetch_chunks_total",
                            "engine_push_total"))))
    return 0


if __name__ == "__main__":
    sys.exit(main())
