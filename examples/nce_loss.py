"""Noise-contrastive estimation (parity: reference ``example/nce-loss/``
— train a next-token model scoring only k noise samples per step instead
of a full-vocab softmax).

TPU-first formulation: the sampled-candidate scores are one batched
embedding gather + dot product (static shapes: k negatives per
positive), and the binary NCE objective is built from graph ops — no
custom C++ op as in the reference.  Evaluation ranks the FULL vocabulary
with the trained embeddings, proving the sampled objective learned the
same structure the softmax would.

    python examples/nce_loss.py [--steps 400]
"""

import argparse
import logging
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

import mxnet_tpu as mx

VOCAB = 120
DIM = 24
K_NOISE = 8
# deterministic bigram language: token t is followed by (t*7+3) % VOCAB
def _next_tok(t):
    return (t * 7 + 3) % VOCAB


def make_batch(rng, batch):
    ctx_tok = rng.randint(0, VOCAB, batch)
    pos = np.array([_next_tok(t) for t in ctx_tok])
    noise = rng.randint(0, VOCAB, (batch, K_NOISE))
    return (ctx_tok.astype(np.float32), pos.astype(np.float32),
            noise.astype(np.float32))


def get_symbol():
    ctx_tok = mx.sym.Variable("data")             # (B,)
    cand = mx.sym.Variable("cand")                # (B, 1+K) pos first
    label = mx.sym.Variable("softmax_label")      # (B, 1+K) 1/0 targets
    in_emb = mx.sym.Embedding(ctx_tok, input_dim=VOCAB, output_dim=DIM,
                              name="in_embed")       # (B, DIM)
    out_emb = mx.sym.Embedding(cand, input_dim=VOCAB, output_dim=DIM,
                               name="out_embed")     # (B, 1+K, DIM)
    # score each candidate against the context vector: batched dot
    scores = mx.sym.batch_dot(out_emb, mx.sym.Reshape(in_emb,
                                                      shape=(-1, DIM, 1)))
    scores = mx.sym.Reshape(scores, shape=(-1, 1 + K_NOISE))
    # binary NCE loss: -[y log σ(s) + (1-y) log σ(-s)]
    return mx.sym.LogisticRegressionOutput(scores, label, name="nce")


def full_vocab_rank(mod, batch):
    """Rank every vocab token as continuation of each context; return
    mean reciprocal rank of the true next token."""
    in_w = mod.get_params()[0]["in_embed_weight"].asnumpy()
    out_w = mod.get_params()[0]["out_embed_weight"].asnumpy()
    ctx_tok = np.arange(VOCAB)
    scores = in_w[ctx_tok] @ out_w.T                 # (VOCAB, VOCAB)
    truth = np.array([_next_tok(t) for t in ctx_tok])
    order = np.argsort(-scores, axis=1)
    ranks = np.array([np.where(order[i] == truth[i])[0][0] + 1
                      for i in range(VOCAB)])
    return float(np.mean(1.0 / ranks))


def run(steps=400, batch=64, seed=0, log=True):
    rng = np.random.RandomState(seed)
    np.random.seed(seed + 1)
    mod = mx.mod.Module(get_symbol(), context=mx.cpu(),
                        data_names=("data", "cand"))
    mod.bind(data_shapes=[("data", (batch,)),
                          ("cand", (batch, 1 + K_NOISE))],
             label_shapes=[("softmax_label", (batch, 1 + K_NOISE))])
    mod.init_params(mx.initializer.Uniform(0.1))
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 5e-3})
    labels = np.zeros((batch, 1 + K_NOISE), np.float32)
    labels[:, 0] = 1.0
    from mxnet_tpu.io import DataBatch

    for i in range(steps):
        ctx_tok, pos, noise = make_batch(rng, batch)
        cand = np.concatenate([pos[:, None], noise], axis=1)
        mod.forward(DataBatch([mx.nd.array(ctx_tok), mx.nd.array(cand)],
                              [mx.nd.array(labels)]), is_train=True)
        mod.backward()
        mod.update()
        if log and (i + 1) % 100 == 0:
            logging.info("step %d: mrr=%.3f", i + 1,
                         full_vocab_rank(mod, batch))
    return {"mrr": full_vocab_rank(mod, batch)}


def main():
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=400)
    args = ap.parse_args()
    stats = run(steps=args.steps)
    print("nce_loss: full-vocab MRR=%.3f (random would be ~%.3f)"
          % (stats["mrr"], np.log(VOCAB) / VOCAB))


if __name__ == "__main__":
    main()
