/*!
 * Engine profiler — chrome://tracing JSON dump.
 *
 * Reference behavior matched: OprExecStat records per-op start/end + thread
 * inside engine execution, Profiler singleton dumps chrome trace JSON
 * (src/engine/profiler.h:20-141, profiler.cc:65-175, hook in
 * threaded_engine.h:294-308).
 *
 * On TPU, device-side timing comes from the XLA profiler (xplane); this
 * profiler owns the *host* lanes: engine ops (IO, decode, staging) and
 * frontend scopes, so mx.profiler can merge both views.
 */
#include <atomic>
#include <chrono>
#include <cstdio>
#include <mutex>
#include <string>
#include <vector>

#include "mxtpu/c_api.h"

namespace mxtpu {

int64_t NowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

namespace {

struct Event {
  std::string name;
  std::string cat;
  int64_t start_us;
  int64_t end_us;
  int tid;
};

struct ProfilerState {
  std::mutex m;
  std::vector<Event> events;
  std::atomic<bool> running{false};
};

ProfilerState *GetState() {
  static ProfilerState *st = new ProfilerState();
  return st;
}

void JsonEscape(const std::string &s, std::string *out) {
  for (char c : s) {
    if (c == '"' || c == '\\') {
      out->push_back('\\');
      out->push_back(c);
    } else if ((unsigned char)c >= 0x20) {
      out->push_back(c);
    }
  }
}

}  // namespace

bool ProfilerRunning() { return GetState()->running.load(); }

void ProfilerRecord(const char *name, const char *cat, int64_t start_us,
                    int64_t end_us, int tid) {
  ProfilerState *st = GetState();
  if (!st->running.load()) return;
  std::lock_guard<std::mutex> lk(st->m);
  st->events.push_back(Event{name ? name : "opr", cat ? cat : "engine",
                             start_us, end_us, tid});
}

}  // namespace mxtpu

extern "C" {

void mxtpu_profiler_set_state(int running) {
  ::mxtpu::GetState()->running.store(running != 0);
}

int mxtpu_profiler_state(void) {
  return ::mxtpu::GetState()->running.load() ? 1 : 0;
}

void mxtpu_profiler_clear(void) {
  auto *st = ::mxtpu::GetState();
  std::lock_guard<std::mutex> lk(st->m);
  st->events.clear();
}

void mxtpu_profiler_add_event(const char *name, const char *cat,
                              int64_t start_us, int64_t end_us, int tid) {
  auto *st = ::mxtpu::GetState();
  std::lock_guard<std::mutex> lk(st->m);
  st->events.push_back(
      ::mxtpu::Event{name ? name : "event", cat ? cat : "frontend", start_us,
                     end_us, tid});
}

int mxtpu_profiler_dump(const char *path) {
  auto *st = ::mxtpu::GetState();
  std::vector<::mxtpu::Event> events;
  {
    std::lock_guard<std::mutex> lk(st->m);
    events = st->events;
  }
  FILE *f = std::fopen(path, "w");
  if (!f) return -1;
  // chrome://tracing "traceEvents" format, complete ('X') events — same
  // consumer as the reference's DumpProfile output.
  std::fprintf(f, "{\n\"traceEvents\": [\n");
  bool first = true;
  for (const auto &e : events) {
    std::string name, cat;
    ::mxtpu::JsonEscape(e.name, &name);
    ::mxtpu::JsonEscape(e.cat, &cat);
    std::fprintf(f,
                 "%s{\"name\":\"%s\",\"cat\":\"%s\",\"ph\":\"X\",\"ts\":%lld,"
                 "\"dur\":%lld,\"pid\":0,\"tid\":%d}",
                 first ? "" : ",\n", name.c_str(), cat.c_str(),
                 (long long)e.start_us, (long long)(e.end_us - e.start_us),
                 e.tid);
    first = false;
  }
  std::fprintf(f, "\n],\n\"displayTimeUnit\": \"ms\"\n}\n");
  std::fclose(f);
  return (int)events.size();
}

}  // extern "C"
