"""Fused-RNN-aware checkpoint helpers (parity: reference
``python/mxnet/rnn/rnn.py:15-80``)."""

from __future__ import annotations

from ..model import load_checkpoint, save_checkpoint

__all__ = ["save_rnn_checkpoint", "load_rnn_checkpoint", "do_rnn_checkpoint"]


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params, aux_params):
    """Save checkpoint, unpacking fused weights (parity: ``save_rnn_checkpoint``)."""
    if isinstance(cells, (list, tuple)):
        for cell in cells:
            arg_params = cell.unpack_weights(arg_params)
    else:
        arg_params = cells.unpack_weights(arg_params)
    save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """Load checkpoint, repacking fused weights (parity: ``load_rnn_checkpoint``)."""
    sym, arg, aux = load_checkpoint(prefix, epoch)
    if isinstance(cells, (list, tuple)):
        for cell in cells:
            arg = cell.pack_weights(arg)
    else:
        arg = cells.pack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end checkpoint callback (parity: ``do_rnn_checkpoint``)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)

    return _callback


def rnn_unroll(cell, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC"):
    """Deprecated alias of ``cell.unroll`` (parity: ``rnn/rnn.py:rnn_unroll``)."""
    import warnings

    warnings.warn("rnn_unroll is deprecated. Please call cell.unroll directly.")
    return cell.unroll(length=length, inputs=inputs, begin_state=begin_state,
                       input_prefix=input_prefix, layout=layout)
