"""Fault-tolerance: engine error propagation, chaos injection, KVStore
retry/dedup semantics, and auto-resume training.

Every injection test uses a fixed seed (the chaos registry draws from a
rule-private RNG, so the failure schedule is a pure function of the seed
and the visit sequence) and sub-second delays.
"""

import os
import pickle
import subprocess
import sys
import traceback

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import chaos, engine
from mxnet_tpu.base import MXNetError, ServerDeadError, ShardFailedError

SHAPE = (4, 4)


class BoomError(Exception):
    pass


def _boom():
    raise BoomError("async op exploded")


# ---------------------------------------------------------------------------
# engine error propagation
# ---------------------------------------------------------------------------

def test_error_surfaces_at_wait_for_var():
    v = engine.new_variable()
    engine.push(_boom, mutable_vars=[v], name="failing_op")
    with pytest.raises(BoomError) as ei:
        engine.wait_for_var(v)
    # the ORIGINAL traceback: it still points into the failing fn
    tb = "".join(traceback.format_exception(
        type(ei.value), ei.value, ei.value.__traceback__))
    assert "_boom" in tb
    # poison is sticky until explicitly cleared
    with pytest.raises(BoomError):
        engine.wait_for_var(v)
    engine.clear_poison(v)
    engine.wait_for_var(v)  # clean after recovery
    engine.delete_variable(v)


def test_dependent_ops_fail_fast():
    v1, v2 = engine.new_variable(), engine.new_variable()
    ran = []
    engine.push(_boom, mutable_vars=[v1], name="producer")
    engine.push(lambda: ran.append(1), const_vars=[v1], mutable_vars=[v2],
                name="consumer")
    # the consumer never executes; it propagates the producer's poison
    with pytest.raises(BoomError):
        engine.wait_for_var(v2)
    assert ran == []
    with pytest.raises(BoomError):
        engine.wait_for_var(v1)
    for v in (v1, v2):
        engine.delete_variable(v)


def test_wait_for_all_raises_once_then_clean():
    v = engine.new_variable()
    engine.push(_boom, mutable_vars=[v], name="failing_op")
    with pytest.raises(BoomError):
        engine.wait_for_all()
    # the failure was surfaced (consumed); the next barrier is clean
    engine.wait_for_all()
    engine.delete_variable(v)


@pytest.fixture
def serial_engine(monkeypatch):
    """Run the module-level push/wait wrappers over the serial backend;
    the poison bookkeeping is backend-agnostic, so semantics must match."""
    engine.wait_for_all()
    monkeypatch.setattr(engine, "_engine", engine._SerialEngine())
    yield


def test_serial_engine_same_error_semantics(serial_engine):
    assert engine.engine_type() == "SerialEngine"
    v1, v2 = engine.new_variable(), engine.new_variable()
    ran = []
    # the serial engine runs fns inline, but the error must STILL defer
    # to the sync point, exactly like the threaded engine
    engine.push(_boom, mutable_vars=[v1], name="producer")
    engine.push(lambda: ran.append(1), const_vars=[v1], mutable_vars=[v2],
                name="consumer")
    assert ran == []  # fail-fast: consumer skipped
    with pytest.raises(BoomError):
        engine.wait_for_var(v2)
    with pytest.raises(BoomError):
        engine.wait_for_var(v1)
    v3 = engine.new_variable()
    engine.push(_boom, mutable_vars=[v3], name="other")
    with pytest.raises(BoomError):
        engine.wait_for_all()
    engine.wait_for_all()
    for v in (v1, v2, v3):
        engine.delete_variable(v)


def test_kv_pull_surfaces_updater_error():
    """Consumer sync point: a failing kvstore updater poisons the key's
    var and the original exception re-raises at pull."""
    kv = mx.kv.create("local")
    kv.init(3, mx.nd.zeros(SHAPE))

    def bad_updater(key, recv, stored):
        raise BoomError("updater died on key %r" % key)

    kv.set_updater(bad_updater)
    kv.push(3, mx.nd.ones(SHAPE))
    out = mx.nd.zeros(SHAPE)
    with pytest.raises(BoomError):
        kv.pull(3, out=out)


@pytest.mark.chaos
def test_load_checkpoint_surfaces_write_failure(tmp_path):
    """Consumer sync point: an async checkpoint write failure surfaces at
    load_checkpoint, chained to the original injected error."""
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                                name="fc")
    prefix = str(tmp_path / "model")
    args = {"fc_weight": mx.nd.ones((4, 3)), "fc_bias": mx.nd.zeros((4,))}
    with chaos.inject("checkpoint.write", "raise", seed=0):
        mx.model.save_checkpoint(prefix, 1, net, args, {})
        with pytest.raises(IOError) as ei:
            mx.model.load_checkpoint(prefix, 1)
    assert isinstance(ei.value.__cause__, chaos.ChaosError)
    # the registry is clean again: the same round-trip now succeeds
    mx.model.save_checkpoint(prefix, 1, net, args, {})
    sym2, args2, _ = mx.model.load_checkpoint(prefix, 1)
    np.testing.assert_allclose(args2["fc_weight"].asnumpy(),
                               np.ones((4, 3), np.float32))


def test_atexit_drain_never_raises():
    """An unsurfaced async failure at interpreter exit is logged, not
    raised — the process's real exit status must survive teardown."""
    code = (
        "from mxnet_tpu import engine\n"
        "v = engine.new_variable()\n"
        "engine.push(lambda: 1/0, mutable_vars=[v], name='doomed')\n"
        "print('reached-exit')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "reached-exit" in proc.stdout
    assert "doomed" in proc.stderr  # the drain logged the lost failure


def test_push_counter_lock_free():
    before = engine.op_count()
    v = engine.new_variable()
    for _ in range(25):
        engine.push(lambda: None, mutable_vars=[v])
    engine.wait_for_var(v)
    assert engine.op_count() >= before + 25
    engine.delete_variable(v)


# ---------------------------------------------------------------------------
# chaos registry
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_chaos_schedule_is_deterministic():
    def realized(seed):
        fires = []
        with chaos.inject("engine.op", "raise", prob=0.5, seed=seed) as inj:
            for _ in range(40):
                try:
                    chaos.visit("engine.op", name="op")
                    fires.append(0)
                except chaos.ChaosError:
                    fires.append(1)
            assert inj.visits == 40
        return fires

    a, b = realized(7), realized(7)
    assert a == b  # same seed, same visit sequence -> same schedule
    assert 0 < sum(a) < 40
    assert realized(8) != a  # and the seed actually matters


@pytest.mark.chaos
def test_chaos_engine_drop_skips_op():
    ran = []
    v = engine.new_variable()
    with chaos.inject("engine.op", "drop", seed=0, limit=1,
                      match="maybe_lost"):
        engine.push(lambda: ran.append(1), mutable_vars=[v],
                    name="maybe_lost")
        engine.push(lambda: ran.append(2), mutable_vars=[v],
                    name="maybe_lost")
        engine.wait_for_var(v)  # a drop is silent loss, NOT an error
    assert ran == [2]  # first op dropped (limit=1), second ran
    engine.delete_variable(v)


@pytest.mark.chaos
def test_chaos_corrupt_preserves_length_and_match_filters():
    payload = bytes(range(64))
    with chaos.inject("kvstore.send", "corrupt", seed=3):
        garbled = chaos.visit("kvstore.send", payload)
    assert len(garbled) == len(payload) and garbled != payload
    # match= keeps unrelated ops untouched
    with chaos.inject("engine.op", "raise", match="only_this") as inj:
        chaos.visit("engine.op", name="something_else")
        assert inj.fires == 0
        with pytest.raises(chaos.ChaosError):
            chaos.visit("engine.op", name="only_this_one")
        assert inj.fires == 1


@pytest.mark.chaos
def test_chaos_env_config(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_CHAOS", "engine.op:raise:1.0:limit=2")
    with pytest.raises(chaos.ChaosError):
        chaos.visit("engine.op", name="x")
    with pytest.raises(chaos.ChaosError):
        chaos.visit("engine.op", name="x")
    chaos.visit("engine.op", name="x")  # limit reached
    # reconfiguring the env is picked up lazily, no re-import
    monkeypatch.setenv("MXNET_TPU_CHAOS", "")
    chaos.visit("engine.op", name="x")


# ---------------------------------------------------------------------------
# kvstore hardening
# ---------------------------------------------------------------------------

from mxnet_tpu.kvstore_async import AsyncClient, AsyncServer, ServerGroup


@pytest.fixture
def fast_retries(monkeypatch):
    """Sub-second retry envelope for injected-failure tests; exercises the
    lazy env reads (no re-import) along the way."""
    monkeypatch.setattr(AsyncClient, "_BACKOFF_CAP_S", 0.1)
    monkeypatch.setenv("MXNET_TPU_PS_CALL_TIMEOUT", "5")
    monkeypatch.setenv("MXNET_TPU_PS_DEADLINE", "30")


def _sgd_pickle(lr=0.1):
    from mxnet_tpu import optimizer as opt

    return pickle.dumps(opt.SGD(learning_rate=lr, wd=0.0))


@pytest.mark.chaos
def test_retry_dedup_single_drop(fast_retries):
    """Satellite: a retried mutating op is answered from the response
    cache and never applied twice — pinned with a GUARANTEED drop."""
    srv = AsyncServer(secret="s").start()
    try:
        cli = AsyncClient(srv.address, rank=0, heartbeat=False, secret="s")
        cli.init([("w", np.zeros(4, np.float32))])
        cli.set_optimizer(_sgd_pickle())
        # drop exactly the response of the next push: the retry resends
        # the SAME seq and must be answered from the dedup cache
        with chaos.inject("kvstore.recv", "drop", seed=0, limit=1) as inj:
            cli.push([("w", np.ones(4, np.float32))])
        assert inj.fires == 1
        assert cli.stats()["push_counts"][0] == 1  # applied exactly once
        np.testing.assert_allclose(cli.pull(["w"])[0],
                                   np.full(4, -0.1, np.float32), rtol=1e-6)
    finally:
        srv.stop()


@pytest.mark.chaos
def test_server_group_converges_under_30pct_drop(fast_retries):
    """Acceptance: under 30% message drop a ServerGroup workload
    converges via retries with ZERO double-applied gradients — server
    apply-count equals client push-count."""
    servers = [AsyncServer(secret="g", server_id=i).start()
               for i in range(2)]
    try:
        grp = ServerGroup([s.address for s in servers], rank=0,
                          heartbeat=False, secret="g")
        keys = ["k0", "k1", "k2", "k3"]
        grp.init([(k, np.zeros(4, np.float32)) for k in keys])
        grp.set_optimizer(_sgd_pickle(lr=0.1))
        # each group push fans out one RPC per server that owns keys
        servers_touched = len({grp.server_of(k) for k in keys})
        n_push = 25
        with chaos.inject("kvstore.recv", "drop", prob=0.3, seed=7) as inj:
            for _ in range(n_push):
                grp.push([(k, np.ones(4, np.float32)) for k in keys])
        assert inj.fires > 0  # the schedule actually exercised retries
        stats = grp.stats()
        assert stats["push_counts"][0] == n_push * servers_touched
        # and the weights prove it: exactly n_push SGD updates per key
        for v in grp.pull(keys):
            np.testing.assert_allclose(
                v, np.full(4, -0.1 * n_push, np.float32), rtol=1e-5)
    finally:
        for s in servers:
            s.stop()


def test_server_dead_error_is_typed_and_bounded(fast_retries, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_PS_CALL_TIMEOUT", "0.5")
    monkeypatch.setenv("MXNET_TPU_PS_DEADLINE", "1.5")
    srv = AsyncServer(secret="s").start()
    cli = AsyncClient(srv.address, rank=0, heartbeat=False, secret="s")
    cli.init([("w", np.zeros(2, np.float32))])
    srv.stop()  # severs established connections too
    import time

    t0 = time.monotonic()
    with pytest.raises(ServerDeadError) as ei:
        cli.pull(["w"])
    assert time.monotonic() - t0 < 10  # bounded, not a hang
    assert isinstance(ei.value, MXNetError)  # typed under the family root
    assert "unreachable" in str(ei.value)


def test_shard_failure_names_the_shard(fast_retries, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_PS_CALL_TIMEOUT", "0.5")
    monkeypatch.setenv("MXNET_TPU_PS_DEADLINE", "1.0")
    servers = [AsyncServer(secret="g", server_id=i).start()
               for i in range(2)]
    grp = ServerGroup([s.address for s in servers], rank=0,
                      heartbeat=False, secret="g")
    grp.init([("a", np.zeros(2, np.float32)),
              ("b", np.zeros(2, np.float32))])
    servers[1].stop()
    with pytest.raises(ShardFailedError) as ei:
        grp.stats()
    msg = str(ei.value)
    assert "shard 1" in msg and servers[1].address.rsplit(":", 1)[1] in msg
    servers[0].stop()


def test_lazy_env_tunables(monkeypatch):
    """Satellite: timeouts/caps re-read the environment per use."""
    from mxnet_tpu import kvstore_async as kva

    monkeypatch.setenv("MXNET_TPU_PS_DEAD_AFTER", "3.5")
    assert kva._dead_after_s() == 3.5
    monkeypatch.setenv("MXNET_TPU_PS_MAX_MSG_MB", "1")
    assert kva._max_msg_bytes() == 1 << 20
    srv = AsyncServer(secret="s").start()
    try:
        cli = AsyncClient(srv.address, rank=0, heartbeat=False, secret="s")
        with pytest.raises(ValueError):  # _MessageTooBig is a ValueError
            cli.init([("big", np.zeros((1 << 19,), np.float32))])  # 2 MB
        monkeypatch.setenv("MXNET_TPU_PS_MAX_MSG_MB", "64")
        cli.init([("big", np.zeros((1 << 19,), np.float32))])  # now fits
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# auto-resume training
# ---------------------------------------------------------------------------

import jax
from jax.sharding import Mesh

from mxnet_tpu.io import NDArrayIter
from mxnet_tpu.parallel import checkpoint as ckpt
from mxnet_tpu.parallel.trainer import ShardedTrainer

B, D = 8, 6


def _mlp():
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=16,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=8, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _data(n=32, seed=3):
    rs = np.random.RandomState(seed)
    return (rs.randn(n, D).astype(np.float32),
            rs.randint(0, 8, (n,)).astype(np.float32))


def _iter(X, Y):
    return NDArrayIter({"data": X}, {"softmax_label": Y}, batch_size=B)


def _trainer(**kw):
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    return ShardedTrainer(_mlp(), mesh, data_shapes={"data": (B, D)},
                          label_shapes={"softmax_label": (B,)},
                          momentum=0.9, rescale_grad=1.0 / B, **kw)


class _Kill(Exception):
    pass


def _kill_after(n):
    count = [0]

    def cb(_bep):
        count[0] += 1
        if count[0] >= n:
            raise _Kill()

    return cb


def test_kill_then_resume_matches_uninterrupted(tmp_path):
    """Acceptance: a mid-epoch kill + resume='auto' reproduces the
    uninterrupted run's parameters exactly."""
    X, Y = _data()
    full_dir, kill_dir = str(tmp_path / "full"), str(tmp_path / "kill")
    (p_full, _, _), _ = _trainer().fit(
        _iter(X, Y), num_epoch=3, seed=5, checkpoint_dir=full_dir,
        checkpoint_every=2, log_every=0)
    # killed mid-epoch-1 (4 batches/epoch; killed at global step 5)
    with pytest.raises(_Kill):
        _trainer().fit(_iter(X, Y), num_epoch=3, seed=5,
                       checkpoint_dir=kill_dir, checkpoint_every=2,
                       log_every=0, batch_end_callback=_kill_after(5))
    assert ckpt.all_steps(kill_dir)  # something was saved before the kill
    ckpt.close_all()  # the kill left an open manager on the directory
    (p_res, _, _), _ = _trainer().fit(
        _iter(X, Y), num_epoch=3, seed=5, checkpoint_dir=kill_dir,
        checkpoint_every=2, resume="auto", log_every=0)
    for n in p_full:
        np.testing.assert_allclose(np.asarray(p_full[n]),
                                   np.asarray(p_res[n]),
                                   rtol=1e-6, atol=1e-7, err_msg=n)


@pytest.mark.chaos
def test_resume_falls_back_past_corrupt_checkpoint(tmp_path):
    X, Y = _data()
    d = str(tmp_path / "ck")
    _trainer().fit(_iter(X, Y), num_epoch=2, seed=5, checkpoint_dir=d,
                   checkpoint_every=4, log_every=0)
    steps = ckpt.all_steps(d)
    assert len(steps) >= 2
    ckpt.close_all()
    # garble the NEWEST checkpoint's largest shard file
    with chaos.inject("checkpoint.write", "corrupt", seed=1):
        assert chaos.corrupt_file("checkpoint.write",
                                  os.path.join(d, str(steps[-1])))
    # resume survives by validating and falling back to the previous step
    (p, _, _), _ = _trainer().fit(_iter(X, Y), num_epoch=2, seed=5,
                                  checkpoint_dir=d, checkpoint_every=4,
                                  resume="auto", log_every=0)
    for n in p:
        assert np.isfinite(np.asarray(p[n])).all()


def test_resume_skips_step_killed_between_shard_and_meta_writes(tmp_path):
    """A kill between the shard write and the fit-meta sidecar write
    leaves a checkpoint with a manifest but no sidecar.  resume='auto'
    must treat that step as mid-save debris and fall back to the prior
    intact step — byte-for-byte the same resume as if the torn step had
    never been written."""
    import shutil

    X, Y = _data()
    d_torn = str(tmp_path / "torn")
    _trainer().fit(_iter(X, Y), num_epoch=2, seed=5, checkpoint_dir=d_torn,
                   checkpoint_every=4, log_every=0)
    steps = ckpt.all_steps(d_torn)
    assert len(steps) >= 2
    ckpt.close_all()
    d_ref = str(tmp_path / "ref")
    shutil.copytree(d_torn, d_ref)
    # torn dir: the newest step kept its shards + manifest, lost its
    # sidecar (the kill window).  ref dir: that step never happened.
    os.remove(os.path.join(d_torn, "fit-meta-%d.json" % steps[-1]))
    shutil.rmtree(os.path.join(d_ref, str(steps[-1])))
    os.remove(os.path.join(d_ref, "fit-meta-%d.json" % steps[-1]))
    os.remove(os.path.join(d_ref, "ckpt-manifest-%d.json" % steps[-1]))

    (p_torn, _, _), _ = _trainer().fit(
        _iter(X, Y), num_epoch=2, seed=5, checkpoint_dir=d_torn,
        checkpoint_every=4, resume="auto", log_every=0)
    ckpt.close_all()
    (p_ref, _, _), _ = _trainer().fit(
        _iter(X, Y), num_epoch=2, seed=5, checkpoint_dir=d_ref,
        checkpoint_every=4, resume="auto", log_every=0)
    for n in p_ref:
        np.testing.assert_array_equal(np.asarray(p_torn[n]),
                                      np.asarray(p_ref[n]))


def test_nonfinite_guard_skips_and_aborts():
    X, Y = _data()
    Xbad = X.copy()
    Xbad[8:16] = np.nan  # poison exactly batch index 1
    tr = _trainer(skip_nonfinite=True)
    (p, _, _), _ = tr.fit(_iter(Xbad, Y), num_epoch=1, seed=5, log_every=0)
    for n in p:
        assert np.isfinite(np.asarray(p[n])).all(), n
    # every batch bad -> abort after max_bad_steps CONSECUTIVE skips
    Xall = np.full_like(X, np.nan)
    with pytest.raises(MXNetError, match="consecutive non-finite"):
        _trainer(skip_nonfinite=True).fit(
            _iter(Xall, Y), num_epoch=2, seed=5, max_bad_steps=3,
            log_every=0)


def test_guard_step_matches_unguarded_on_clean_data():
    X, Y = _data()
    (p0, _, _), _ = _trainer().fit(_iter(X, Y), num_epoch=1, seed=5,
                                   log_every=0)
    (p1, _, _), _ = _trainer(skip_nonfinite=True).fit(
        _iter(X, Y), num_epoch=1, seed=5, log_every=0)
    for n in p0:
        np.testing.assert_allclose(np.asarray(p0[n]), np.asarray(p1[n]),
                                   rtol=1e-6, atol=1e-7, err_msg=n)
