"""Compute-efficiency accounting: HLO FLOPs, MFU, and the goodput ledger.

PRs 4-6 built the wall-clock side of the observability plane (what
happened, where the time went); this module is the *what did the
hardware achieve* layer — the denominator that makes the ROADMAP's
"as fast as the hardware allows" claim verifiable.

Three accounts, one falsifiability bar:

- **HLO cost accounting** (:func:`record_compile`): every jit-cache
  compile in ``ShardedTrainer`` records the compiled program's FLOPs /
  bytes-accessed / memory footprint from XLA's own
  ``lowered.compile().cost_analysis()`` into
  ``trainer_compile_flops{cache}`` et al.  The per-step model-FLOPs
  figure (``trainer_step_model_flops``) therefore comes from the
  program XLA actually runs — not a ``6N + 12LTd`` formula — with a
  graceful fallback chain: compiled cost analysis → the cheaper
  pre-compile ``lowered.cost_analysis()`` → a
  ``trainer_compile_cost_unsupported_total{cache}`` marker when the
  backend supports neither.
- **MFU + roofline** (:func:`record_step_rate`):
  ``model_flops_utilization`` = achieved model FLOPs/s ÷ device peak
  (per-device-kind table, ``MXNET_TPU_DEVICE_PEAK_FLOPS`` override),
  plus ``trainer_compile_arithmetic_intensity{cache}`` (FLOPs per byte
  accessed — the roofline x-coordinate).  Federated into
  ``cluster_mfu{member}`` / ``cluster_mfu_min`` by ``federation.py``.
- **Goodput ledger** (:func:`ledger`): accounts every second of a
  ``fit()`` call as ``goodput_productive_seconds_total`` vs
  ``badput_seconds_total{cause=data_wait|recompile|kv_retry|failover|
  checkpoint|other}``.  Productive time is summed step wall minus the
  in-step badput (attribution phases + compile/kv-retry/failover
  counter deltas); whatever the named causes do not cover lands in
  ``cause="other"`` — so the books reconcile against
  ``fit_wall_seconds_total`` within 5% *by construction*, and a tier-1
  test asserts it (the same falsifiability contract as step-time
  attribution).  ``goodput_ratio`` is the derived gauge.

:func:`capture_profile` backs the ``/profile?ms=N`` endpoint
(``exporters.start_metrics_server``): an on-demand ``jax.profiler``
device trace, falling back to the span-ring tail
(``export_chrome_trace``) when the backend profiler is unavailable.
Either way the result is Perfetto-loadable and mergeable with other
processes' dumps via ``merge_chrome_traces``.

Every record path honors the ``MXNET_TPU_METRICS=0`` constant-time
guard, and the gauge families register lazily (first record, not
import) so a process that never measures efficiency never renders
zero-valued ``goodput_ratio`` / ``model_flops_utilization`` rows.
"""

from __future__ import annotations

import os
import threading
import time as _time

from . import metrics as _metrics

__all__ = [
    "peak_flops", "record_compile", "record_variant_compile",
    "record_step_rate",
    "model_flops_per_step", "GoodputLedger", "ledger", "BADPUT_CAUSES",
    "efficiency_table", "format_efficiency", "goodput_table",
    "format_goodput", "goodput_reconciles", "capture_profile",
]

#: Every cause ``badput_seconds_total`` can carry.
BADPUT_CAUSES = ("data_wait", "recompile", "kv_retry", "failover",
                 "checkpoint", "other")

# attribution phases that are badput when they show up inside a step
# (compute/placement/kv/flush are the productive work itself)
_IN_STEP_BAD_PHASES = ("data_wait", "checkpoint")

# ----------------------------------------------------------------------
# Device peak FLOP/s

#: Peak dense (bf16) FLOP/s per chip, matched as a lowercase substring
#: of ``device.device_kind`` — first hit wins, so more specific entries
#: come first (public per-chip numbers from the vendor datasheets).
PEAK_FLOPS_TABLE = (
    ("v5 lite", 197e12), ("v5litepod", 197e12), ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6", 918e12),             # Trillium / v6e
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
    ("h100", 989e12),           # bf16 dense, SXM
    ("a100", 312e12),
)

#: Denominator when the device kind matches nothing (the CPU smoke
#: backend) — an arbitrary but *stable* 1 TFLOP/s so MFU stays a
#: comparable diagnostic across runs rather than a meaningless 0/0.
DEFAULT_PEAK_FLOPS = 1e12

_KIND_CACHE = {"v": None}


def peak_flops(device_kind=None):
    """Peak FLOP/s for one device.  ``MXNET_TPU_DEVICE_PEAK_FLOPS``
    (raw FLOP/s, e.g. ``197e12``) overrides; otherwise the
    :data:`PEAK_FLOPS_TABLE` row matching ``device_kind`` (default: the
    first visible device's kind), else :data:`DEFAULT_PEAK_FLOPS`."""
    env = os.environ.get("MXNET_TPU_DEVICE_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    if device_kind is None:
        device_kind = _KIND_CACHE["v"]
        if device_kind is None:
            try:
                import jax

                device_kind = jax.devices()[0].device_kind
            except Exception:
                device_kind = ""
            _KIND_CACHE["v"] = device_kind
    kind = str(device_kind).lower()
    for sub, flops in PEAK_FLOPS_TABLE:
        if sub in kind:
            return flops
    return DEFAULT_PEAK_FLOPS


# ----------------------------------------------------------------------
# Lazily-registered families (see module doc for why not at import)

_LAZY = {}
_LAZY_LOCK = threading.Lock()


def _cost_fams():
    with _LAZY_LOCK:
        f = _LAZY.get("cost")
        if f is None:
            f = {
                "flops": _metrics.gauge(
                    "trainer_compile_flops",
                    "FLOPs of one execution of the compiled program, from "
                    "XLA cost analysis, per jit cache", ["cache"]),
                "bytes": _metrics.gauge(
                    "trainer_compile_bytes_accessed",
                    "Bytes the compiled program reads+writes per execution "
                    "(XLA cost analysis), per jit cache", ["cache"]),
                "mem": _metrics.gauge(
                    "trainer_compile_peak_memory_bytes",
                    "Compiled-program memory footprint: argument + output "
                    "+ XLA temp allocation bytes (memory_analysis), per "
                    "jit cache", ["cache"]),
                "ai": _metrics.gauge(
                    "trainer_compile_arithmetic_intensity",
                    "FLOPs per byte accessed of the compiled program (the "
                    "roofline x-coordinate), per jit cache", ["cache"]),
                "unsupported": _metrics.counter(
                    "trainer_compile_cost_unsupported_total",
                    "Compiles whose backend supports neither compiled nor "
                    "lowered cost analysis (MFU falls back to 0/absent)",
                    ["cache"]),
                "step_flops": _metrics.gauge(
                    "trainer_step_model_flops",
                    "Model FLOPs of ONE optimizer step, derived from the "
                    "latest train-step compile's cost analysis (flops / "
                    "steps-per-dispatch)"),
            }
            _LAZY["cost"] = f
        return f


def _mfu_fams():
    with _LAZY_LOCK:
        f = _LAZY.get("mfu")
        if f is None:
            f = {
                "rate": _metrics.gauge(
                    "model_flops_per_sec",
                    "Achieved model FLOP/s over the most recent step "
                    "(trainer_step_model_flops x steps / wall)"),
                "mfu": _metrics.gauge(
                    "model_flops_utilization",
                    "Model FLOPs utilization: achieved model FLOP/s over "
                    "the device peak (peak_flops(); "
                    "MXNET_TPU_DEVICE_PEAK_FLOPS override)"),
            }
            _LAZY["mfu"] = f
        return f


def _goodput_fams():
    with _LAZY_LOCK:
        f = _LAZY.get("goodput")
        if f is None:
            f = {
                "productive": _metrics.counter(
                    "goodput_productive_seconds_total",
                    "fit() wall seconds spent on productive training work "
                    "(step wall minus in-step badput)"),
                "bad": _metrics.counter(
                    "badput_seconds_total",
                    "fit() wall seconds lost to one badput cause; "
                    "productive + all causes reconcile with "
                    "fit_wall_seconds_total within 5% (tier-1-enforced)",
                    ["cause"]),
                "wall": _metrics.counter(
                    "fit_wall_seconds_total",
                    "Total fit() wall seconds the goodput ledger "
                    "accounted"),
                "ratio": _metrics.gauge(
                    "goodput_ratio",
                    "Productive fraction of the last closed fit() ledger "
                    "(goodput_productive / fit_wall)"),
            }
            _LAZY["goodput"] = f
        return f


# ----------------------------------------------------------------------
# HLO cost accounting


def _first_cost(obj):
    """Normalize a cost_analysis() result: newer jax returns a list of
    per-program dicts, older a plain dict."""
    if isinstance(obj, (list, tuple)):
        return obj[0] if obj else None
    return obj if isinstance(obj, dict) else None


def record_compile(cache, lower, steps=1):
    """Record HLO cost analysis for one jit-cache compile.

    ``lower`` is a zero-arg callable returning a ``jax.stages.Lowered``
    for the traced call (the trainer lowers the raw jit under its mesh
    with the first call's arguments).  ``steps`` is how many optimizer
    steps one dispatch advances (``pipeline_fn(n)`` scans ``n``); pass
    ``steps=0`` for programs that are not a training step (the eval
    forward) — cost families are still recorded, but
    ``trainer_step_model_flops`` is left alone.

    Fallback chain: ``lowered.compile().cost_analysis()`` (+
    ``memory_analysis()``) → ``lowered.cost_analysis()`` (no peak
    memory) → ``trainer_compile_cost_unsupported_total{cache}``.
    Never raises; constant-time guard when metrics are disabled.
    ``MXNET_TPU_COST_ANALYSIS=0`` skips entirely, ``=lowered`` skips
    the AOT compile (cheaper, no memory footprint).
    """
    if not _metrics.metrics_enabled():
        return
    mode = os.environ.get("MXNET_TPU_COST_ANALYSIS", "compiled").lower()
    if mode in ("0", "false", "off", "no"):
        return
    fams = _cost_fams()
    try:
        lowered = lower()
    except Exception:
        fams["unsupported"].labels(cache).inc()
        return
    cost = mem = None
    if mode != "lowered":
        try:
            compiled = lowered.compile()
            cost = _first_cost(compiled.cost_analysis())
            try:
                mem = compiled.memory_analysis()
            except Exception:
                mem = None
        except Exception:
            cost = None
    if cost is None:
        try:
            cost = _first_cost(lowered.cost_analysis())
        except Exception:
            cost = None
    if not cost:
        fams["unsupported"].labels(cache).inc()
        return
    flops = float(cost.get("flops", 0.0) or 0.0)
    nbytes = float(cost.get("bytes accessed", 0.0) or 0.0)
    fams["flops"].labels(cache).set(flops)
    fams["bytes"].labels(cache).set(nbytes)
    if nbytes > 0:
        fams["ai"].labels(cache).set(flops / nbytes)
    if mem is not None:
        try:
            footprint = float(
                getattr(mem, "argument_size_in_bytes", 0)
                + getattr(mem, "output_size_in_bytes", 0)
                + getattr(mem, "temp_size_in_bytes", 0))
        except Exception:
            footprint = 0.0
        if footprint > 0:
            fams["mem"].labels(cache).set(footprint)
            # book the XLA footprint into the memory ledger (allocator-
            # side bytes, outside the live-array truth → device="xla")
            from . import memory as _memory
            _memory.tag("compile", cache, int(footprint), device="xla")
    if steps and flops > 0:
        fams["step_flops"].set(flops / float(steps))


def record_variant_compile(op_name, variant, fn, *args, **kwargs):
    """Record one fused-tier variant's compile cost under the cache key
    ``variant:<op>:<variant>``.

    The per-variant ``trainer_compile_flops{cache}`` row is how MFU
    attribution credits a kernel-level win to the variant that earned
    it (ISSUE 19) — attention/paged-decode variants gate on parity plus
    this row, never on a quoted CPU timing.  ``fn(*args, **kwargs)`` is
    jit-lowered for analysis only; nothing executes.  Never raises
    (:func:`record_compile`'s fallback chain applies).
    """
    import jax

    record_compile("variant:%s:%s" % (op_name, variant),
                   lambda: jax.jit(fn).lower(*args, **kwargs), steps=0)


def model_flops_per_step(registry=None):
    """The latest cost-analysis-derived model FLOPs per optimizer step,
    or None when no train-step compile has been accounted (backend
    unsupported, metrics off, or nothing compiled yet)."""
    reg = registry or _metrics.REGISTRY
    fam = reg.get("trainer_step_model_flops")
    if fam is None or fam._default is None:
        return None
    v = fam._default.value
    return v if v > 0 else None


def record_step_rate(steps, seconds, peak=None):
    """Update ``model_flops_per_sec`` / ``model_flops_utilization``
    from ``steps`` optimizer steps that took ``seconds`` of wall.
    No-op until a train-step compile has recorded its FLOPs (the MFU
    numerator comes from the compiled program, never a formula)."""
    if not _metrics.metrics_enabled():
        return
    if seconds <= 0.0:
        return
    mfps = model_flops_per_step()
    if not mfps:
        return
    fams = _mfu_fams()
    achieved = mfps * steps / seconds
    fams["rate"].set(achieved)
    pk = peak if peak else peak_flops()
    if pk > 0:
        fams["mfu"].set(achieved / pk)


# ----------------------------------------------------------------------
# Goodput ledger

# counter families whose in-fit deltas become badput causes
_DELTA_SOURCES = (
    ("recompile", "trainer_compile_seconds", "hist"),
    ("kv_retry", "kv_retry_seconds_total", "counter"),
    ("failover", "kv_failover_seconds_total", "counter"),
)


class GoodputLedger(object):
    """Books one ``fit()``'s wall seconds into productive vs badput.

    Construction snapshots the compile/kv-retry/failover second
    counters; :meth:`step` feeds each step's wall + attribution phases;
    :meth:`bad` books out-of-step badput (the epoch-end checkpoint);
    :meth:`close` settles: counter deltas become in-step badput
    (compiles, RPC retries and failovers all happen inside step
    windows), productive = step wall minus in-step badput (clamped at
    0), and the unaccounted remainder of ``wall_s`` lands in
    ``cause="other"`` — eval passes, iterator resets, epoch plumbing.
    Books that overcount are falsifiable: the causes can only exceed
    wall if a timer double-books, and the 5% reconciliation test
    catches exactly that."""

    __slots__ = ("_reg", "_base", "_step_wall", "_in_step", "_out")

    def __init__(self, registry=None):
        self._reg = registry or _metrics.REGISTRY
        self._base = self._snapshot()
        self._step_wall = 0.0
        self._in_step = {}
        self._out = {}

    def _snapshot(self):
        snap = {}
        for cause, fam_name, kind in _DELTA_SOURCES:
            fam = self._reg.get(fam_name)
            total = 0.0
            if fam is not None:
                try:
                    if kind == "hist":
                        with fam._lock:
                            total = sum(c.sum
                                        for c in fam._children.values())
                        if fam._default is not None:
                            total += fam._default.sum
                    else:
                        total = fam.total()
                except Exception:
                    total = 0.0
            snap[cause] = total
        return snap

    def step(self, wall_s, phases=None):
        """Book one step/flush: its wall seconds plus the attribution
        phase dict ``StepAttribution.close`` returned (data-wait and
        in-step checkpoint seconds are badput)."""
        self._step_wall += wall_s
        if phases:
            for cause in _IN_STEP_BAD_PHASES:
                v = phases.get(cause)
                if v:
                    self._in_step[cause] = self._in_step.get(cause, 0.0) + v

    def bad(self, cause, seconds):
        """Book out-of-step badput (e.g. the epoch-end checkpoint)."""
        if seconds > 0.0:
            self._out[cause] = self._out.get(cause, 0.0) + seconds

    def close(self, wall_s):
        """Settle the books over ``wall_s`` fit wall seconds; records
        the goodput/badput counters + ``goodput_ratio`` and returns the
        settled dict (None when metrics got disabled mid-run)."""
        if not _metrics.metrics_enabled():
            return None
        now = self._snapshot()
        in_step = dict(self._in_step)
        for cause, _, _ in _DELTA_SOURCES:
            d = max(now[cause] - self._base[cause], 0.0)
            if d > 0.0:
                in_step[cause] = in_step.get(cause, 0.0) + d
        productive = max(self._step_wall - sum(in_step.values()), 0.0)
        causes = dict(in_step)
        for cause, v in self._out.items():
            causes[cause] = causes.get(cause, 0.0) + v
        other = wall_s - productive - sum(causes.values())
        if other > 0.0:
            causes["other"] = other
        fams = _goodput_fams()
        fams["productive"].inc(productive)
        fams["wall"].inc(wall_s)
        for cause, v in sorted(causes.items()):
            if v > 0.0:
                fams["bad"].labels(cause).inc(v)
        ratio = productive / wall_s if wall_s > 0 else 0.0
        fams["ratio"].set(ratio)
        return {"wall": wall_s, "productive": productive,
                "badput": causes, "goodput_ratio": ratio}


class _NullLedger(object):
    """Shared no-op ledger for the metrics-disabled path: no clock
    reads, no snapshots, no allocation."""

    __slots__ = ()

    def step(self, wall_s, phases=None):
        pass

    def bad(self, cause, seconds):
        pass

    def close(self, wall_s):
        return None


_NULL_LEDGER = _NullLedger()


def ledger(registry=None):
    """A fresh :class:`GoodputLedger` — or the shared no-op singleton
    when ``MXNET_TPU_METRICS=0`` (constant-time guard)."""
    if not _metrics.metrics_enabled():
        return _NULL_LEDGER
    return GoodputLedger(registry)


# ----------------------------------------------------------------------
# Tables / reconciliation


def efficiency_table(registry=None):
    """Per-cache HLO cost rows ``(cache, flops, bytes, intensity,
    footprint_bytes)`` sorted by FLOPs, plus trailing
    ``("model_flops/step", v)`` / ``("mfu", v)`` summary pairs (None
    when unmeasured)."""
    reg = registry or _metrics.REGISTRY

    def _children(name):
        fam = reg.get(name)
        if fam is None:
            return {}
        with fam._lock:
            return {k[0]: c.value for k, c in fam._children.items()}

    flops = _children("trainer_compile_flops")
    nbytes = _children("trainer_compile_bytes_accessed")
    ai = _children("trainer_compile_arithmetic_intensity")
    mem = _children("trainer_compile_peak_memory_bytes")
    rows = [(c, v, nbytes.get(c), ai.get(c), mem.get(c))
            for c, v in flops.items()]
    rows.sort(key=lambda r: -r[1])

    def _gauge(name):
        fam = reg.get(name)
        if fam is None or fam._default is None:
            return None
        v = fam._default.value
        return v if v > 0 else None

    summary = [("model_flops/step", _gauge("trainer_step_model_flops")),
               ("model_flops/s", _gauge("model_flops_per_sec")),
               ("mfu", _gauge("model_flops_utilization"))]
    return rows, summary


def format_efficiency(registry=None):
    """:func:`efficiency_table` rendered as an aligned text table."""
    rows, summary = efficiency_table(registry)
    lines = ["%-12s %14s %14s %10s %14s"
             % ("cache", "flops", "bytes", "flops/B", "mem_bytes")]
    for cache, fl, nb, ai, mem in rows:
        lines.append("%-12s %14.4g %14s %10s %14s"
                     % (cache, fl,
                        "-" if nb is None else "%.4g" % nb,
                        "-" if ai is None else "%.3f" % ai,
                        "-" if mem is None else "%.4g" % mem))
    if not rows:
        lines.append("(no compile cost recorded)")
    for name, v in summary:
        lines.append("%-18s %s" % (name + ":",
                                   "-" if v is None else "%.6g" % v))
    return "\n".join(lines)


def goodput_table(registry=None):
    """The goodput books as rows ``(cause, seconds, share-of-wall)``:
    ``productive`` first, then each badput cause by size, then a
    trailing ``("wall", wall, 1.0)`` row."""
    reg = registry or _metrics.REGISTRY

    def _total(name):
        fam = reg.get(name)
        return fam.total() if fam is not None else 0.0

    wall = _total("fit_wall_seconds_total")
    rows = [("productive", _total("goodput_productive_seconds_total"),
             None)]
    fam = reg.get("badput_seconds_total")
    if fam is not None:
        with fam._lock:
            bad = [(k[0], c.value) for k, c in fam._children.items()
                   if c.value > 0]
        bad.sort(key=lambda r: -r[1])
        rows.extend((c, v, None) for c, v in bad)
    rows = [(c, v, (v / wall if wall > 0 else None)) for c, v, _ in rows]
    rows.append(("wall", wall, 1.0 if wall > 0 else None))
    return rows


def format_goodput(registry=None):
    """:func:`goodput_table` rendered as an aligned text table."""
    lines = ["%-12s %12s %7s" % ("account", "seconds", "share")]
    for cause, v, share in goodput_table(registry):
        lines.append("%-12s %12.4f %7s"
                     % (cause, v,
                        "-" if share is None else "%5.1f%%" % (100 * share)))
    return "\n".join(lines)


def goodput_reconciles(tol=0.05, registry=None):
    """The falsifiability gate: ``(ok, wall, accounted)`` where
    ``accounted`` = productive + every badput cause and ``ok`` means it
    matches ``fit_wall_seconds_total`` within ``tol`` (False when no
    ledger closed)."""
    reg = registry or _metrics.REGISTRY

    def _total(name):
        fam = reg.get(name)
        return fam.total() if fam is not None else 0.0

    wall = _total("fit_wall_seconds_total")
    accounted = (_total("goodput_productive_seconds_total")
                 + _total("badput_seconds_total"))
    ok = wall > 0 and abs(accounted - wall) <= tol * wall
    return ok, wall, accounted


# ----------------------------------------------------------------------
# On-demand device profiling (the /profile endpoint's engine)

_PROFILE_LOCK = threading.Lock()

#: ``/profile?ms=N`` cap — a scrape must not hold the profiler hostage.
PROFILE_MS_CAP = 10000


def capture_profile(duration_ms=500):
    """Capture a ``duration_ms`` device trace and return
    ``(trace_dict, source)`` where ``source`` is ``"jax_profiler"`` or
    ``"span_ring"``.

    Primary: ``jax.profiler`` start/stop into a temp dir, returning the
    gunzipped chrome-trace JSON (device + host tracks, Perfetto-
    loadable).  Fallback — profiler unavailable, another capture in
    flight, or no trace produced: the span ring buffer tail via
    :func:`~.exporters.export_chrome_trace`.  Both shapes carry
    ``traceEvents`` so :func:`~.exporters.merge_chrome_traces` accepts
    them unchanged."""
    import glob
    import gzip
    import json
    import shutil
    import tempfile

    ms = max(1, min(int(duration_ms), PROFILE_MS_CAP))
    trace = None
    if _PROFILE_LOCK.acquire(blocking=False):
        tmpdir = tempfile.mkdtemp(prefix="mxtpu_profile_")
        try:
            import jax

            jax.profiler.start_trace(tmpdir)
            try:
                _time.sleep(ms / 1000.0)
            finally:
                jax.profiler.stop_trace()
            dumps = sorted(glob.glob(
                os.path.join(tmpdir, "**", "*.trace.json.gz"),
                recursive=True), key=os.path.getmtime)
            if dumps:
                with gzip.open(dumps[-1], "rt", encoding="utf-8") as f:
                    candidate = json.load(f)
                if candidate.get("traceEvents"):
                    trace = candidate
        except Exception:
            trace = None
        finally:
            shutil.rmtree(tmpdir, ignore_errors=True)
            _PROFILE_LOCK.release()
    if trace is not None:
        return trace, "jax_profiler"
    from . import exporters as _exporters

    return _exporters.export_chrome_trace(), "span_ring"
