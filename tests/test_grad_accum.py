"""Gradient accumulation in ShardedTrainer (`grad_accum=k`).

The graph traces at the microbatch, the step lax.scans the k microbatches
summing gradients in fp32, and ONE optimizer update applies — the same
update math as the full batch (the reference reaches large effective
batches only by adding devices; this reaches them on fixed HBM).
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel.trainer import ShardedTrainer


def _mlp():
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=16,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=8, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _batch(b=8, d=6, seed=0):
    rs = np.random.RandomState(seed)
    return {"data": rs.randn(b, d).astype(np.float32),
            "softmax_label": rs.randint(0, 8, (b,)).astype(np.float32)}


def _run(mesh, accum, b=8, steps=3, zero_stage=0, optimizer="sgd",
         momentum=0.9):
    tr = ShardedTrainer(_mlp(), mesh, data_shapes={"data": (b, 6)},
                        label_shapes={"softmax_label": (b,)},
                        momentum=momentum, wd=1e-4,
                        rescale_grad=1.0 / b, optimizer=optimizer,
                        zero_stage=zero_stage, grad_accum=accum)
    params, moms, aux = tr.init(seed=0)
    batch = tr.place_batch(_batch(b))
    step = tr.step_fn()
    outs = None
    for i in range(steps):
        outs, params, moms, aux = step(params, moms, aux, batch,
                                       jax.random.PRNGKey(0))
    return tr, outs, params


def test_accum_matches_full_batch():
    # summed microbatch grads == full-batch grads for this graph; only
    # the fp32 summation order differs
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    _, outs1, base = _run(mesh, accum=1)
    for k in (2, 4):
        _, outsk, acc = _run(mesh, accum=k)
        for n in base:
            np.testing.assert_allclose(np.asarray(acc[n]),
                                       np.asarray(base[n]),
                                       rtol=1e-5, atol=1e-7, err_msg=n)
        # merged outputs line up row-major with the unaccumulated run
        np.testing.assert_allclose(np.asarray(outsk[0]),
                                   np.asarray(outs1[0]),
                                   rtol=1e-5, atol=1e-6)


def test_accum_with_dp_and_zero():
    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), ("data",))
    _, _, base = _run(mesh, accum=1)
    _, _, acc = _run(mesh, accum=2, zero_stage=1)
    for n in base:
        np.testing.assert_allclose(np.asarray(acc[n]), np.asarray(base[n]),
                                   rtol=1e-5, atol=1e-7, err_msg=n)


def test_accum_with_adam_counter_once_per_step():
    from mxnet_tpu.parallel.trainer import _STEP_COUNT

    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    tr = ShardedTrainer(_mlp(), mesh, data_shapes={"data": (8, 6)},
                        label_shapes={"softmax_label": (8,)},
                        optimizer="adam", grad_accum=4)
    params, moms, aux = tr.init(seed=0)
    batch = tr.place_batch(_batch())
    step = tr.step_fn()
    for i in range(3):
        _, params, moms, aux = step(params, moms, aux, batch,
                                    jax.random.PRNGKey(i))
    # one optimizer step per outer step, regardless of microbatch count
    assert int(np.asarray(moms[_STEP_COUNT])) == 3


def test_accum_bn_aux_advances_sequentially():
    # moving stats update once per MICRObatch (standard accumulation
    # semantics: the scan threads aux through sequentially)
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=8,
                                name="fc")
    net = mx.sym.BatchNorm(net, name="bn", momentum=0.5)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    tr = ShardedTrainer(net, mesh, data_shapes={"data": (8, 6)},
                        label_shapes={"softmax_label": (8,)},
                        grad_accum=2)
    params, moms, aux = tr.init(seed=0)
    batch = tr.place_batch(_batch())
    step = tr.step_fn()
    mean0 = np.asarray(aux["bn_moving_mean"]).copy()
    _, params, moms, aux = step(params, moms, aux, batch,
                                jax.random.PRNGKey(0))
    assert not np.allclose(np.asarray(aux["bn_moving_mean"]), mean0)


def test_accum_forward_takes_unsplit_batches():
    # inference is independent of grad_accum: place_batch(train=False)
    # skips the split, and any batch size — even one not divisible by
    # grad_accum — evaluates
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    tr1 = ShardedTrainer(_mlp(), mesh, data_shapes={"data": (8, 6)},
                         label_shapes={"softmax_label": (8,)})
    tr2 = ShardedTrainer(_mlp(), mesh, data_shapes={"data": (8, 6)},
                         label_shapes={"softmax_label": (8,)}, grad_accum=2)
    b = _batch()
    p1, _, a1 = tr1.init(seed=0)
    p2, _, a2 = tr2.init(seed=0)
    o1 = tr1.forward_fn()(p1, a1, tr1.place_batch(b, train=False),
                          jax.random.PRNGKey(0))
    o2 = tr2.forward_fn()(p2, a2, tr2.place_batch(b, train=False),
                          jax.random.PRNGKey(0))
    np.testing.assert_allclose(np.asarray(o2[0]), np.asarray(o1[0]),
                               rtol=1e-6, atol=1e-7)
    # odd batch (3 rows) — impossible to split by 2, fine for inference
    odd = {"data": np.ones((3, 6), np.float32),
           "softmax_label": np.zeros((3,), np.float32)}
    o3 = tr2.forward_fn()(p2, a2, tr2.place_batch(odd, train=False),
                          jax.random.PRNGKey(0))
    assert np.asarray(o3[0]).shape[0] == 3


def test_sharded_fit_loop(tmp_path):
    # ShardedTrainer.fit: the Module.fit role at mesh scale — converges on
    # separable blobs, evals, checkpoints per epoch, and resumes
    import mxnet_tpu.io as mio
    from mxnet_tpu.parallel import checkpoint as ckpt

    rs = np.random.RandomState(0)
    centers = rs.randn(4, 6) * 3.0
    labels = rs.randint(0, 4, 256)
    data = (centers[labels] + rs.randn(256, 6)).astype(np.float32)
    train = mio.NDArrayIter(data, labels.astype(np.float32), batch_size=32,
                            shuffle=True)
    val = mio.NDArrayIter(data, labels.astype(np.float32), batch_size=32)

    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=16,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    devs = jax.devices()[:2]
    mesh = Mesh(np.array(devs), ("data",))
    d = str(tmp_path / "fitck")
    from mxnet_tpu.lr_scheduler import FactorScheduler

    tr = ShardedTrainer(net, mesh, data_shapes={"data": (32, 6)},
                        label_shapes={"softmax_label": (32,)},
                        learning_rate=0.2, momentum=0.9,
                        lr_scheduler=FactorScheduler(step=16, factor=0.5),
                        rescale_grad=1.0 / 32, grad_accum=2, zero_stage=1)
    state, hist = tr.fit(train, eval_data=val, num_epoch=6,
                         checkpoint_dir=d, log_every=0)
    name, acc = hist[5]["eval"]
    assert name == "accuracy" and acc > 0.9, hist

    # resume from the saved checkpoint and keep training; begin_epoch
    # continues the checkpoint step sequence instead of colliding with it
    assert ckpt.latest_step(d) == 6
    restored = ckpt.restore_sharded(d, 6, trainer=tr)
    seen = []
    state2, hist2 = tr.fit(train, eval_data=val, num_epoch=1,
                           state=restored, begin_epoch=6,
                           checkpoint_dir=d, log_every=0,
                           batch_end_callback=lambda p: seen.append(
                               (p.epoch, p.nbatch)))
    _, acc2 = hist2[6]["eval"]
    assert acc2 > 0.9, hist2
    assert ckpt.latest_step(d) == 7
    # batch-end callbacks see the resumed epoch number and 1-based batches
    assert seen[0] == (6, 1) and seen[-1][1] == len(seen)


def test_accum_scalar_head_shape_invariant():
    # a rank-0 loss head (MakeLoss over a mean) must produce the SAME
    # output shape whether or not the step accumulates — the stacked
    # per-microbatch scalars average back to one scalar, which for a
    # mean-normalized loss over the equal row-major split equals the
    # full-batch value
    def scalar_net():
        net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=8,
                                    name="fc")
        err = net - mx.sym.Reshape(mx.sym.Variable("softmax_label"),
                                   shape=(-1, 1))
        return mx.sym.MakeLoss(mx.sym.mean(err * err))

    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    outs = {}
    for accum in (1, 2):
        tr = ShardedTrainer(scalar_net(), mesh,
                            data_shapes={"data": (8, 6)},
                            label_shapes={"softmax_label": (8,)},
                            grad_accum=accum)
        params, moms, aux = tr.init(seed=0)
        batch = tr.place_batch(_batch())
        o, params, moms, aux = tr.step_fn()(params, moms, aux, batch,
                                            jax.random.PRNGKey(0))
        outs[accum] = np.asarray(o[0])
    assert outs[1].shape == outs[2].shape == ()
    np.testing.assert_allclose(outs[2], outs[1], rtol=1e-6)


def test_accum_shape_validation():
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    with pytest.raises(MXNetError):
        ShardedTrainer(_mlp(), mesh, data_shapes={"data": (9, 6)},
                       label_shapes={"softmax_label": (9,)}, grad_accum=2)
    tr = ShardedTrainer(_mlp(), mesh, data_shapes={"data": (8, 6)},
                        label_shapes={"softmax_label": (8,)}, grad_accum=2)
    with pytest.raises(MXNetError):
        tr.place_batch({"data": np.ones((9, 6), np.float32)})
