"""Per-op numerics (parity model: reference
``tests/python/unittest/test_operator.py`` — numeric-gradient checking vs
finite differences + golden forward/backward, SURVEY.md §4)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import (
    assert_almost_equal,
    check_numeric_gradient,
    check_symbolic_forward,
    check_symbolic_backward,
)


def _rand(*shape):
    return np.random.uniform(-1, 1, shape).astype(np.float32)


# ---------------------------------------------------------------- elemwise


@pytest.mark.parametrize(
    "name,npf",
    [
        ("exp", np.exp),
        ("log", None),
        ("sqrt", None),
        ("square", lambda x: x * x),
        ("tanh", np.tanh),
        ("sigmoid", lambda x: 1.0 / (1.0 + np.exp(-x))),
        ("relu", lambda x: np.maximum(x, 0)),
        ("sin", np.sin),
        ("cos", np.cos),
        ("abs", np.abs),
    ],
)
def test_unary_forward_and_grad(name, npf):
    x = mx.sym.Variable("x")
    sym = getattr(mx.sym, name)(x)
    if name in ("log", "sqrt"):
        data = np.random.uniform(0.5, 2.0, (3, 4)).astype(np.float32)
        npf = np.log if name == "log" else np.sqrt
    else:
        data = _rand(3, 4)
    check_symbolic_forward(sym, [data], [npf(data)], rtol=1e-5)
    if name != "abs":  # |x| kink breaks finite differences near 0
        check_numeric_gradient(sym, [data], numeric_eps=1e-3, rtol=5e-2,
                               atol=1e-3)


def test_binary_ops_forward():
    a, b = _rand(4, 5), np.random.uniform(0.5, 2.0, (4, 5)).astype(np.float32)
    x = mx.sym.Variable("x")
    y = mx.sym.Variable("y")
    for sym, ref in [
        (x + y, a + b),
        (x - y, a - b),
        (x * y, a * b),
        (x / y, a / b),
        (mx.sym.maximum(x, y), np.maximum(a, b)),
        (mx.sym.minimum(x, y), np.minimum(a, b)),
    ]:
        check_symbolic_forward(sym, {"x": a, "y": b}, [ref], rtol=1e-5)


def test_binary_grad():
    a, b = _rand(4, 5), np.random.uniform(0.5, 2.0, (4, 5)).astype(np.float32)
    x = mx.sym.Variable("x")
    y = mx.sym.Variable("y")
    og = _rand(4, 5)
    check_symbolic_backward(x * y, {"x": a, "y": b}, [og],
                            {"x": og * b, "y": og * a}, rtol=1e-5)
    check_symbolic_backward(x / y, {"x": a, "y": b}, [og],
                            {"x": og / b, "y": -og * a / (b * b)}, rtol=1e-4)


def test_scalar_ops():
    a = _rand(3, 4)
    x = mx.sym.Variable("x")
    for sym, ref in [
        (x + 2.0, a + 2.0),
        (2.0 - x, 2.0 - a),
        (x * 3.0, a * 3.0),
        (6.0 / (x + 3.0), 6.0 / (a + 3.0)),
        (x ** 2.0, a ** 2.0),
    ]:
        check_symbolic_forward(sym, [a], [ref], rtol=1e-5)


# ---------------------------------------------------------------- broadcast


def test_broadcast_binary():
    a = _rand(2, 1, 4)
    b = _rand(2, 3, 1)
    x, y = mx.sym.Variable("x"), mx.sym.Variable("y")
    check_symbolic_forward(mx.sym.broadcast_add(x, y), {"x": a, "y": b},
                           [a + b])
    check_symbolic_forward(mx.sym.broadcast_mul(x, y), {"x": a, "y": b},
                           [a * b])
    check_numeric_gradient(mx.sym.broadcast_mul(x, y), {"x": a, "y": b},
                           rtol=5e-2, atol=1e-3)


def test_broadcast_to_and_axis():
    a = _rand(1, 3, 1)
    x = mx.sym.Variable("x")
    check_symbolic_forward(mx.sym.broadcast_to(x, shape=(2, 3, 4)), [a],
                           [np.broadcast_to(a, (2, 3, 4))])
    check_symbolic_forward(
        mx.sym.broadcast_axis(x, axis=0, size=5), [a],
        [np.broadcast_to(a, (5, 3, 1))])


# ---------------------------------------------------------------- reductions


@pytest.mark.parametrize("name,npf", [
    ("sum", np.sum), ("mean", np.mean), ("max", np.max),
    ("min", np.min), ("prod", np.prod),
])
def test_reductions(name, npf):
    a = np.random.uniform(0.5, 1.5, (2, 3, 4)).astype(np.float32)
    x = mx.sym.Variable("x")
    f = getattr(mx.sym, name)
    check_symbolic_forward(f(x), [a], [npf(a).reshape(())], rtol=1e-5)
    check_symbolic_forward(f(x, axis=1), [a], [npf(a, axis=1)], rtol=1e-5)
    check_symbolic_forward(f(x, axis=(0, 2), keepdims=True), [a],
                           [npf(a, axis=(0, 2), keepdims=True)], rtol=1e-5)


def test_sum_grad():
    a = _rand(3, 4)
    x = mx.sym.Variable("x")
    check_numeric_gradient(mx.sym.sum(x, axis=1), [a], rtol=5e-2, atol=1e-3)


def test_norm():
    a = _rand(3, 4)
    x = mx.sym.Variable("x")
    check_symbolic_forward(mx.sym.norm(x), [a],
                           [np.linalg.norm(a).reshape(())], rtol=1e-5)


def test_nansum():
    a = _rand(3, 4)
    a[0, 0] = np.nan
    x = mx.sym.Variable("x")
    check_symbolic_forward(mx.sym.nansum(x), [a],
                           [np.nansum(a).reshape(())], rtol=1e-5)


# ---------------------------------------------------------------- linalg


def test_dot():
    a, b = _rand(3, 4), _rand(4, 5)
    x, y = mx.sym.Variable("x"), mx.sym.Variable("y")
    check_symbolic_forward(mx.sym.dot(x, y), {"x": a, "y": b}, [a @ b],
                           rtol=1e-4)
    check_numeric_gradient(mx.sym.dot(x, y), {"x": a, "y": b}, rtol=5e-2,
                           atol=1e-3)


def test_dot_transpose():
    a, b = _rand(4, 3), _rand(5, 4)
    x, y = mx.sym.Variable("x"), mx.sym.Variable("y")
    check_symbolic_forward(
        mx.sym.dot(x, y, transpose_a=True, transpose_b=True),
        {"x": a, "y": b}, [a.T @ b.T], rtol=1e-4)


def test_batch_dot():
    a, b = _rand(6, 3, 4), _rand(6, 4, 5)
    x, y = mx.sym.Variable("x"), mx.sym.Variable("y")
    check_symbolic_forward(mx.sym.batch_dot(x, y), {"x": a, "y": b},
                           [np.einsum("bij,bjk->bik", a, b)], rtol=1e-4)


# ---------------------------------------------------------------- shape manip


def test_reshape_transpose_etc():
    a = _rand(2, 3, 4)
    x = mx.sym.Variable("x")
    check_symbolic_forward(mx.sym.reshape(x, shape=(4, 6)), [a],
                           [a.reshape(4, 6)])
    check_symbolic_forward(mx.sym.transpose(x, axes=(2, 0, 1)), [a],
                           [a.transpose(2, 0, 1)])
    check_symbolic_forward(mx.sym.swapaxes(x, dim1=0, dim2=2), [a],
                           [a.swapaxes(0, 2)])
    check_symbolic_forward(mx.sym.expand_dims(x, axis=1), [a],
                           [a[:, None]])
    check_symbolic_forward(mx.sym.flatten(x), [a], [a.reshape(2, 12)])


def test_slice_ops():
    a = _rand(4, 6)
    x = mx.sym.Variable("x")
    check_symbolic_forward(
        mx.sym.slice(x, begin=(1, 2), end=(3, 5)), [a], [a[1:3, 2:5]])
    check_symbolic_forward(
        mx.sym.slice_axis(x, axis=1, begin=1, end=4), [a], [a[:, 1:4]])
    check_numeric_gradient(
        mx.sym.slice_axis(x, axis=1, begin=1, end=4), [a], rtol=5e-2,
        atol=1e-3)


def test_repeat_tile_reverse():
    a = _rand(2, 3)
    x = mx.sym.Variable("x")
    check_symbolic_forward(mx.sym.repeat(x, repeats=2, axis=1), [a],
                           [np.repeat(a, 2, axis=1)])
    check_symbolic_forward(mx.sym.tile(x, reps=(2, 3)), [a],
                           [np.tile(a, (2, 3))])
    check_symbolic_forward(mx.sym.reverse(x, axis=1), [a], [a[:, ::-1]])


def test_concat_split_stack():
    a, b = _rand(2, 3), _rand(2, 3)
    x, y = mx.sym.Variable("x"), mx.sym.Variable("y")
    check_symbolic_forward(mx.sym.Concat(x, y, dim=1), {"x": a, "y": b},
                           [np.concatenate([a, b], axis=1)])
    out = mx.sym.SliceChannel(x, num_outputs=3, axis=1)
    ex = out.bind(mx.cpu(), {"x": mx.nd.array(a)})
    res = ex.forward()
    for i in range(3):
        assert_almost_equal(res[i].asnumpy(), a[:, i:i + 1])
    check_symbolic_forward(mx.sym.stack(x, y, axis=0), {"x": a, "y": b},
                           [np.stack([a, b])])


def test_clip_where():
    a = _rand(3, 4)
    x = mx.sym.Variable("x")
    check_symbolic_forward(mx.sym.clip(x, a_min=-0.5, a_max=0.5), [a],
                           [np.clip(a, -0.5, 0.5)])
    cond = (np.random.rand(3, 4) > 0.5).astype(np.float32)
    c, y = mx.sym.Variable("c"), mx.sym.Variable("y")
    b = _rand(3, 4)
    check_symbolic_forward(
        mx.sym.where(c, x, y), {"c": cond, "x": a, "y": b},
        [np.where(cond > 0, a, b)])


# ---------------------------------------------------------------- indexing


def test_take_one_hot_pick():
    a = _rand(5, 4)
    idx = np.array([0, 2, 4, 1], np.float32)
    x, i = mx.sym.Variable("x"), mx.sym.Variable("i")
    check_symbolic_forward(mx.sym.take(x, i), {"x": a, "i": idx},
                           [a[idx.astype(int)]])
    check_symbolic_forward(
        mx.sym.one_hot(i, depth=5), {"i": idx},
        [np.eye(5, dtype=np.float32)[idx.astype(int)]])
    pidx = np.array([1, 3, 0, 2, 1], np.float32)
    check_symbolic_forward(
        mx.sym.pick(x, i, axis=1), {"x": a, "i": pidx},
        [a[np.arange(5), pidx.astype(int)]])


def test_embedding_forward_grad():
    W = _rand(10, 4)
    idx = np.array([1, 5, 1, 9], np.float32)
    d = mx.sym.Variable("data")
    w = mx.sym.Variable("weight")
    sym = mx.sym.Embedding(data=d, weight=w, input_dim=10, output_dim=4)
    check_symbolic_forward(sym, {"data": idx, "weight": W},
                           [W[idx.astype(int)]])
    # gradient accumulates over duplicate indices
    gw = mx.nd.zeros((10, 4))
    ex = sym.bind(mx.cpu(), {"data": mx.nd.array(idx), "weight": mx.nd.array(W)},
                  args_grad={"weight": gw}, grad_req={"weight": "write",
                                                      "data": "null"})
    ex.forward(is_train=True)
    og = np.ones((4, 4), np.float32)
    ex.backward(mx.nd.array(og))
    expect = np.zeros((10, 4), np.float32)
    for j, k in enumerate(idx.astype(int)):
        expect[k] += og[j]
    assert_almost_equal(gw.asnumpy(), expect, rtol=1e-5)


# ---------------------------------------------------------------- ordering


def test_sort_argsort_topk():
    a = np.random.uniform(-1, 1, (4, 6)).astype(np.float32)
    x = mx.sym.Variable("x")
    check_symbolic_forward(mx.sym.sort(x, axis=1), [a], [np.sort(a, axis=1)])
    check_symbolic_forward(mx.sym.argsort(x, axis=1), [a],
                           [np.argsort(a, kind="stable", axis=1).astype(np.float32)])
    check_symbolic_forward(mx.sym.argmax(x, axis=1), [a],
                           [np.argmax(a, axis=1).astype(np.float32)])
    check_symbolic_forward(mx.sym.argmin(x, axis=1), [a],
                           [np.argmin(a, axis=1).astype(np.float32)])
    # topk returns indices of the k largest by default
    k = 3
    top = mx.sym.topk(x, k=k, axis=1)
    ex = top.bind(mx.cpu(), {"x": mx.nd.array(a)})
    got = ex.forward()[0].asnumpy().astype(int)
    ref = np.argsort(-a, axis=1)[:, :k]
    gathered = np.take_along_axis(a, got, axis=1)
    expect = np.take_along_axis(a, ref, axis=1)
    assert_almost_equal(gathered, expect, rtol=1e-6)


# ---------------------------------------------------------------- NN layers


def test_fully_connected():
    a, w, b = _rand(4, 8), _rand(3, 8), _rand(3)
    d = mx.sym.Variable("data")
    sym = mx.sym.FullyConnected(data=d, num_hidden=3, name="fc")
    check_symbolic_forward(
        sym, {"data": a, "fc_weight": w, "fc_bias": b},
        [a @ w.T + b], rtol=1e-4)
    check_numeric_gradient(sym, {"data": a, "fc_weight": w, "fc_bias": b},
                           rtol=5e-2, atol=1e-3)


def test_convolution_vs_numpy():
    # golden check vs direct convolution
    a = _rand(2, 3, 5, 5)
    w = _rand(4, 3, 3, 3)
    b = _rand(4)
    d = mx.sym.Variable("data")
    sym = mx.sym.Convolution(data=d, num_filter=4, kernel=(3, 3), name="c")
    ref = np.zeros((2, 4, 3, 3), np.float32)
    for n in range(2):
        for f in range(4):
            for i in range(3):
                for j in range(3):
                    ref[n, f, i, j] = np.sum(
                        a[n, :, i:i + 3, j:j + 3] * w[f]) + b[f]
    check_symbolic_forward(sym, {"data": a, "c_weight": w, "c_bias": b},
                           [ref], rtol=1e-3, atol=1e-4)


def test_convolution_grad():
    a = _rand(1, 2, 5, 5)
    w = _rand(3, 2, 3, 3)
    b = _rand(3)
    d = mx.sym.Variable("data")
    sym = mx.sym.Convolution(data=d, num_filter=3, kernel=(3, 3), stride=(2, 2),
                             pad=(1, 1), name="c")
    check_numeric_gradient(sym, {"data": a, "c_weight": w, "c_bias": b},
                           numeric_eps=1e-2, rtol=1e-1, atol=1e-2)


def test_pooling():
    a = _rand(1, 2, 4, 4)
    d = mx.sym.Variable("data")
    mxp = mx.sym.Pooling(data=d, kernel=(2, 2), stride=(2, 2), pool_type="max")
    ref = a.reshape(1, 2, 2, 2, 2, 2).max(axis=(3, 5))
    check_symbolic_forward(mxp, [a], [ref])
    avg = mx.sym.Pooling(data=d, kernel=(2, 2), stride=(2, 2), pool_type="avg")
    refa = a.reshape(1, 2, 2, 2, 2, 2).mean(axis=(3, 5))
    check_symbolic_forward(avg, [a], [refa], rtol=1e-5)
    check_numeric_gradient(avg, [a], rtol=5e-2, atol=1e-3)


def test_batchnorm_inference_stats():
    np.random.seed(0)
    a = np.random.normal(3.0, 2.0, (16, 4, 5, 5)).astype(np.float32)
    d = mx.sym.Variable("data")
    sym = mx.sym.BatchNorm(data=d, fix_gamma=False, name="bn")
    ex = sym.simple_bind(mx.cpu(), data=a.shape)
    ex.arg_dict["data"][:] = a
    ex.arg_dict["bn_gamma"][:] = 1.0
    ex.arg_dict["bn_beta"][:] = 0.0
    ex.forward(is_train=True)
    out = ex.outputs[0].asnumpy()
    # normalized output: per-channel mean ~0, var ~1
    assert np.allclose(out.mean(axis=(0, 2, 3)), 0.0, atol=1e-3)
    assert np.allclose(out.var(axis=(0, 2, 3)), 1.0, atol=1e-2)


def test_batchnorm_singlepass_offset_stats():
    """BN computes var as E[x^2]-E[x]^2 in one fused pass (perf: halves
    BN-stat HBM reads).  Pin the numerics with a large mean:var ratio —
    fp32 accumulation must keep cancellation error benign."""
    np.random.seed(1)
    # mean ~100, var ~1: ratio 1e4 is far beyond what conv outputs see
    a = (100.0 + np.random.normal(0.0, 1.0, (32, 4, 8, 8))).astype(np.float32)
    d = mx.sym.Variable("data")
    sym = mx.sym.BatchNorm(data=d, fix_gamma=False, momentum=0.0, name="bn")
    ex = sym.simple_bind(mx.cpu(), data=a.shape)
    ex.arg_dict["data"][:] = a
    ex.arg_dict["bn_gamma"][:] = 1.0
    ex.arg_dict["bn_beta"][:] = 0.0
    ex.forward(is_train=True)
    out = ex.outputs[0].asnumpy()
    assert np.allclose(out.mean(axis=(0, 2, 3)), 0.0, atol=1e-3)
    assert np.allclose(out.var(axis=(0, 2, 3)), 1.0, atol=5e-2)
    # the updated moving var (momentum=0 -> pure batch var) must match the
    # two-pass fp64 reference to fp32-cancellation tolerance: at ratio 1e4
    # the E[x^2]-E[x]^2 form loses ~mean^2*eps_f32*sqrt(log n) ~ 1e-2 of
    # variance — the same envelope as cuDNN's single-pass BN
    ref_var = a.astype(np.float64).transpose(1, 0, 2, 3).reshape(4, -1).var(axis=1)
    got_var = ex.aux_dict["bn_moving_var"].asnumpy()
    assert np.allclose(got_var, ref_var, rtol=5e-2), (got_var, ref_var)
    # round 3: the single pass is SHIFTED by the running mean.  After the
    # first forward (momentum=0) the running mean IS the batch mean, so
    # the second pass reduces E[(x-mean)^2] directly — cancellation gone,
    # variance fp32-tight even at mean:var ratio 1e4 (advisor r2 finding)
    ex.forward(is_train=True)
    ex.outputs[0].asnumpy()  # train-mode forward is deferred; materialize
    got_var2 = ex.aux_dict["bn_moving_var"].asnumpy()
    assert np.allclose(got_var2, ref_var, rtol=1e-3), (got_var2, ref_var)


def test_activation_types():
    a = _rand(3, 4)
    d = mx.sym.Variable("data")
    for act, ref in [
        ("relu", np.maximum(a, 0)),
        ("sigmoid", 1 / (1 + np.exp(-a))),
        ("tanh", np.tanh(a)),
        ("softrelu", np.log1p(np.exp(a))),
    ]:
        check_symbolic_forward(mx.sym.Activation(data=d, act_type=act), [a],
                               [ref], rtol=1e-5)


def test_leaky_relu():
    a = _rand(3, 4)
    d = mx.sym.Variable("data")
    check_symbolic_forward(
        mx.sym.LeakyReLU(data=d, act_type="leaky", slope=0.1), [a],
        [np.where(a > 0, a, 0.1 * a)], rtol=1e-5)


def test_softmax_ops():
    a = _rand(4, 5)
    x = mx.sym.Variable("x")
    e = np.exp(a - a.max(axis=-1, keepdims=True))
    sm = e / e.sum(axis=-1, keepdims=True)
    check_symbolic_forward(mx.sym.softmax(x), [a], [sm], rtol=1e-5)
    check_symbolic_forward(mx.sym.log_softmax(x), [a], [np.log(sm)],
                           rtol=1e-5)
    check_numeric_gradient(mx.sym.softmax(x), [a], rtol=5e-2, atol=1e-3)


def test_softmax_output_ignores_label_grad():
    a = _rand(4, 5)
    lbl = np.array([0, 1, 2, 3], np.float32)
    d = mx.sym.Variable("data")
    l = mx.sym.Variable("label")
    sym = mx.sym.SoftmaxOutput(data=d, label=l)
    ga = mx.nd.zeros((4, 5))
    ex = sym.bind(mx.cpu(), {"data": mx.nd.array(a), "label": mx.nd.array(lbl)},
                  args_grad={"data": ga}, grad_req={"data": "write",
                                                    "label": "null"})
    ex.forward(is_train=True)
    ex.backward()
    e = np.exp(a - a.max(axis=-1, keepdims=True))
    sm = e / e.sum(axis=-1, keepdims=True)
    expect = sm.copy()
    expect[np.arange(4), lbl.astype(int)] -= 1.0
    assert_almost_equal(ga.asnumpy(), expect, rtol=1e-4, atol=1e-5)


def test_dropout_train_vs_test():
    a = np.ones((100, 100), np.float32)
    d = mx.sym.Variable("data")
    sym = mx.sym.Dropout(data=d, p=0.5)
    ex = sym.bind(mx.cpu(), {"data": mx.nd.array(a)})
    ex.forward(is_train=True)
    out_t = ex.outputs[0].asnumpy()
    frac = (out_t == 0).mean()
    assert 0.4 < frac < 0.6
    # kept units are scaled by 1/(1-p)
    assert np.allclose(out_t[out_t != 0], 2.0)
    out_i = ex.forward(is_train=False)[0].asnumpy()
    assert_almost_equal(out_i, a)


def test_block_grad():
    a = _rand(3, 4)
    x = mx.sym.Variable("x")
    sym = mx.sym.sum(mx.sym.BlockGrad(x * x) + x)
    g = mx.nd.zeros((3, 4))
    ex = sym.bind(mx.cpu(), {"x": mx.nd.array(a)}, args_grad={"x": g})
    ex.forward(is_train=True)
    ex.backward()
    assert_almost_equal(g.asnumpy(), np.ones_like(a))


def test_cast():
    a = _rand(3, 4)
    x = mx.sym.Variable("x")
    ex = mx.sym.Cast(x, dtype="float16").bind(mx.cpu(), {"x": mx.nd.array(a)})
    out = ex.forward()[0]
    assert out.dtype == np.float16


def test_sequence_mask_last_reverse():
    # sequence ops use (seq, batch, ...) layout
    a = _rand(5, 3, 2)
    length = np.array([2, 5, 3], np.float32)
    d = mx.sym.Variable("data")
    sl = mx.sym.Variable("len")
    masked = mx.sym.SequenceMask(data=d, sequence_length=sl,
                                 use_sequence_length=True, value=0.0)
    ref = a.copy()
    for b, L in enumerate(length.astype(int)):
        ref[L:, b] = 0.0
    check_symbolic_forward(masked, {"data": a, "len": length}, [ref])

    last = mx.sym.SequenceLast(data=d, sequence_length=sl,
                               use_sequence_length=True)
    refl = np.stack([a[int(L) - 1, b] for b, L in enumerate(length)])
    check_symbolic_forward(last, {"data": a, "len": length}, [refl])

    rev = mx.sym.SequenceReverse(data=d, sequence_length=sl,
                                 use_sequence_length=True)
    refr = a.copy()
    for b, L in enumerate(length.astype(int)):
        refr[:L, b] = a[:L, b][::-1]
    check_symbolic_forward(rev, {"data": a, "len": length}, [refr])


def test_l2_normalization():
    a = _rand(3, 4)
    d = mx.sym.Variable("data")
    sym = mx.sym.L2Normalization(data=d)
    ref = a / np.sqrt((a * a).sum(axis=1, keepdims=True) + 1e-10)
    check_symbolic_forward(sym, [a], [ref], rtol=1e-4)


def test_instance_norm():
    a = _rand(2, 3, 4, 4)
    d = mx.sym.Variable("data")
    g = mx.sym.Variable("gamma")
    b = mx.sym.Variable("beta")
    sym = mx.sym.InstanceNorm(data=d, gamma=g, beta=b)
    mean = a.mean(axis=(2, 3), keepdims=True)
    var = a.var(axis=(2, 3), keepdims=True)
    ref = (a - mean) / np.sqrt(var + 1e-3)
    check_symbolic_forward(
        sym, {"data": a, "gamma": np.ones(3, np.float32),
              "beta": np.zeros(3, np.float32)}, [ref], rtol=1e-3, atol=1e-4)


def test_correlation_brute_force():
    rng = np.random.RandomState(0)
    B, C, H, W = 1, 2, 5, 5
    d1 = rng.randn(B, C, H, W).astype(np.float32)
    d2 = rng.randn(B, C, H, W).astype(np.float32)
    md, pad = 1, 1
    out = mx.nd.Correlation(mx.nd.array(d1), mx.nd.array(d2),
                            kernel_size=1, max_displacement=md, stride1=1,
                            stride2=1, pad_size=pad,
                            is_multiply=True).asnumpy()
    p1 = np.pad(d1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = np.pad(d2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    oh = H + 2 * pad - 2 * md
    want = np.zeros((B, 9, oh, oh), np.float32)
    idx = 0
    for di in (-1, 0, 1):
        for dj in (-1, 0, 1):
            for i in range(oh):
                for j in range(oh):
                    y, x = i + md, j + md
                    want[0, idx, i, j] = (
                        p1[0, :, y, x] * p2[0, :, y + di, x + dj]).sum() / C
            idx += 1
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_identity_attach_kl_sparse_reg():
    rng = np.random.RandomState(0)
    xv = rng.rand(4, 3).astype(np.float32)
    sym = mx.sym.IdentityAttachKLSparseReg(mx.sym.Variable("data"),
                                           penalty=0.01, momentum=0.9,
                                           sparseness_target=0.1)
    aux_name = sym.list_auxiliary_states()[0]
    ex = sym.bind(mx.cpu(), {"data": mx.nd.array(xv)},
                  args_grad={"data": mx.nd.zeros((4, 3))},
                  aux_states={aux_name: mx.nd.ones((3,)) * 0.5})
    ex.forward(is_train=True)
    ex.backward(mx.nd.zeros((4, 3)))
    # forward updates the moving average first; backward uses the new one
    # (reference identity_attach_KL_sparse_reg-inl.h order)
    avg_new = 0.9 * 0.5 + 0.1 * xv.mean(axis=0)
    # no batch division — reference adds the raw penalty per element
    want = 0.01 * (-0.1 / avg_new + 0.9 / (1 - avg_new))
    np.testing.assert_allclose(ex.grad_dict["data"].asnumpy(),
                               np.broadcast_to(want, (4, 3)), rtol=1e-4)
    np.testing.assert_allclose(ex.aux_dict[aux_name].asnumpy(), avg_new,
                               rtol=1e-5)
    # forward output is the identity
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), xv, rtol=1e-6)


def test_correlation_numeric_gradient():
    rng = np.random.RandomState(0)
    d1 = rng.rand(1, 2, 4, 4).astype(np.float32)
    d2 = rng.rand(1, 2, 4, 4).astype(np.float32)
    sym = mx.sym.Correlation(mx.sym.Variable("data1"),
                             mx.sym.Variable("data2"),
                             kernel_size=1, max_displacement=1, stride1=1,
                             stride2=1, pad_size=1)
    mx.test_utils.check_numeric_gradient(
        sym, {"data1": d1, "data2": d2}, numeric_eps=1e-3, rtol=1e-2,
        atol=1e-3)


def test_smooth_l1_numeric_gradient():
    rng = np.random.RandomState(1)
    # stay away from the |x|=1/sigma^2 kink where the numeric grad is bogus
    x = rng.uniform(1.2, 2.5, (3, 4)).astype(np.float32) * \
        np.sign(rng.randn(3, 4)).astype(np.float32)
    sym = mx.sym.smooth_l1(mx.sym.Variable("data"), scalar=1.0)
    mx.test_utils.check_numeric_gradient(
        sym, {"data": x}, numeric_eps=1e-3, rtol=1e-2, atol=1e-3)


def test_slice_assign():
    a = _rand(4, 5)
    b = _rand(2, 3)
    got = mx.nd._slice_assign(mx.nd.array(a), mx.nd.array(b),
                              begin=(1, 1), end=(3, 4)).asnumpy()
    ref = a.copy()
    ref[1:3, 1:4] = b
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_slice_assign_scalar():
    from mxnet_tpu.ops import registry
    import jax.numpy as jnp
    a = _rand(4, 5)
    op = registry.get_op("_crop_assign_scalar")  # via alias
    got = np.asarray(op.fn({"begin": (0, 2), "end": (2, 5), "scalar": 7.0},
                           jnp.asarray(a)))
    ref = a.copy()
    ref[0:2, 2:5] = 7.0
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_gen_negative_binomial_moments():
    # mean of GenNB(mu, alpha) is mu; var is mu + alpha*mu^2
    s = mx.nd.sample_gennegbinomial(
        mx.nd.array(np.full(2, 5.0, np.float32)),
        mx.nd.array(np.full(2, 0.1, np.float32)),
        shape=(4000,)).asnumpy()
    assert s.shape == (2, 4000)
    assert np.allclose(s.mean(axis=1), 5.0, atol=0.5), s.mean(axis=1)
    assert np.allclose(s.var(axis=1), 5.0 + 0.1 * 25.0, atol=2.0)


def test_slice_assign_validation_and_negatives():
    from mxnet_tpu.ops import registry
    import jax.numpy as jnp
    a = _rand(4, 5)
    b = _rand(2, 2)
    op = registry.get_op("_slice_assign")
    # negative indices normalize like the sibling slice op
    got = np.asarray(op.fn({"begin": (1, -4), "end": (3, -2)},
                           jnp.asarray(a), jnp.asarray(b)))
    ref = a.copy()
    ref[1:3, 1:3] = b
    np.testing.assert_allclose(got, ref, rtol=1e-6)
    # shape mismatch must raise, not silently write a shifted block
    with pytest.raises(ValueError):
        op.fn({"begin": (1, 1), "end": (2, 2)},
              jnp.asarray(a), jnp.asarray(b))
    with pytest.raises(ValueError):
        op.fn({"begin": (3, 0), "end": (6, 2)},
              jnp.asarray(a), jnp.asarray(b))


def test_gen_negative_binomial_alpha_zero():
    # alpha == 0 degenerates to Poisson(mu) (reference sampler behavior)
    s = mx.nd.random_generalized_negative_binomial(
        mu=4.0, alpha=0.0, shape=(8000,)).asnumpy()
    assert np.isfinite(s).all()
    assert abs(s.mean() - 4.0) < 0.3
    assert abs(s.var() - 4.0) < 0.8  # Poisson: var == mean
    s2 = mx.nd.sample_gennegbinomial(
        mx.nd.array(np.array([4.0, 4.0], np.float32)),
        mx.nd.array(np.array([0.0, 0.5], np.float32)),
        shape=(6000,)).asnumpy()
    assert np.isfinite(s2).all()
    assert abs(s2[0].var() - 4.0) < 1.0          # Poisson lane
    assert s2[1].var() > 8.0                     # overdispersed lane


def test_beyond_reference_unary_and_mod():
    """Numeric coverage for the beyond-reference convenience ops."""
    a = _rand(3, 4)
    b = np.random.uniform(0.5, 2.0, (3, 4)).astype(np.float32)
    x, y = mx.sym.Variable("x"), mx.sym.Variable("y")
    check_symbolic_forward(mx.sym.softsign(x), [a], [a / (1 + np.abs(a))],
                           rtol=1e-5)
    check_numeric_gradient(mx.sym.softsign(x), [a], rtol=5e-2, atol=1e-3)
    check_symbolic_forward(mx.sym.reciprocal(y), [b], [1.0 / b], rtol=1e-5)
    check_symbolic_forward(mx.sym.logical_not(x), [a],
                           [(a == 0).astype(np.float32)])
    check_symbolic_forward(mx.sym.broadcast_mod(x, y), {"x": np.abs(a) + 2,
                                                        "y": b},
                           [np.mod(np.abs(a) + 2, b)], rtol=1e-5)
    # stack: symbol n-ary
    s = mx.sym.stack(x, y, axis=1)
    check_symbolic_forward(s, {"x": a, "y": b}, [np.stack([a, b], axis=1)])


def test_fused_lm_head_matches_dense():
    """_contrib_fused_lm_head (beyond-parity long-context head): per-token
    CE of x @ W.T computed in chunks must match the dense
    logits-materializing path exactly — forward, dx and dW — including
    the padding arm (T not divisible by chunk) and ignored (<0) labels."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.registry import OP_REGISTRY

    op = OP_REGISTRY["_contrib_fused_lm_head"]
    rng = np.random.RandomState(3)
    T, d, V = 37, 16, 50  # 37 % 8 != 0 -> padding path
    x = jnp.asarray(rng.randn(T, d).astype(np.float32))
    w = jnp.asarray(rng.randn(V, d).astype(np.float32)) * 0.3
    lab = jnp.asarray(rng.randint(0, V, (T,)).astype(np.float32))
    lab = lab.at[5].set(-1.0)
    attrs = op.parse_attrs({"chunk": 8})

    def dense(x_, w_, l_):
        logits = x_ @ w_.T
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        idx = jnp.clip(l_.astype(jnp.int32), 0, V - 1)[:, None]
        ll = jnp.take_along_axis(logits, idx, axis=-1)[:, 0]
        return jnp.where(l_ >= 0, lse - ll, 0.0)

    loss = op.fn(attrs, x, w, lab)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(dense(x, w, lab)),
                               rtol=1e-6, atol=1e-6)
    assert float(loss[5]) == 0.0  # ignored row
    gf = jax.grad(lambda a, b: jnp.sum(op.fn(attrs, a, b, lab)),
                  argnums=(0, 1))(x, w)
    gd = jax.grad(lambda a, b: jnp.sum(dense(a, b, lab)),
                  argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gf[0]), np.asarray(gd[0]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gf[1]), np.asarray(gd[1]),
                               rtol=1e-5, atol=1e-5)
    # ignored row contributes no dx
    assert float(np.abs(np.asarray(gf[0])[5]).max()) == 0.0


def test_fused_lm_head_symbol_trains():
    """The fused head as a graph node: bind, forward (per-token losses),
    backward — and three SGD steps reduce the mean loss."""
    rng = np.random.RandomState(4)
    T, d, V = 48, 8, 13
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("pred_weight", shape=(V, d))
    lab = mx.sym.Variable("softmax_label")
    out = mx.sym._contrib_fused_lm_head(data, w, lab, chunk=16,
                                        name="softmax")
    xs = rng.randn(T, d).astype(np.float32)
    ys = rng.randint(0, V, (T,)).astype(np.float32)
    ex = out.simple_bind(mx.cpu(), data=(T, d), softmax_label=(T,),
                         grad_req="write")
    ex.arg_dict["data"][:] = xs
    ex.arg_dict["softmax_label"][:] = ys
    ex.arg_dict["pred_weight"][:] = rng.randn(V, d).astype(np.float32) * 0.2
    first = None
    for _ in range(3):
        ex.forward(is_train=True)
        loss = ex.outputs[0].asnumpy()
        if first is None:
            first = loss.mean()
        ex.backward()
        ex.arg_dict["pred_weight"][:] = (
            ex.arg_dict["pred_weight"].asnumpy()
            - 0.5 * ex.grad_dict["pred_weight"].asnumpy())
    assert loss.shape == (T,)
    assert loss.mean() < first, (loss.mean(), first)


def test_conv1x1_backward_modes_parity(monkeypatch):
    """The MXTPU_CONV1X1 experiment surface (docs/PERF.md round-5
    measured-negative section): every backward mode must produce the
    default XLA conv's gradients. Forward is byte-identical (same XLA
    conv in all modes); dgrad exactly, wgrad to accumulation order."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.registry import get_op

    op = get_op("Convolution")
    attrs = op.parse_attrs({"kernel": (1, 1), "num_filter": 48,
                            "no_bias": True, "layout": "NHWC"})
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(2, 8, 8, 32), jnp.float32)
    w = jnp.asarray(rs.randn(48, 1, 1, 32) * 0.1, jnp.float32)

    def f(x, w):
        return op.apply(attrs, [x, w])[0][0]

    monkeypatch.setenv("MXTPU_CONV1X1", "")
    y0, vjp0 = jax.vjp(f, x, w)
    dy = jnp.asarray(rs.randn(*y0.shape), jnp.float32)
    dx0, dw0 = vjp0(dy)
    for mode in ("dot", "pallas"):
        monkeypatch.setenv("MXTPU_CONV1X1", mode)
        y1, vjp1 = jax.vjp(f, x, w)
        dx1, dw1 = vjp1(dy)
        np.testing.assert_array_equal(np.asarray(y1), np.asarray(y0),
                                      err_msg=mode)
        np.testing.assert_allclose(np.asarray(dx1), np.asarray(dx0),
                                   rtol=1e-6, atol=1e-6, err_msg=mode)
        np.testing.assert_allclose(np.asarray(dw1), np.asarray(dw0),
                                   rtol=1e-5, atol=1e-5, err_msg=mode)
    # ineligible shapes (stride 2) must fall back to the default conv
    monkeypatch.setenv("MXTPU_CONV1X1", "pallas")
    attrs2 = op.parse_attrs({"kernel": (1, 1), "num_filter": 48,
                             "stride": (2, 2), "no_bias": True,
                             "layout": "NHWC"})
    out = op.apply(attrs2, [x, w])[0][0]
    assert out.shape == (2, 4, 4, 48)
