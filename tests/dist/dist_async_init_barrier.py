"""Cross-server atomic init barrier (``launch.py -n 3 -s 2``).

Every rank attempts ``kv.init`` with a DIFFERENT value (rank+1), and
rank 0 delays its init — under per-shard first-writer-wins this mixes
winners across shards (a striped array could even end up torn, chunk 0
from one rank and chunk 1 from another).  The barrier contract
(parity: ``kvstore_dist.h`` Init = rank-0 ``Push_`` + ``Barrier()``)
says: only rank 0 writes, everyone else blocks until that write is
visible on every shard it touches.  Asserts every pulled value —
sharded small keys and the striped big key — is EXACTLY rank 0's.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import mxnet_tpu as mx
from mxnet_tpu.parallel import init_process_group


def main():
    assert os.environ.get("MXNET_TPU_ASYNC_PS_ADDRS"), \
        "launcher must provide server addresses (-s N)"
    init_process_group()
    kv = mx.kv.create("dist_async")
    rank = kv.rank
    group = kv._async
    assert group.num_servers == 2, group.num_servers
    group._bound = 64  # force 'big' to stripe across both servers

    shape_small, shape_big = (3, 4), (16, 16)
    if rank == 0:
        # rank 0 inits LAST: the others must genuinely block, not race
        time.sleep(1.5)
    mine = float(rank + 1)
    kv.init("alpha", mx.nd.ones(shape_small) * mine)
    kv.init("beta", mx.nd.ones(shape_small) * mine)
    kv.init("big", mx.nd.ones(shape_big) * mine)

    # init returned -> rank 0's values must be visible, whole and
    # untorn, to every rank (for a striped array: every chunk)
    for key, shape in (("alpha", shape_small), ("beta", shape_small),
                       ("big", shape_big)):
        w = mx.nd.zeros(shape)
        kv.pull(key, out=w)
        got = w.asnumpy()
        assert np.all(got == 1.0), (key, rank, np.unique(got))

    kv.barrier()
    # one write() syscall: ranks print in lockstep after the barrier and
    # print()'s separate text/newline writes interleave under -u
    sys.stdout.write("worker %d: dist_async init barrier OK\n" % rank)
    sys.stdout.flush()


if __name__ == "__main__":
    main()
